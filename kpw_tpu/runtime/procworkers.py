"""Process-parallel workers: zero-copy shared-memory batch handoff.

PR 10 moved page assembly behind the nogil boundary, but the e2e stall
breakdown still showed shred + queue-put convoyed inside ONE interpreter:
GIL *round trips* (each handoff between the fetcher, the worker loop and
the pipeline threads re-acquires the lock), not held time, are the convoy
killer, and a 2-thread worker sweep cannot beat 1x while every worker
shares a GIL.  This module escapes the single-interpreter ceiling by
running each worker as a **spawned subprocess**:

* **Handoff** — broker pages already live in contiguous payload+offset
  buffers (:class:`~kpw_tpu.ingest.broker.RecordBatch`, PR 6), which is
  exactly the representation that crosses a process boundary zero-copy.
  The parent stages each poll batch into a slot of a
  ``multiprocessing.shared_memory`` ring (:class:`ShmBatchRing` — one
  memcpy, the same single copy ``fetch_batch`` pays out of the broker log
  in thread mode) and sends the child only a tiny ``(seq, slot)``
  descriptor; the child maps the same ring and feeds the slot's
  payload+offsets views **in place** to the C++ wire shredder — no
  pickling, no per-record objects, no second copy.
* **Ownership split** — each child runs the full shred → encode →
  assemble → publish leg against its own encoder (its own interpreter,
  its own ``_kpw_assemble``) and its own tmp namespace; the parent keeps
  the ``PagedOffsetTracker`` + ack protocol.  Offsets commit only when
  the child acknowledges the published file, so at-least-once is
  unchanged: a child SIGKILLed mid-file never acked, and the parent
  redelivers its held runs to a restarted slot — exactly the thread-mode
  supervisor contract, now with a kill that actually reclaims the slot.
* **Spawn only** — the start method is pinned to ``spawn``
  (:data:`_MP_CTX`): fork with live jax/XLA threads deadlocks (recorded
  gotcha; the ``spawn-safety`` lint pass mechanizes the rule).

Parent-side pieces: :class:`ProcessWorkerPool` (dispatcher + collector
threads, ring bookkeeping), :class:`_ProcWorkerSlot` (the ``_Worker``
duck type the existing supervisor/watchdog/stats machinery operates on),
:class:`_ProcHeartbeat` (watchdog adapter over the child's shared-memory
heartbeat cells).  Child-side: :func:`child_main` (the spawn entry) and
:class:`_ChildWorker` (the in-process worker loop).
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import queue as pyqueue
import struct
import threading
import time

import numpy as np

from ..ingest.broker import RecordBatch, StaleGenerationError
from ..utils import schedcheck, tracing
from ..utils.tracing import stage
from .retry import RetryInterrupted
from .telemetry import TM_FIELDS

logger = logging.getLogger(__name__)

# spawn ONLY: this package starts jax/XLA threads in the parent, and
# fork() with live threads deadlocks in the child (recorded gotcha; the
# spawn-safety lint pass enforces this module-wide)
_MP_CTX = multiprocessing.get_context("spawn")

# -- shared-memory ring geometry --------------------------------------------
# [ heartbeat cells: _HB_MAX * _HB_CELL bytes ]
# [ telemetry cells: _HB_MAX * _TM_CELL bytes ][ slot 0 ][ slot 1 ] ...
# slot = [ header _SLOT_HEADER bytes ][ offsets (count+1) int64 ][ payload ]
_HB_MAX = 64          # max worker processes one ring serves
_HB_CELL = 32         # label_code i64, pending i64, started_at f64, beat f64
_TM_SLOTS = 16        # int64 counter slots per worker telemetry cell
#                       (telemetry.TM_FIELDS names all 16 as of the
#                       rebalance fields — shared-memory layout is
#                       append-only; grow _TM_SLOTS before TM_FIELDS)
_TM_CELL = _TM_SLOTS * 8
_SLOT_HEADER = 48     # count, offs_bytes, payload_bytes, partition,
#                       start_offset, ingest_us — all little-endian int64
_HDR = struct.Struct("<qqqqqq")

# heartbeat seam labels travel as small codes through the cells (fixed
# table, parent side decodes); 0 = unlabeled
_HB_LABELS = ("io", "open", "flush", "close", "publish", "shred",
              "append", "dead_letter")
_HB_CODE = {lbl: i + 1 for i, lbl in enumerate(_HB_LABELS)}


class ShmBatchRing:
    """A ring of fixed-size batch slots in one shared-memory segment,
    plus per-worker heartbeat AND telemetry cells at the front.

    The parent creates it (``create=True``), writes batches into free
    slots and recycles them when the consuming child reports the slot
    drained; children attach by name and read slot views zero-copy.
    Slot allocation/free bookkeeping lives entirely in the parent
    (:class:`ProcessWorkerPool`) — the ring itself is just memory."""

    def __init__(self, slots: int, slot_bytes: int, *, create: bool = True,
                 name: str | None = None) -> None:
        from multiprocessing import shared_memory

        if slots < 1 or slot_bytes <= _SLOT_HEADER + 16:
            raise ValueError("ring needs >= 1 slot of useful capacity")
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._hb_bytes = _HB_MAX * _HB_CELL
        self._tm_bytes = _HB_MAX * _TM_CELL
        total = self._hb_bytes + self._tm_bytes + slots * slot_bytes
        self._shm = shared_memory.SharedMemory(create=create, name=name,
                                               size=total if create else 0)
        # NOTE on resource tracking: spawn children inherit the parent's
        # resource-tracker process, and register() dedupes by name, so
        # attach-side registrations collapse into the parent's one entry;
        # the parent's unlink() (pool.finalize) both removes the segment
        # and unregisters it.  A SIGKILLed child therefore never unlinks
        # the ring out from under the survivors (cpython #82300 only
        # bites processes with independent trackers).
        self.name = self._shm.name
        self._buf = self._shm.buf
        # heartbeat cells as one (HB_MAX, 4) float64/int64 view pair
        self._hb_i = np.frombuffer(self._buf, np.int64,
                                   count=_HB_MAX * 4).reshape(_HB_MAX, 4)
        self._hb_f = np.frombuffer(self._buf, np.float64,
                                   count=_HB_MAX * 4).reshape(_HB_MAX, 4)
        # telemetry cells: one int64 counter vector per worker (see
        # runtime/telemetry.py for the field meanings); single-writer
        # per cell, torn reads benign — every field is monotonic
        self._tm = np.frombuffer(
            self._buf, np.int64, count=_HB_MAX * _TM_SLOTS,
            offset=self._hb_bytes).reshape(_HB_MAX, _TM_SLOTS)

    # -- slot payload capacity ------------------------------------------------
    def fits(self, count: int, payload_bytes: int) -> bool:
        need = _SLOT_HEADER + (count + 1) * 8 + payload_bytes
        return need <= self.slot_bytes

    def max_records_for(self, est_record_bytes: float) -> int:
        """How many ~``est_record_bytes`` records one slot holds — the
        dispatcher's unit-splitting bound."""
        usable = self.slot_bytes - _SLOT_HEADER
        return max(1, int(usable / (max(est_record_bytes, 1.0) + 8)) - 1)

    def _slot_off(self, idx: int) -> int:
        if not 0 <= idx < self.slots:
            raise IndexError(f"slot {idx} out of range")
        return self._hb_bytes + self._tm_bytes + idx * self.slot_bytes

    # -- parent side -----------------------------------------------------------
    def write_slot(self, idx: int, partition: int, start_offset: int,
                   offsets: np.ndarray, payload) -> int:
        """Stage one contiguous batch into slot ``idx``: offsets are
        rebased to 0 (a RecordBatch slice window may start nonzero) and
        the payload window is memcpy'd once.  Returns the record count."""
        return self.write_slot_parts(idx, partition, start_offset,
                                     [(offsets, payload)])

    def write_slot_parts(self, idx: int, partition: int, start_offset: int,
                         parts, ingest_us: int = 0) -> int:
        """Stage SEVERAL offset-contiguous windows into one slot as a
        single merged offsets table + payload blob — the dispatcher packs
        a poll round's per-partition fetch slices together so unit size
        follows slot capacity, not fetch granularity (small fetches would
        otherwise make per-unit fixed costs the throughput ceiling).
        ``parts`` = [(offsets int64 n_i+1, payload buffer), ...]; the
        staging memcpy concatenates the windows (the same single copy the
        one-part path pays).  ``ingest_us`` stamps the unit's oldest
        batch's ingest wall-time (microseconds since the epoch, 0 =
        unknown) through the descriptor — the end-to-end ack-latency
        plane's anchor.  Returns the merged record count."""
        norm = [(np.ascontiguousarray(o, np.int64), p) for o, p in parts]
        count = sum(len(o) - 1 for o, _ in norm)
        nbytes = sum(int(o[-1] - o[0]) for o, _ in norm)
        if not self.fits(count, nbytes):
            raise ValueError(
                f"batch ({count} records, {nbytes} B) exceeds slot capacity "
                f"({self.slot_bytes} B incl. header+offsets)")
        off = self._slot_off(idx)
        self._buf[off: off + _SLOT_HEADER] = _HDR.pack(
            count, (count + 1) * 8, nbytes, partition, start_offset,
            int(ingest_us))
        dst_offs = np.frombuffer(self._buf, np.int64, count=count + 1,
                                 offset=off + _SLOT_HEADER)
        data_start = off + _SLOT_HEADER + (count + 1) * 8
        dst_offs[0] = 0
        rec = 0
        byte = 0
        for o, payload in norm:
            n = len(o) - 1
            base = int(o[0])
            window = memoryview(payload)[base: int(o[-1])]
            np.subtract(o[1:], base - byte, out=dst_offs[rec + 1:
                                                         rec + n + 1])
            self._buf[data_start + byte: data_start + byte + len(window)] \
                = window
            rec += n
            byte += len(window)
        return count

    # -- child side ------------------------------------------------------------
    def read_slot(self, idx: int):
        """(partition, start_offset, count, offsets_view, payload_view,
        ingest_us) — both views alias the shared segment (zero-copy); the
        caller must finish with them before the slot is reported free."""
        off = self._slot_off(idx)
        (count, offs_bytes, nbytes, partition, start_offset,
         ingest_us) = _HDR.unpack(bytes(self._buf[off: off + _SLOT_HEADER]))
        offs = np.frombuffer(self._buf, np.int64, count=count + 1,
                             offset=off + _SLOT_HEADER)
        o_end = off + _SLOT_HEADER + offs_bytes
        payload = self._buf[o_end: o_end + nbytes]
        return partition, start_offset, count, offs, payload, ingest_us

    # -- heartbeat cells -------------------------------------------------------
    def hb_publish(self, widx: int, label_code: int, pending: bool,
                   started_at: float) -> None:
        """Child side: publish this worker's oldest pending IO op (or
        clear it) plus a liveness beat.  One cell per worker, torn reads
        acceptable — the watchdog tolerates a stale sample.  Ordering:
        pending flips LAST on set and FIRST on clear, so a racing reader
        can never observe pending=1 paired with a cleared/stale
        started_at (which would read as an enormous stall age and get a
        healthy child condemned)."""
        if self._hb_i is None:  # ring already closed (exit race)
            return
        schedcheck.note_hb_write(widx)
        if pending:
            self._hb_i[widx, 0] = label_code
            self._hb_f[widx, 2] = started_at
            # schedule-explorer edge: the ordering above (payload fields
            # BEFORE the pending flip) is exactly what the torn-read
            # probe in _ProcHeartbeat.stall verifies under perturbation
            schedcheck.point("proc.hb.publish")
            self._hb_i[widx, 1] = 1
        else:
            self._hb_i[widx, 1] = 0
            schedcheck.point("proc.hb.publish")
            self._hb_i[widx, 0] = label_code
            self._hb_f[widx, 2] = started_at
        self._hb_f[widx, 3] = time.monotonic()

    def hb_read(self, widx: int) -> tuple[int, bool, float, float]:
        if self._hb_i is None:
            return 0, False, 0.0, 0.0
        return (int(self._hb_i[widx, 0]), bool(self._hb_i[widx, 1]),
                float(self._hb_f[widx, 2]), float(self._hb_f[widx, 3]))

    def hb_clear(self, widx: int) -> None:
        if self._hb_i is None:
            return
        self._hb_i[widx, 1] = 0
        self._hb_i[widx, 0] = 0

    def hb_label(self, widx: int) -> str | None:
        """Decode the op label the worker last published (``None`` when
        the cell is unlabeled or already cleared).  This is the flight
        recorder's stalled-stage attribution for a child that died
        without a goodbye (kill -9, OOM): the cell survives the death
        and is only cleared later by ``respawn_slot``."""
        code, _pending, _started, _beat = self.hb_read(widx)
        if 1 <= code <= len(_HB_LABELS):
            return _HB_LABELS[code - 1]
        return None

    # -- telemetry cells -------------------------------------------------------
    def tm_publish(self, widx: int, values) -> None:
        """Child side: overwrite this worker's telemetry counter cell
        (field order = ``telemetry.TM_FIELDS``).  Single writer per
        cell; a torn parent read sees a counter one tick stale, never
        garbage — every field is monotonic."""
        if self._tm is None:  # ring already closed (exit race)
            return
        n = min(len(values), _TM_SLOTS)
        self._tm[widx, :n] = values[:n]

    def tm_read(self, widx: int) -> list[int]:
        if self._tm is None:
            return [0] * _TM_SLOTS
        return [int(v) for v in self._tm[widx]]

    def tm_clear(self, widx: int) -> None:
        if self._tm is None:
            return
        self._tm[widx, :] = 0

    def close(self) -> None:
        # drop our numpy views before closing the mmap; a caller-held
        # slot view keeps the mapping alive until IT is released
        # (BufferError from mmap — the unmap happens at that release)
        self._hb_i = self._hb_f = self._tm = None
        self._buf = None
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass


class _ProcHeartbeat:
    """Parent-side watchdog adapter over one child's heartbeat cells:
    presents the :class:`~kpw_tpu.runtime.watchdog.Heartbeat` read API
    (``stall()``) the Watchdog scans.  CLOCK_MONOTONIC is system-wide on
    Linux, so the child's ``started_at`` stamp is directly comparable."""

    def __init__(self, ring: ShmBatchRing, widx: int) -> None:
        self._ring = ring
        self._widx = widx

    def stall(self) -> tuple[float, str | None]:
        code, pending, started_at, _beat = self._ring.hb_read(self._widx)
        # started_at == 0.0 can only be a torn read racing a clear (a
        # real op stamps a live monotonic clock) — never a stall
        if not pending or started_at == 0.0:
            return 0.0, None
        # invariant probe (schedule explorer): a stall age is about to
        # be computed — the clock it is computed from must be a live
        # stamp.  pending with a cleared/garbage started_at here is the
        # PR-11 torn-read shape that condemned a healthy child; the
        # hb_publish write ordering plus the guard above must make this
        # unreachable under ANY interleaving (the legacy shapes in
        # tools/schedx reach it)
        schedcheck.note_hb_sample(self._widx, True, started_at)
        label = (_HB_LABELS[code - 1]
                 if 1 <= code <= len(_HB_LABELS) else "io")
        return max(0.0, time.monotonic() - started_at), label


def _proto_spec(proto_class) -> tuple[str, tuple[bytes, ...]]:
    """(message full name, serialized FileDescriptorProto closure) — the
    picklable shape a spawned child rebuilds the message class from.
    Works for protoc-generated AND runtime-built (message_factory)
    classes; a class without a protobuf DESCRIPTOR is not spawnable."""
    desc = getattr(proto_class, "DESCRIPTOR", None)
    if desc is None or not hasattr(desc, "file"):
        raise ValueError(
            "process_workers needs a protobuf message class (DESCRIPTOR "
            "with a file) so the spawned children can rebuild it")
    from google.protobuf import descriptor_pb2

    blobs: list[bytes] = []
    seen: set[str] = set()

    def add(fd) -> None:
        if fd.name in seen:
            return
        seen.add(fd.name)
        for dep in fd.dependencies:
            add(dep)
        fdp = descriptor_pb2.FileDescriptorProto()
        fd.CopyToProto(fdp)
        blobs.append(fdp.SerializeToString())

    add(desc.file)
    return desc.full_name, tuple(blobs)


def _proto_class_from_spec(spec):
    full_name, blobs = spec
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)

    pool = descriptor_pool.DescriptorPool()
    for b in blobs:
        pool.Add(descriptor_pb2.FileDescriptorProto.FromString(b))
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName(full_name))


class ChildConfig:
    """Everything one spawned worker needs, picklable by construction.
    Built by the pool from the Builder; the child reconstructs the proto
    class from its descriptor closure and a fresh LocalFileSystem (the
    only filesystem whose handles are per-process by nature)."""

    def __init__(self, b, index: int, ring_name: str, ring_slots: int,
                 slot_bytes: int) -> None:
        self.index = index
        self.ring_name = ring_name
        self.ring_slots = ring_slots
        self.slot_bytes = slot_bytes
        self.instance_name = b._instance_name
        self.target_dir = b._target_dir.rstrip("/")
        self.proto_spec = _proto_spec(b._proto_class)
        self.properties = b.writer_properties()  # plain dataclass
        self.backend = b._backend
        self.pipeline = b._pipeline
        self.batch_size = b._batch_size
        self.max_file_size = b._max_file_size
        self.max_file_open_duration = b._max_file_open_duration
        self.file_date_time_pattern = b._file_date_time_pattern
        self.directory_date_time_pattern = b._directory_date_time_pattern
        self.file_extension = b._file_extension
        self.on_parse_error = b._on_parse_error
        self.durable_publish = b._durable_publish
        self.verify_on_publish = b._verify_on_publish
        self.tracing = b._tracing
        self.trace_span_capacity = b._trace_span_capacity


# ---------------------------------------------------------------------------
# child side
# ---------------------------------------------------------------------------

def child_main(cfg: ChildConfig, work_q, ack_q) -> None:
    """Spawn entry: run one worker process until poison or fatal error.
    Must stay module-level (spawn pickles the callable by reference)."""
    try:
        worker = _ChildWorker(cfg, work_q, ack_q)
    except BaseException as e:  # noqa: BLE001 — startup must report, not vanish
        ack_q.put(("died", cfg.index, os.getpid(),
                   f"child startup failed: {e!r}"))
        raise
    worker.run()


class _ChildWorker:
    """The in-process half of one worker slot: drain ``(seq, slot)``
    units from the work queue, shred each slot's buffer in place, encode
    and rotate parquet files, publish with the exact tmp→(verify)→rename
    protocol of the thread-mode worker, and acknowledge published units
    so the parent can ack their offset runs.  Mirrors ``_Worker``'s loop
    shape; deliberately self-contained — it runs in a fresh interpreter
    where the parent's writer object does not exist."""

    def __init__(self, cfg: ChildConfig, work_q, ack_q) -> None:
        from ..io.fs import LocalFileSystem
        from ..models.proto_bridge import ProtoColumnarizer
        from .retry import RetryPolicy
        from .watchdog import Heartbeat

        self.cfg = cfg
        self.work_q = work_q
        self.ack_q = ack_q
        self.fs = LocalFileSystem()
        self.proto_class = _proto_class_from_spec(cfg.proto_spec)
        self.columnarizer = ProtoColumnarizer(self.proto_class)
        self.ring = ShmBatchRing(cfg.ring_slots, cfg.slot_bytes,
                                 create=False, name=cfg.ring_name)
        self.retry = RetryPolicy()
        self._stop = threading.Event()
        self.heartbeat = Heartbeat()
        self._hb_publisher = threading.Thread(target=self._publish_hb,
                                              name="kpw-child-hb",
                                              daemon=True)
        if cfg.backend in (None, "cpu"):
            self._encoder_factory = lambda: None
        else:
            from .select import make_encoder

            opts = cfg.properties.encoder_options()
            self._encoder_factory = lambda: make_encoder(opts, cfg.backend)
        self.current_file = None
        self._pending_seqs: list[int] = []  # units in the open file
        self._pending_parts: set[int] = set()  # partitions of those units
        self._carry_est = 64.0
        # cooperative-rebalance counters (TM cell fields): files flushed
        # under a revoke fence / open files abandoned on revoke-lost
        self._rebalance_fenced = 0
        self._rebalance_abandoned = 0
        # test seam for the zombie-child drill: while this path exists on
        # disk the child parks INSIDE its publish (heartbeat label
        # "publish" stays pending) — the cross-process analog of the
        # thread-mode gated exists() probe, which cannot reach a child
        # because proc mode pins the child filesystem to LocalFileSystem
        self._publish_gate = os.environ.get("KPW_CHILD_PUBLISH_GATE")
        # retry accounting, reported to the parent with every published
        # file so process-mode stats() shows real retry activity
        self._retries = 0
        self._backoff_s = 0.0
        self._last_error: str | None = None
        self._files_published = 0
        self._use_wire = self.columnarizer.wire_capable
        # telemetry-plane counters, published to this worker's shm cell
        # (~20 Hz from the heartbeat thread) and snapshotted over the
        # low-rate ack-queue side channel at rotation/seal boundaries
        self._written_records = 0
        self._written_bytes = 0
        self._flushed_records = 0
        self._flushed_bytes = 0
        self._deadletter_records = 0
        self._units_processed = 0
        self._rot_size = 0
        self._rot_time = 0
        self._last_side_send = 0.0
        self._spans_shipped = 0
        self.stage_timer: tracing.StageTimer | None = None
        self.span_recorder: tracing.SpanRecorder | None = None
        if cfg.tracing:
            # this interpreter's module globals are the child's own —
            # installing here mirrors writer.start() in the parent
            self.stage_timer = tracing.StageTimer()
            self.span_recorder = tracing.SpanRecorder(
                capacity=cfg.trace_span_capacity)
            tracing.set_tracer(self.stage_timer)
            tracing.set_span_recorder(self.span_recorder)

    # -- heartbeat publisher --------------------------------------------------
    def _publish_hb(self) -> None:
        ring, widx = self.ring, self.cfg.index
        while not self._stop.is_set():
            age, label = self.heartbeat.stall()
            if label is None:
                ring.hb_publish(widx, 0, False, 0.0)
            else:
                ring.hb_publish(widx, _HB_CODE.get(label, 0), True,
                                time.monotonic() - age)
            ring.tm_publish(widx, self._tm_values())
            self._stop.wait(0.05)
        ring.hb_clear(widx)

    # -- telemetry plane ------------------------------------------------------
    def _tm_values(self) -> tuple:
        """This worker's counter vector, field order = ``TM_FIELDS``."""
        rec = self.span_recorder
        st = self.stage_timer
        stage_us = 0
        if st is not None:
            stage_us = int(sum(s["seconds"]
                               for s in st.summary().values()) * 1e6)
        return (self._written_records, self._written_bytes,
                self._flushed_records, self._flushed_bytes,
                self._files_published, self._units_processed,
                self._retries, int(self._backoff_s * 1000),
                self._deadletter_records, self._rot_size, self._rot_time,
                # cumulative spans: shipped batches + whatever the side
                # channel has not drained yet (len(rec) alone would reset
                # to ~0 on every drain — a sawtooth, not a counter)
                (self._spans_shipped + len(rec)) if rec is not None else 0,
                rec.dropped if rec is not None else 0,
                stage_us,
                self._rebalance_fenced, self._rebalance_abandoned)

    def _maybe_send_telemetry(self, force: bool = False) -> None:
        """The low-rate side channel: a full snapshot (counter dict +
        stage summary + drained span buffer) over the ack queue.  Sent
        at rotation/seal boundaries and at exit; throttled so a
        fast-rotating child cannot flood the collector."""
        now = time.monotonic()
        if not force and now - self._last_side_send < 0.5:
            return
        self._last_side_send = now
        spans = None
        if self.span_recorder is not None:
            spans = self.span_recorder.export_payload(
                process_name=f"kpw-proc-worker-{self.cfg.index}")
            self._spans_shipped += len(spans["spans"])
        payload = {
            "pid": os.getpid(),
            "tm": dict(zip(TM_FIELDS, self._tm_values())),
            "stages": (self.stage_timer.summary()
                       if self.stage_timer is not None else None),
            "spans": spans,
        }
        try:
            self.ack_q.put(("telemetry", self.cfg.index, payload))
        except (OSError, ValueError):
            pass  # parent queue torn down mid-exit; nothing to report to

    def _retry(self, fn, label: str = "io"):
        token = self.heartbeat.io_started(label)
        try:
            return self.retry.call(fn, stop_event=self._stop,
                                   on_retry=self._on_retry, label=label)
        finally:
            self.heartbeat.io_finished(token)

    def _on_retry(self, attempt: int, exc: BaseException,
                  sleep_s: float) -> None:
        self.heartbeat.beat()
        self._retries += 1
        self._backoff_s += sleep_s
        self._last_error = repr(exc)

    # -- main loop -------------------------------------------------------------
    def run(self) -> None:
        self._hb_publisher.start()
        self.ack_q.put(("ready", self.cfg.index, os.getpid()))
        try:
            fence: dict[int, str] = {}  # partition -> pending fence mode
            while True:
                try:
                    msg = self.work_q.get_nowait()
                except pyqueue.Empty:
                    # queue drained: NOW service accumulated fence
                    # descriptors.  The deferral is the thread worker's
                    # _service_fence parity — an abandon posted a few µs
                    # behind its flush (the rejoin-after-expiry shape)
                    # must supersede it, not watch it publish rows whose
                    # commits can only come back fenced
                    if fence:
                        self._service_fences(fence)
                        fence = {}
                    try:
                        msg = self.work_q.get(timeout=0.05)
                    except pyqueue.Empty:
                        self._maybe_time_rotate()
                        continue
                if msg is None:  # poison: abandon the open tmp un-acked
                    self._abandon("close")
                    self.ack_q.put(("closed", self.cfg.index))
                    return
                kind = msg[0]
                if kind == "revoke":
                    # cross-process fence descriptor: the parent's
                    # rebalance listener revoked partitions; flush (drain
                    # window open) or abandon (LOST / deadline lapsed)
                    # whatever of the open file touches them.  Abandon
                    # supersedes a pending flush; a flush never
                    # downgrades an abandon.
                    _, parts, mode = msg
                    for p in parts:
                        if mode == "abandon" or fence.get(p) != "abandon":
                            fence[p] = mode
                elif kind == "unit":
                    _, seq, slot_idx = msg
                    self._process_unit(seq, slot_idx)
                self._maybe_time_rotate()
        except RetryInterrupted:
            self._abandon("close")
            self.ack_q.put(("closed", self.cfg.index))
        except BaseException as e:  # noqa: BLE001 — the death report IS the seam
            logger.exception("proc worker %d terminated", self.cfg.index)
            self._abandon("error")
            self.ack_q.put(("died", self.cfg.index, os.getpid(), repr(e)))
            raise
        finally:
            # final telemetry flush: the cell freezes at these values
            # (the parent banks them on respawn) and the side channel
            # carries the tail spans the parent has not seen yet
            self.ring.tm_publish(self.cfg.index, self._tm_values())
            self._maybe_send_telemetry(force=True)
            self._stop.set()
            # the heartbeat publisher must stop touching the mapping
            # before the ring closes (BufferError/segfault race otherwise)
            self._hb_publisher.join(timeout=1.0)
            self.ring.close()

    def _process_unit(self, seq: int, slot_idx: int) -> None:
        partition, start_offset, count, offs, payload, ingest_us = \
            self.ring.read_slot(slot_idx)
        self._units_processed += 1
        nbytes = int(offs[-1] - offs[0])
        # lint: clock-discipline ok — operator-facing ingest age (the
        # wall stamp travels from the consumer through the descriptor);
        # a span attribute for the trace timeline, never a liveness
        # verdict
        age_s = (round(max(0.0, time.time() - ingest_us / 1e6), 6)
                 if ingest_us else 0.0)
        batch = None
        records = None
        if self._use_wire:
            from ..models.proto_bridge import WireShredError

            try:
                with stage("worker.shred", records=count,
                           ingest_age_s=age_s):
                    batch = self.columnarizer.columnarize_buffer(payload,
                                                                 offs)
            except WireShredError:
                batch = None
        if batch is not None:
            if self.current_file is None:
                self._open_file()
            self._retry(self.current_file.flush_buffered, "flush")
            with stage("worker.append"):
                self.current_file.append_batch(batch)
            # slot memory is no longer referenced (shredder outputs are
            # fresh arrays) and the rows are IN the open file — recycle.
            # This message is also the parent's "written" edge, so it
            # must not precede the append (a death in between would
            # count written rows that never entered any file).
            self.ack_q.put(("free", self.cfg.index, slot_idx, seq))
            self._written_records += count
            self._written_bytes += nbytes
            self._retry(self.current_file.maybe_flush_row_group, "flush")
        else:
            # fallback: materialize + parse per record (poison-pill
            # policies live here, exactly like thread mode)
            blob = bytes(payload)
            records = [blob[int(offs[i]): int(offs[i + 1])]
                       for i in range(count)]
            parsed = self._parse_fallback(records, partition, start_offset)
            if not parsed:
                # nothing written for this unit: it is already safe
                # (skipped/dead-lettered) — recycle + ack, no publish
                self.ack_q.put(("free", self.cfg.index, slot_idx, seq))
                self.ack_q.put(("published", self.cfg.index, [seq], None,
                                self._retry_stats()))
                return
            if self.current_file is None:
                self._open_file()
            self.current_file.append_records(parsed)
            self.ack_q.put(("free", self.cfg.index, slot_idx, seq))
            self._written_records += len(parsed)
            self._written_bytes += nbytes
            self._retry(self.current_file.flush_if_full, "flush")
        self._pending_seqs.append(seq)
        self._pending_parts.add(partition)
        if (self.current_file is not None
                and self.current_file.get_data_size()
                >= self.cfg.max_file_size):
            self._finalize("size")

    def _parse_fallback(self, payloads: list, partition: int,
                        start_offset: int) -> list:
        parsed = []
        for i, raw in enumerate(payloads):
            try:
                parsed.append(self.proto_class.FromString(raw))
            except Exception:
                if self.cfg.on_parse_error == "dead_letter":
                    self._retry(lambda r=raw, o=start_offset + i:
                                self._dead_letter(partition, o, r),
                                "dead_letter")
                elif self.cfg.on_parse_error != "skip":
                    raise
        return parsed

    def _dead_letter(self, partition: int, offset: int, raw: bytes) -> None:
        d = f"{self.cfg.target_dir}/deadletter"
        self.fs.mkdirs(d)
        path = f"{d}/{self.cfg.instance_name}_{self.cfg.index}.bin"
        frame = struct.pack("<iqI", partition, offset, len(raw)) + raw
        with self.fs.open_append(path) as f:
            f.write(frame)
        self._deadletter_records += 1

    # -- files -----------------------------------------------------------------
    def _open_file(self) -> None:
        from .parquet_file import ParquetFile

        def make():
            tmp_dir = f"{self.cfg.target_dir}/tmp"
            self.fs.mkdirs(tmp_dir)
            import random

            path = (f"{tmp_dir}/{self.cfg.instance_name}_"
                    f"{self.cfg.index}_{random.getrandbits(63)}.tmp")
            return ParquetFile(self.fs, path, self.columnarizer,
                               self.cfg.properties,
                               batch_size=self.cfg.batch_size,
                               encoder=self._encoder_factory(),
                               pipeline=bool(self.cfg.pipeline),
                               est_record_bytes=self._carry_est,
                               retry_policy=self.retry,
                               heartbeat=self.heartbeat)

        self.current_file = self._retry(make, "open")

    def _service_fences(self, fence: dict) -> None:
        """Service the accumulated fence descriptors, abandon flavor
        first (its partitions' rows must not publish at all)."""
        ab = frozenset(p for p, m in fence.items() if m == "abandon")
        fl = frozenset(p for p, m in fence.items() if m == "flush")
        if ab:
            self._service_revoke(ab, "abandon")
        if fl:
            self._service_revoke(fl, "flush")

    def _service_revoke(self, parts: frozenset, mode: str) -> None:
        """One fence descriptor from the parent.  ``flush``: the drain
        window is open — publish+ack the open file now if it holds any
        revoked partition's rows (rotation cause ``revoke``, exactly the
        thread-mode `_service_fence` flavor).  ``abandon``: the window
        lapsed or the assignment is LOST — publishing would only earn a
        fenced commit, so the open file is dropped whole and its units
        reported ``abandoned`` (the parent redelivers retained-partition
        runs; revoked ones ride the committed frontier to the new owner).

        Work-queue FIFO makes the protocol race-free child-side: every
        unit dispatched before the fence lands in the open file before
        this runs, so the flush/abandon decision covers them all."""
        if not (self._pending_parts & parts):
            return  # open file (if any) holds only retained partitions
        if mode == "abandon":
            seqs, self._pending_seqs = self._pending_seqs, []
            self._pending_parts.clear()
            self._abandon("revoke")
            self._rebalance_abandoned += 1
            self.ack_q.put(("abandoned", self.cfg.index, seqs))
            self._maybe_send_telemetry()
            return
        self._rebalance_fenced += 1
        self._finalize("revoke")

    def _maybe_time_rotate(self) -> None:
        f = self.current_file
        # lint: clock-discipline ok — wall-clock file-age rotation
        # mirrors thread mode exactly (ParquetFile.get_creation_time is
        # wall time); rotation is a naming/policy deadline, never a
        # liveness verdict — a clock step rotates a file early, it
        # cannot condemn a worker
        if (f is not None and time.time() - f.get_creation_time()
                >= self.cfg.max_file_open_duration):
            self._finalize("time")

    def _finalize(self, reason: str) -> None:
        f = self.current_file
        if f is None:
            return
        f.rotation_reason = reason
        self._carry_est = f.est_record_bytes
        if f.get_num_written_records() == 0:
            self._retry(f.close, "close")
            self._retry(lambda: self.fs.delete(f.path), "close")
            self.current_file = None
            # an empty file can still cover all-skipped units
            self._ack_pending(None, reason)
            return
        self._retry(f.close, "close")
        size = self.fs.size(f.path)
        # publish: (verify) -> collision-safe dest -> (durable) rename —
        # the rename tail is the SHARED writer.publish_rename protocol,
        # so thread and process mode cannot drift
        from .writer import _format_now, publish_rename

        with stage("worker.publish"):
            if self.cfg.verify_on_publish:
                from ..io.verify import verify_file

                rep = verify_file(self.fs, f.path)
                if not rep.ok:
                    qdir = f"{self.cfg.target_dir}/quarantine"
                    self.fs.mkdirs(qdir)
                    qpath = f"{qdir}/{f.path.rsplit('/', 1)[-1]}"
                    n = 0
                    while self.fs.exists(qpath):
                        n += 1
                        qpath = (f"{qdir}/{f.path.rsplit('/', 1)[-1]}.{n}")
                    self.fs.rename(f.path, qpath)
                    # the parent meters the failure + quarantine; the
                    # raise below kills this child un-acked (redelivery)
                    self.ack_q.put(("verify_failed", self.cfg.index))
                    raise RuntimeError(
                        f"tmp failed structural verification, quarantined "
                        f"to {qpath}: {rep.errors[:3]}")
            dest_dir = self.cfg.target_dir
            if self.cfg.directory_date_time_pattern:
                dest_dir = (f"{dest_dir}/"
                            f"{_format_now(self.cfg.directory_date_time_pattern)}")
                self._retry(lambda d=dest_dir: self.fs.mkdirs(d), "publish")
            ts = _format_now(self.cfg.file_date_time_pattern)
            name = (f"{ts}_{self.cfg.instance_name}_{self.cfg.index}"
                    f"{self.cfg.file_extension}")
            if self._publish_gate:
                # zombie-child drill seam: park mid-publish (heartbeat
                # pending under "publish") until the gate file is removed
                tok = self.heartbeat.io_started("publish")
                try:
                    while (os.path.exists(self._publish_gate)
                           and not self._stop.is_set()):
                        time.sleep(0.01)
                finally:
                    self.heartbeat.io_finished(tok)
            dest = publish_rename(self.fs, self._retry, f.path, dest_dir,
                                  name, self.cfg.durable_publish)
        info = {
            "size": size,
            "records": f.get_num_written_records(),
            "reason": reason,
            # the published path rides the ack so the parent's fenced-ack
            # backstop can un-publish a zombie child's file (the parent
            # and child share the local tree — proc mode pins the fs)
            "dest": dest,
            "verified": bool(self.cfg.verify_on_publish),
            "index": f.index_info(),
            "assembly": f.assembly_info(),
        }
        self._files_published += 1
        self._flushed_records += info["records"]
        self._flushed_bytes += size
        if reason == "time":
            self._rot_time += 1
        elif reason != "revoke":  # revoke counts via _rebalance_fenced
            self._rot_size += 1
        self.current_file = None
        self._ack_pending(info, reason)

    def _ack_pending(self, file_info, reason: str) -> None:
        """Every unit whose rows are now durably published (or that wrote
        nothing) is safe to ack — the parent commits their offset runs."""
        self._pending_parts.clear()
        if not self._pending_seqs:
            if file_info is not None:
                self.ack_q.put(("published", self.cfg.index, [], file_info,
                                self._retry_stats()))
                self._maybe_send_telemetry()
            return
        seqs, self._pending_seqs = self._pending_seqs, []
        self.ack_q.put(("published", self.cfg.index, seqs, file_info,
                        self._retry_stats()))
        # seal boundary: the natural low-rate beat for the side channel
        self._maybe_send_telemetry()

    def _retry_stats(self) -> tuple:
        """(retries, backoff_s, last_error) riding every published-file
        ack so the parent slot's observability mirrors thread mode."""
        return (self._retries, round(self._backoff_s, 6), self._last_error)

    def _abandon(self, reason: str) -> None:
        f = self.current_file
        if f is None:
            return
        try:
            f.rotation_reason = reason
            f.abandon()
        except Exception:
            logger.exception("proc worker %d: abandon failed (ignored)",
                             self.cfg.index)
        self.current_file = None
        self._pending_parts.clear()


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class _ProcWorkerSlot:
    """Parent-side handle for one worker process — the ``_Worker`` duck
    type: the supervisor joins/restarts it, the watchdog scans its
    heartbeat, ``stats()``/``ack_lag()`` read the same attributes.  The
    decisive difference from a thread slot: ``condemn`` **SIGKILLs** the
    process, so a hung child is actually reclaimed instead of parked."""

    def __init__(self, pool: "ProcessWorkerPool", index: int) -> None:
        self.pool = pool
        self.index = index
        self.work_q = _MP_CTX.Queue()
        self._proc = _MP_CTX.Process(
            target=child_main,
            args=(pool.child_config(index), self.work_q, pool.ack_q),
            name=f"KPW-proc-{pool.instance_name}-{index}",
            daemon=True)
        self.heartbeat = _ProcHeartbeat(pool.ring, index)
        self.failed = False
        self.condemned = False
        self.ready = False  # set by the collector on the child's hello
        self.exit_reason: str | None = None
        self.retries = 0
        self.backoff_s = 0.0
        self.last_error: str | None = None
        self.pid: int | None = None
        # seq -> {"runs": [(p, s, e)], "count", "bytes", "slot", "freed",
        #          "sent", "fenced"} — guarded by _mu: dispatcher inserts
        # (sent=False) and marks sent under the lock, collector settles,
        # the supervisor reads held_runs() after join, the rebalance
        # listener backs out un-sent revoked units / force-releases runs
        self._mu = threading.Lock()
        self._ledger: dict[int, dict] = {}
        # sticky cooperative-revocation fence: partitions whose drain
        # window is open (GIL-atomic frozenset swaps, the thread
        # worker's _fence_req discipline — fetcher thread writes,
        # dispatcher reads)
        self._fence_flush: frozenset = frozenset()
        self._unacked_count = 0
        self._oldest_unacked_ts: float | None = None
        self._written = 0
        self._published_files = 0
        self._poisoned = False
        # stats() compatibility with the thread worker
        self._part_files: dict = {}

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        self._proc.start()
        self.pid = self._proc.pid

    def alive(self) -> bool:
        return self._proc.is_alive()

    def join(self, timeout: float | None = None) -> None:
        self._proc.join(timeout)

    def condemn(self, reason: str) -> None:
        """Watchdog abandon, process edition: the hung child is killed
        outright (its tmp stays on disk, swept next start; its held runs
        redeliver), and the slot is declared failed for the supervisor."""
        self.condemned = True
        self.exit_reason = reason
        self.failed = True
        try:
            self._proc.kill()
        except (OSError, ValueError):
            pass

    def close(self, timeout: float = 30.0,
              abandon_if_hung: bool = True) -> bool:
        """Poison → join → escalate.  The child abandons its open tmp on
        poison (never published, never acked — thread-mode close
        semantics); a child still alive at the deadline is terminated,
        then killed."""
        if not self._poisoned:
            self._poisoned = True
            try:
                self.work_q.put(None)
            except (OSError, ValueError):
                pass
        self._proc.join(timeout=max(0.0, timeout))
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=1.0)
            if self._proc.is_alive() and abandon_if_hung:
                self._proc.kill()
                self._proc.join(timeout=1.0)
        self.work_q.close()
        return not self._proc.is_alive()

    # -- supervisor surface ----------------------------------------------------
    def held_runs(self) -> list[tuple[int, int, int]]:
        """Every offset run dispatched to this child and never acked —
        the redelivery set after a death.  Mirrors ``_Worker.held_runs``
        (called by the supervisor AFTER joining the dead process)."""
        with self._mu:
            return [tuple(r) for e in self._ledger.values()
                    for r in e["runs"]]

    def drain_unfreed_slots(self) -> list[int]:
        """Ring slots dispatched to this child that it never reported
        drained — reclaimed by the pool once the process is dead (a dead
        process cannot be mid-read).  Atomically marks every entry freed
        under the ledger lock: a stale ``free`` ack still in the queue
        must find nothing left to recycle, or the same ring slot would
        enter the free pool twice and two units would be staged into the
        same shared memory concurrently.  Held runs stay in the ledger
        for the supervisor's redelivery."""
        schedcheck.point("proc.slot.drain")
        with self._mu:
            out = [e["slot"] for e in self._ledger.values()
                   if not e["freed"]]
            for e in self._ledger.values():
                e["freed"] = True
            return out

    # -- cooperative-rebalance surface (the _Worker fence duck type) -----------
    def request_fence(self, parts) -> None:
        """Revocation drain window opened: back out revoked units the
        child was never handed (their ledger runs release — the new
        owner reads them off the committed frontier), then forward the
        fence descriptor so the child flushes its open file early.  The
        fence is STICKY until ``fence_clear`` (mirroring the thread
        worker's ``_fence_req``): a batch buffered before the revoke can
        still dispatch after this descriptor, and the dispatcher re-sends
        the fence behind any such late unit so FIFO flushes it too."""
        ps = frozenset(parts)
        self._fence_flush = frozenset(self._fence_flush | ps)
        self.pool.backout_undispatched(self, ps)
        self._send_revoke(ps, "flush")

    def request_abandon(self, parts) -> None:
        """Drain deadline lapsed or assignment LOST: back out un-sent
        revoked units, force-release the revoked runs still in flight
        (held_runs() must stop reporting them even when the child is
        parked/unresponsive — the rejoin waits on that), and tell the
        child to drop its open file.  A file the parked child publishes
        later settles to zero acked runs and the collector's fenced
        backstop un-publishes it, so the release cannot double-count."""
        ps = frozenset(parts)
        # supersede any pending flush fence for them (thread-worker
        # request_abandon parity: their commits could no longer land)
        self._fence_flush = frozenset(self._fence_flush - ps)
        self.pool.backout_undispatched(self, ps)
        with self._mu:
            for e in self._ledger.values():
                if e["runs"] and any(r[0] in ps for r in e["runs"]):
                    e["runs"] = []
                    e["fenced"] = True
        self._send_revoke(ps, "abandon")

    def fence_clear(self, parts) -> None:
        """Drain confirmed for ``parts``: retire their sticky flush
        fence (the child-side state was consumed when the descriptor
        was serviced)."""
        self._fence_flush = frozenset(self._fence_flush - frozenset(parts))

    def _send_revoke(self, ps: frozenset, mode: str) -> None:
        rec = getattr(self.pool.w, "_flightrec", None)
        if rec is not None:
            rec.note("rebalance_fence_sent", worker=self.index,
                     partitions=sorted(ps), mode=mode)
        try:
            self.work_q.put(("revoke", tuple(sorted(ps)), mode))
        except (OSError, ValueError):
            pass  # child torn down; its ledger redelivers via the supervisor

    # -- ledger (dispatcher/collector) -----------------------------------------
    def note_dispatch(self, seq: int, runs, count: int, nbytes: int,
                      slot_idx: int) -> None:
        with self._mu:
            self._ledger[seq] = {"runs": runs, "count": count,
                                 "bytes": nbytes, "slot": slot_idx,
                                 "freed": False, "sent": False,
                                 "fenced": False}
            if self._oldest_unacked_ts is None:
                # lint: clock-discipline ok — operator-facing ack-age
                # observability matches thread-mode stats() (wall
                # timestamps); never consulted by watchdog/condemn logic
                self._oldest_unacked_ts = time.time()
            self._unacked_count += count

    def note_free(self, seq: int) -> tuple[int, int]:
        """The child drained the unit's ring slot (== its rows entered an
        open file).  Returns (count, bytes) for the written meters —
        (0, 0) when the entry is unknown OR already freed (a stale ack
        from a dead child whose slots ``drain_unfreed_slots`` reclaimed:
        recycling again would double-free the ring slot)."""
        schedcheck.point("proc.slot.note_free")
        with self._mu:
            e = self._ledger.get(seq)
            if e is None or e["freed"]:
                return 0, 0
            e["freed"] = True
            self._written += e["count"]
            return e["count"], e["bytes"]

    def mark_sent(self, seq: int) -> bool:
        """Dispatcher, immediately before the work-queue put: commit to
        sending.  Returns False when a concurrent revocation already
        backed the unit out — the put must not happen (the ring slot is
        recycled and the runs belong to the new owner)."""
        with self._mu:
            e = self._ledger.get(seq)
            if e is None:
                return False
            e["sent"] = True
            return True

    def backout_units(self, parts: frozenset) -> list[int]:
        """Pop every revoked unit the child was never handed (sent=False)
        and whose ring slot is still staged (freed=False): its runs were
        never processed anywhere, so dropping the entry hands them to the
        new owner via the committed frontier.  Returns the ring slots to
        recycle — the caller routes them through ``_recycle_slot`` so the
        double-recycle probe guards this path against the collector's
        concurrent ``free`` handling for the same slot."""
        with self._mu:
            out = []
            for seq, e in list(self._ledger.items()):
                if (not e["sent"] and not e["freed"] and e["runs"]
                        and all(r[0] in parts for r in e["runs"])):
                    self._ledger.pop(seq)
                    self._unacked_count = max(
                        0, self._unacked_count - e["count"])
                    out.append(e["slot"])
            if not self._ledger:
                self._oldest_unacked_ts = None
            return out

    def settle(self, seq: int):
        """The unit's rows are durably published (or needed no publish):
        pop its runs for acking."""
        return self.settle_unit(seq)[0]

    def peek_unit(self, seq: int) -> tuple[list, bool]:
        """(runs, fenced) WITHOUT popping the entry: the collector acks
        off the peek and settles only after the commits land, so
        ``held_runs()`` keeps reporting the runs until they are durable
        — ``revocation_drained`` must not confirm a handoff whose
        offsets have not committed yet (the new owner would refetch
        rows this member's file already published)."""
        with self._mu:
            e = self._ledger.get(seq)
            if e is None:
                return [], False
            return list(e["runs"]), bool(e.get("fenced"))

    def settle_unit(self, seq: int) -> tuple[list, bool]:
        """(runs, fenced): pop the unit; ``fenced`` is True when a
        revocation already force-released its runs — the ack arriving
        now is a zombie child's stale publish, and a file settling to
        zero acked runs with any fenced unit must be un-published."""
        with self._mu:
            e = self._ledger.pop(seq, None)
            if e is None:
                return [], False
            self._unacked_count = max(0, self._unacked_count - e["count"])
            if not self._ledger:
                self._oldest_unacked_ts = None
            return e["runs"], bool(e.get("fenced"))

    def inflight_units(self) -> int:
        with self._mu:
            return len(self._ledger)

    # -- observability ---------------------------------------------------------
    def rss_bytes(self) -> int:
        if self.pid is None:
            return 0
        try:
            with open(f"/proc/{self.pid}/statm") as f:
                return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
        except (OSError, IndexError, ValueError):
            return 0

    def open_partitions(self) -> list:
        return []

    def observability(self) -> dict:
        """Same key shape as ``_Worker.observability`` so ``stats()``
        folds both modes uniformly, plus the process-mode extras."""
        ts = self._oldest_unacked_ts
        stall_age, stall_label = self.heartbeat.stall()
        return {
            "worker": self.index,
            "mode": "process",
            "pid": self.pid,
            "alive": self.alive(),
            "failed": self.failed,
            "condemned": self.condemned,
            "stall_age_s": round(stall_age, 3),
            "stalled_in": stall_label,
            "exit_reason": self.exit_reason,
            "restarts": self.pool.restart_count(self.index),
            "retries": self.retries,
            "retry_backoff_s": round(self.backoff_s, 6),
            "last_error": self.last_error,
            "unacked_records": self._unacked_count,
            # lint: clock-discipline ok — observability age over the
            # wall timestamp recorded above; stats()-only, not liveness
            "oldest_unacked_age_s": (round(time.time() - ts, 6)
                                     if ts is not None else 0.0),
            "open_partitions": [],
            "proc_rate_rps": 0.0,
            "poll_batch": 0,
            "rss_bytes": self.rss_bytes(),
            "inflight_units": self.inflight_units(),
            "written_records": self._written,
            "published_files": self._published_files,
            "pipeline": {"files": self._published_files,
                         "split_assembly": False, "stage_busy_s": {},
                         "queues": {}},
        }


class ProcessWorkerPool:
    """The parent's process-mode engine: the shared-memory ring, one
    dispatcher thread (consumer queue → ring slots → per-child work
    queues) and one collector thread (child acks → offset commits +
    meters + liveness).  Owned by :class:`KafkaProtoParquetWriter`;
    ``slots`` is the live worker list the writer aliases as
    ``self._workers`` so the PR-3/5 supervisor, watchdog and stats
    machinery operate on process slots unchanged."""

    def __init__(self, writer) -> None:
        self.w = writer
        b = writer._b
        self.instance_name = b._instance_name
        self.n_workers = b._proc_workers
        if self.n_workers > _HB_MAX:
            raise ValueError(f"process_workers supports at most {_HB_MAX}")
        self.ring = ShmBatchRing(b._proc_ring_slots, b._proc_slot_bytes)
        self.ack_q = _MP_CTX.Queue()
        self._max_inflight = b._proc_max_inflight
        self.slots: list[_ProcWorkerSlot] = [
            _ProcWorkerSlot(self, i) for i in range(self.n_workers)]
        self._free: pyqueue.Queue = pyqueue.Queue()
        self._pool_key = id(self)
        schedcheck.note_pool_reset(self._pool_key, b._proc_ring_slots)
        for i in range(b._proc_ring_slots):
            self._free.put(i)
        self._stop = threading.Event()
        self._seq = 0
        self._rr = 0
        self.dispatched_units = 0
        self.acked_units = 0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"KPW-proc-dispatch-{self.instance_name}", daemon=True)
        self._collector = threading.Thread(
            target=self._collect_loop,
            name=f"KPW-proc-collect-{self.instance_name}", daemon=True)
        self._closed = False

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        for s in self.slots:
            s.start()
        self._collector.start()
        self._dispatcher.start()

    def child_config(self, index: int) -> ChildConfig:
        b = self.w._b
        return ChildConfig(b, index, self.ring.name, b._proc_ring_slots,
                           b._proc_slot_bytes)

    def restart_count(self, index: int) -> int:
        return self.w._restart_counts[index]

    def respawn_slot(self, index: int) -> _ProcWorkerSlot:
        """Supervisor restart: the dead slot's un-drained ring slots are
        reclaimed (the process is joined-dead, it cannot be mid-read) and
        a fresh process takes the index.  Held-run redelivery stays the
        supervisor's job, same as thread mode."""
        schedcheck.point("proc.pool.respawn")
        old = self.slots[index]
        for ring_idx in old.drain_unfreed_slots():
            self._recycle_slot(ring_idx)
        old.work_q.close()
        # bank the dead child's final telemetry counters (and clear the
        # cell for the successor) BEFORE the heartbeat clear: merged
        # scrape totals stay monotonic across restarts, and the dead
        # cell can never poison a later scrape
        self.w._bank_child_telemetry(index)
        # a child killed MID-IO leaves pending=1 in its heartbeat cell;
        # left stale, the watchdog would age it through the replacement's
        # spawn import and condemn the healthy newborn
        self.ring.hb_clear(index)
        fresh = _ProcWorkerSlot(self, index)
        self.slots[index] = fresh
        return fresh

    def healthy(self) -> bool:
        return (self._dispatcher.is_alive() and self._collector.is_alive()
                and not self._closed)

    def close(self, timeout: float = 10.0) -> None:
        """Stop dispatch FIRST (no new units), then the writer closes each
        slot (poison/join), then the collector drains and the ring is
        unlinked via :meth:`finalize`."""
        self._stop.set()
        self._dispatcher.join(timeout=timeout)

    def finalize(self, timeout: float = 5.0) -> None:
        self._closed = True
        self._collector.join(timeout=timeout)
        # bank every child's final counters before the views go away so
        # post-close stats()/scrapes keep the tree's lifetime totals
        for s in self.slots:
            self.w._bank_child_telemetry(s.index)
        self.ring.close()
        self.ring.unlink()

    def _recycle_slot(self, ring_idx: int) -> None:
        """THE re-entry point to the ring free pool — every recycler
        (collector free ack, respawn reclaim, dispatch backout) routes
        through here so the schedule explorer's double-recycle probe
        guards all of them: a slot entering the pool while already free
        is the PR-11 double-free, whichever interleaving produced it."""
        schedcheck.note_slot_recycled(self._pool_key, ring_idx)
        self._free.put(ring_idx)

    def backout_undispatched(self, slot: _ProcWorkerSlot,
                             parts: frozenset) -> int:
        """Revocation met a unit still sitting un-dispatched in the ring
        (staged, ledger'd, never handed to the child): back it out whole.
        The runs release with the ledger entry (the new owner reads them
        from the committed frontier — sending now would double-write),
        and the ring slot recycles through the probed single re-entry
        point: the collector's ``free`` handling for the same slot is the
        racing party, the cross-process analog of the PR-11 stale-free/
        respawn double recycle."""
        # schedule-explorer edge, BEFORE the ledger pop: the collector's
        # ``free`` handling for a unit of the same child races this
        # back-out — a shape that takes entries the dispatcher already
        # committed to sending (or the child already freed) recycles the
        # same ring slot twice, and the probe in _recycle_slot catches it
        schedcheck.point("proc.revoke.backout")
        backed = slot.backout_units(parts)
        for ring_idx in backed:
            self._recycle_slot(ring_idx)
        if backed:
            rec = getattr(self.w, "_flightrec", None)
            if rec is not None:
                rec.note("rebalance_backout", worker=slot.index,
                         units=len(backed))
        return len(backed)

    def redeliver_async(self, runs, label: str) -> None:
        """Redeliver abandoned runs off the collector thread (the
        consumer's redeliver path can block on a full queue and drops
        revoked/unassigned partitions itself — the retained-vs-revoked
        filter lives there, same as thread mode)."""
        if not runs:
            return
        t = threading.Thread(
            target=self._redeliver_runs, args=(list(runs),),
            name=f"KPW-proc-redeliver-{label}", daemon=True)
        t.start()

    def _redeliver_runs(self, runs) -> None:
        for p, s, e in runs:
            try:
                self.w.consumer.redeliver_run(p, s, e - s,
                                              stop_event=self._stop)
            except Exception:
                logger.exception("proc redelivery of %s failed", (p, s, e))

    # -- stats ------------------------------------------------------------------
    def ring_free(self) -> int:
        return self._free.qsize()

    def snapshot(self) -> dict:
        return {
            "workers": self.n_workers,
            "ring": {"slots": self.ring.slots,
                     "slot_bytes": self.ring.slot_bytes,
                     "free": self.ring_free(),
                     "shm_name": self.ring.name},
            "dispatched_units": self.dispatched_units,
            "acked_units": self.acked_units,
            "inflight_units": sum(s.inflight_units() for s in self.slots),
            "children": [{"worker": s.index, "pid": s.pid,
                          "alive": s.alive(),
                          "rss_bytes": s.rss_bytes(),
                          "inflight_units": s.inflight_units(),
                          "restarts": self.restart_count(s.index)}
                         for s in self.slots],
        }

    # -- dispatcher --------------------------------------------------------------
    def _dispatch_loop(self) -> None:
        try:
            # startup barrier: hold the first dispatch until every child
            # reported ready — spawn costs ~1-2 s of interpreter import,
            # and dispatching meanwhile would drain the backlog through
            # the first child alone (skewing short replays and bunching
            # every early unit's redelivery risk on one process)
            while (not self._stop.is_set()
                   and any(not s.ready and not s.failed
                           for s in self.slots)):
                time.sleep(0.01)
            while not self._stop.is_set():
                items, _runs = self.w.consumer.poll_many_batches(
                    self._poll_cap())
                if not items:
                    time.sleep(0.001)
                    continue
                with stage("worker.proc.dispatch"):
                    if not self._dispatch_round(items):
                        return  # shutting down mid-round
        except RetryInterrupted:
            pass  # close() interrupted a dead-letter retry
        except Exception:
            logger.exception("proc dispatcher died; process workers "
                             "starve (writer unhealthy)")

    def _poll_cap(self) -> int:
        # drain up to a few slots' worth per poll round at the ~64 B/rec
        # cfg6 shape; split-to-fit handles anything fatter per unit
        return max(256, 2 * self.ring.max_records_for(64.0))

    def _normalize_item(self, item):
        """One queue chunk -> (partition, start, offsets, payload,
        exact_runs).  ``exact_runs`` is None for an offset-contiguous
        chunk (the run is derivable as one (partition, start, count));
        a gapped Record list (compacted topic) carries its exact
        per-record runs instead.  Returns None for an empty chunk."""
        if isinstance(item, RecordBatch):
            if len(item) == 0:
                return None
            return (item.partition, item.start_offset,
                    np.ascontiguousarray(item.offsets, np.int64),
                    item.payload, None)
        if not item:
            return None
        blob = b"".join(r.value for r in item)
        lens = np.fromiter((len(r.value) for r in item), np.int64,
                           count=len(item))
        offs = np.zeros(len(item) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        contiguous = item[-1].offset - item[0].offset == len(item) - 1
        exact = (None if contiguous
                 else [(r.partition, r.offset, r.offset + 1)
                       for r in item])
        return item[0].partition, item[0].offset, offs, blob, exact

    def _dispatch_round(self, items) -> bool:
        """Dispatch one poll round: offset-contiguous chunks of the same
        partition PACK into shared ring slots (merged offsets table, one
        staging memcpy each) so unit size tracks slot capacity rather
        than broker fetch granularity — with small fetches, one-unit-per-
        fetch made the per-unit fixed costs (queue messages, flush
        checks, ack round trips) the child's throughput ceiling.  Gapped
        chunks dispatch alone with exact per-record runs; oversized
        chunks split to fit.  Returns False when shutdown interrupted
        the round (the remainder stays tracked-but-unacked: redelivered
        to the next instance — the thread-mode close contract)."""
        packs: dict[int, dict] = {}
        for item in items:
            norm = self._normalize_item(item)
            if norm is None:
                continue
            partition, start, offs, payload, exact_runs = norm
            count = len(offs) - 1
            nbytes = int(offs[-1] - offs[0])
            if exact_runs is not None:
                # gapped: flush the partition's pack (order!), go alone
                if not self._flush_pack(packs.pop(partition, None)):
                    return False
                if not self._dispatch_split(partition, start, offs,
                                            payload, exact_runs):
                    return False
                continue
            pack = packs.get(partition)
            if pack is not None and (
                    pack["end"] != start
                    or not self.ring.fits(pack["count"] + count,
                                          pack["bytes"] + nbytes)):
                if not self._flush_pack(packs.pop(partition)):
                    return False
                pack = None
            if pack is None:
                if not self.ring.fits(count, nbytes):
                    if not self._dispatch_split(partition, start, offs,
                                                payload, None):
                        return False
                    continue
                packs[partition] = {
                    "partition": partition, "start": start,
                    "end": start + count, "count": count,
                    "bytes": nbytes, "parts": [(offs, payload)]}
            else:
                pack["parts"].append((offs, payload))
                pack["count"] += count
                pack["bytes"] += nbytes
                pack["end"] = start + count
        for pack in packs.values():
            if not self._flush_pack(pack):
                return False
        return True

    def _flush_pack(self, pack) -> bool:
        if pack is None:
            return True
        runs = [(pack["partition"], pack["start"],
                 pack["start"] + pack["count"])]
        return self._dispatch_unit(pack["partition"], pack["start"],
                                   pack["parts"], pack["count"],
                                   pack["bytes"], runs)

    def _dispatch_split(self, partition: int, start: int,
                        offs: np.ndarray, payload, exact_runs) -> bool:
        """Split one chunk across as many slots as its bytes need.  A
        gapped chunk (``exact_runs``) dispatches one record per unit so
        the child's ``start_offset + i`` offset arithmetic (dead-letter
        frame labels) stays exact — gapped batches are the rare
        compacted-topic shape, never the hot path."""
        n = len(offs) - 1
        pos = 0
        while pos < n:
            if exact_runs is not None:
                take = 1
            else:
                avg = max(1.0, float(offs[-1] - offs[0]) / n)
                take = min(n - pos, self.ring.max_records_for(avg))
                while take > 1 and not self.ring.fits(
                        take, int(offs[pos + take] - offs[pos])):
                    take = max(1, take // 2)
            rec_off = (start + pos if exact_runs is None
                       else exact_runs[pos][1])
            if take == 1 and not self.ring.fits(
                    1, int(offs[pos + 1] - offs[pos])):
                # a single record wider than a ring slot can never cross
                # the handoff: a poison pill at the DISPATCH layer — the
                # on_parse_error policy decides, exactly like a child-side
                # unparseable record (the first cut raised out of the
                # dispatcher thread, killing ingestion forever)
                if not self._handle_oversized(partition, rec_off,
                                              offs, payload, pos):
                    return False
                pos += 1
                continue
            sub_offs = offs[pos: pos + take + 1]
            if exact_runs is None:
                runs = [(partition, start + pos, start + pos + take)]
            else:
                runs = exact_runs[pos: pos + take]
            nbytes = int(sub_offs[-1] - sub_offs[0])
            if not self._dispatch_unit(partition, rec_off,
                                       [(sub_offs, payload)], take,
                                       nbytes, runs):
                return False
            pos += take
        return True

    def _handle_oversized(self, partition: int, offset: int,
                          offs: np.ndarray, payload, pos: int) -> bool:
        """One record too wide for any ring slot, resolved under the
        ``on_parse_error`` policy in the parent (the record cannot reach
        a child): ``raise`` kills the dispatcher — the process-mode
        analog of the reference poison pill killing the worker, visible
        via ``healthy()`` — while ``skip``/``dead_letter`` ack the
        single offset (after durable dead-letter append) and move on."""
        from ..ingest.offsets import PartitionOffset

        policy = self.w._b._on_parse_error
        nbytes = int(offs[pos + 1] - offs[pos])
        if policy == "raise":
            raise ValueError(
                f"record {partition}/{offset} ({nbytes} B) exceeds the "
                f"shared-memory slot capacity ({self.ring.slot_bytes} B); "
                f"raise process_workers(slot_bytes=...) or use "
                f"on_parse_error='skip'/'dead_letter'")
        logger.error(
            "%s oversized record %d/%d (%d B > slot capacity %d B)",
            "dead-lettering" if policy == "dead_letter" else "skipping",
            partition, offset, nbytes, self.ring.slot_bytes)
        if policy == "dead_letter":
            raw = bytes(memoryview(payload)[int(offs[pos]):
                                            int(offs[pos + 1])])
            b = self.w._b
            d = f"{self.w.target_dir}/deadletter"
            frame = struct.pack("<iqI", partition, offset, len(raw)) + raw

            def append() -> None:
                self.w.fs.mkdirs(d)
                with self.w.fs.open_append(
                        f"{d}/{b._instance_name}_dispatch.bin") as f:
                    f.write(frame)

            self.w.retry_policy.call(append, stop_event=self._stop,
                                     label="dead_letter")
        self.w.consumer.ack(PartitionOffset(partition, offset))
        return True

    def _dispatch_unit(self, partition: int, start_offset: int, parts,
                       count: int, nbytes: int, runs) -> bool:
        """Stage one unit (one or more contiguous windows) into a free
        slot and hand it to a child; ``runs`` are [start, end) tuples."""
        slot_idx = self._get_free_slot()
        if slot_idx is None:
            return False
        schedcheck.point("proc.ring.stage")
        # ack-latency anchor: the oldest covered batch's ingest
        # wall-time rides the descriptor (0 when the consumer has no
        # stamp for this run — e.g. records enqueued pre-upgrade)
        ing = self.w.consumer.ingest_stamp(partition, start_offset)
        self.ring.write_slot_parts(slot_idx, partition, start_offset,
                                   parts,
                                   ingest_us=int(ing * 1e6) if ing else 0)
        target = self._pick_child()
        if target is None:
            self._recycle_slot(slot_idx)
            return False
        self._seq += 1
        seq = self._seq
        target.note_dispatch(seq, [tuple(r) for r in runs], count, nbytes,
                             slot_idx)
        # commit-to-send under the ledger lock: a rebalance listener
        # backing out revoked un-sent units races this exact window, and
        # sending a unit whose ledger entry (and ring slot) were just
        # reclaimed would publish rows the new owner also redelivers
        if not target.mark_sent(seq):
            return not self._stop.is_set()
        try:
            target.work_q.put(("unit", seq, slot_idx))
        except (OSError, ValueError):
            # the child died between pick and put: the ledger entry makes
            # the runs redeliverable through the supervisor path
            return not self._stop.is_set()
        self.dispatched_units += 1
        if partition in target._fence_flush:
            # a batch buffered before the revoke dispatched AFTER the
            # fence descriptor: re-send it so work-queue FIFO flushes
            # this late unit inside the drain window too
            target._send_revoke(frozenset({partition}), "flush")
        return True

    def _get_free_slot(self):
        while not self._stop.is_set():
            try:
                idx = self._free.get(timeout=0.1)
            except pyqueue.Empty:
                continue
            schedcheck.note_slot_taken(self._pool_key, idx)
            return idx
        return None

    def _pick_child(self):
        """Round-robin over live, un-failed children with inflight
        headroom; blocks (stop-aware) while everyone is saturated —
        this, plus the bounded ring, is the process-mode backpressure."""
        while not self._stop.is_set():
            for k in range(len(self.slots)):
                s = self.slots[(self._rr + k) % len(self.slots)]
                if (not s.failed and not s._poisoned and s.alive()
                        and s.inflight_units() < self._max_inflight):
                    self._rr = (self._rr + k + 1) % len(self.slots)
                    return s
            time.sleep(0.002)
        return None

    # -- collector ---------------------------------------------------------------
    def _collect_loop(self) -> None:
        try:
            last_monitor = time.monotonic()
            while True:
                try:
                    msg = self.ack_q.get(timeout=0.2)
                except pyqueue.Empty:
                    if self._closed:
                        return
                    msg = None
                # liveness is TIME-based, not idle-based: under sustained
                # ack traffic from surviving children the queue never goes
                # Empty, and an OOM-killed child (no death notice) would
                # otherwise hold its unacked runs forever
                now = time.monotonic()
                if now - last_monitor >= 0.2:
                    last_monitor = now
                    self._monitor_liveness()
                if msg is not None:
                    self._handle(msg)
        except Exception:
            logger.exception("proc collector died; acks stop flowing "
                             "(writer unhealthy)")

    def _handle(self, msg) -> None:
        kind = msg[0]
        if kind == "free":
            _, widx, ring_idx, seq = msg
            schedcheck.point("proc.collector.free")
            count, nbytes = self.slots[widx].note_free(seq)
            if count:
                self.w._written_records.mark(count)
                self.w._written_bytes.mark(nbytes)
                # recycle ONLY when the ledger entry existed: a stale
                # "free" from a dead child's last breath arrives after
                # respawn_slot already reclaimed its un-drained slots,
                # and honoring it would double-free the ring slot (two
                # concurrent units staged into the same memory)
                self._recycle_slot(ring_idx)
        elif kind == "published":
            _, widx, seqs, file_info, retry_stats = msg
            slot = self.slots[widx]
            slot.retries, slot.backoff_s, slot.last_error = retry_stats
            acked_runs = 0
            fenced = False
            fenced_runs: list = []
            with stage("worker.proc.ack"):
                for seq in seqs:
                    runs, was_fenced = slot.peek_unit(seq)
                    fenced |= was_fenced
                    for p, s, e in runs:
                        try:
                            self.w.consumer.ack_run(p, s, e - s)
                            acked_runs += 1
                        except StaleGenerationError:
                            # the broker fenced this commit: the child
                            # published across a generation bump (zombie
                            # shape) — resolved below, never fatal to
                            # the collector
                            fenced = True
                            fenced_runs.append((p, s, e))
                    # settle strictly AFTER the commits (peek/settle
                    # split): see peek_unit
                    slot.settle_unit(seq)
                    self.acked_units += 1
            if fenced:
                self.w._fenced_acks.mark()
            if file_info is not None:
                if fenced and acked_runs == 0:
                    # nothing under the file committed: un-publish it
                    # (exactly-once restored — the rows ride the
                    # committed frontier to the new owner / redelivery),
                    # the proc-mode mirror of _fenced_ack_cleanup
                    self._fenced_unpublish(widx, file_info, fenced_runs)
                    return
                if fenced:
                    rec = getattr(self.w, "_flightrec", None)
                    if rec is not None:
                        rec.note("rebalance_fenced_ack_dropped",
                                 worker=widx, mode="proc",
                                 runs=fenced_runs)
                slot._published_files += 1
                self.w._flushed_records.mark(file_info["records"])
                self.w._flushed_bytes.mark(file_info["size"])
                self.w._file_size_histogram.update(file_info["size"])
                if file_info.get("verified"):
                    self.w._verified.mark()
                reason = file_info["reason"]
                if reason == "revoke":
                    self.w._rotated_revoke.mark()
                    rec = getattr(self.w, "_flightrec", None)
                    if rec is not None:
                        rec.note("rebalance_child_drained", worker=widx,
                                 records=file_info["records"])
                else:
                    (self.w._rotated_time if reason == "time"
                     else self.w._rotated_size).mark()
                info = file_info.get("index") or {}
                if info.get("pages_indexed"):
                    self.w._indexed.mark()
                if info.get("bloom_bytes"):
                    self.w._bloom_bytes_meter.mark(info["bloom_bytes"])
                asm = file_info.get("assembly") or {}
                if asm.get("native_chunks"):
                    self.w._native_asm_chunks.mark(asm["native_chunks"])
                    self.w._native_asm_pages.mark(asm["native_pages"])
        elif kind == "abandoned":
            # the child dropped its open file on a revoke-abandon: settle
            # every covered unit and redeliver what this member RETAINS
            # (redeliver_run drops revoked/unassigned partitions itself);
            # revoked runs were force-released at request_abandon and ride
            # the committed frontier to the new owner
            _, widx, seqs = msg
            slot = self.slots[widx]
            runs: list = []
            for seq in seqs:
                rs, _was_fenced = slot.settle_unit(seq)
                runs.extend(rs)
            self.w._fence_abandons.mark()
            rec = getattr(self.w, "_flightrec", None)
            if rec is not None:
                rec.note("rebalance_child_abandoned", worker=widx,
                         units=len(seqs), retained_runs=len(runs))
            self.redeliver_async(runs, f"abandon-{widx}")
        elif kind == "died":
            _, widx, pid, reason = msg
            schedcheck.point("proc.collector.died")
            slot = self.slots[widx]
            # pid-check: a delayed death notice from the PREVIOUS
            # occupant of this index must not condemn its replacement
            acted = (slot.pid == pid and not slot.failed
                     and not slot.condemned)
            schedcheck.note_death_notice(slot.pid, pid, acted)
            if acted:
                slot.exit_reason = reason
                slot.failed = True
                self.w._failed.mark()
                self.w._notify_worker_death(widx, reason)
        elif kind == "verify_failed":
            # the child quarantined its tmp and is about to die un-acked
            # (redelivery); the parent owns the meters
            self.w._verify_failed.mark()
            self.w._quarantined.mark()
        elif kind == "telemetry":
            # the low-rate side channel: a full child snapshot (counter
            # dict + stage summary + drained span buffer) — absorbed
            # into the merged trace and stats()['telemetry']
            _, widx, payload = msg
            self.w._absorb_child_telemetry(widx, payload)
        elif kind == "ready":
            _, widx, pid = msg
            self.slots[widx].pid = pid
            self.slots[widx].ready = True
        elif kind == "closed":
            pass  # clean poison exit; close() already joins

    def _fenced_unpublish(self, widx: int, file_info: dict,
                          fenced_runs) -> None:
        """A child's publish crossed a generation fence and NOTHING under
        the file committed: delete the just-renamed dest so the tree
        stays exactly-once (the new owner republishes the same rows from
        the committed frontier), and redeliver any retained runs whose
        ack the fence rejected.  Parent and child share the local tree —
        proc mode pins the filesystem — so the parent can un-publish."""
        dest = file_info.get("dest")
        if dest:
            try:
                self.w.fs.delete(dest)
            except OSError:
                logger.exception("fenced un-publish of %s failed "
                                 "(duplicate rows possible)", dest)
        rec = getattr(self.w, "_flightrec", None)
        if rec is not None:
            rec.note("rebalance_fenced_unpublish", worker=widx,
                     dest=dest, records=file_info.get("records"))
        self.redeliver_async(fenced_runs, f"fence-{widx}")

    def _monitor_liveness(self) -> None:
        """A SIGKILLed child sends no death notice — poll exit codes so
        the supervisor still wakes (the process analog of a thread's
        silent death being visible via ``alive()``)."""
        if self._stop.is_set():
            return
        for s in self.slots:
            if (not s.failed and not s._poisoned and s.pid is not None
                    and not s.alive()):
                s.exit_reason = (f"process exited rc="
                                 f"{s._proc.exitcode}")
                s.failed = True
                self.w._failed.mark()
                self.w._notify_worker_death(s.index, s.exit_reason)
