"""Fluent, validated Builder — the framework's L5 public config surface.

Setter-for-setter parity with the reference Builder (KafkaProtoParquetWriter.
java:450-749) including defaults, the 100 KiB max-file-size floor (:453,564),
required-field validation (:729-733), and the offset-tracker open-page
auto-derivation / equation check (:735-746).  Deliberate divergences, per
SURVEY.md §5: `max_file_size=0` is rejected loudly (the reference's javadoc
falsely promises "no limit"), and the parquet page size defaults to 1 MiB
rather than inheriting the 128 MiB block size (a reference quirk).
"""

from __future__ import annotations

import math
import os
import socket

from ..core.compression import codec_from_name
from ..core.writer import WriterProperties
from ..io.fs import FileSystem, LocalFileSystem
from ..io.objectstore import ObjectStoreFileSystem

MIN_MAX_FILE_SIZE = 100 * 1024  # reference MIN_MAX_FILE_SIZE (KPW.java:453)


class Builder:
    def __init__(self) -> None:
        # required
        self._broker = None
        self._topic: str | None = None
        self._proto_class = None
        self._parser = None
        self._target_dir: str | None = None
        # defaults mirror KPW.java:455-490
        self._instance_name = f"{socket.gethostname()}-{os.getpid()}"
        self._thread_count = 1
        self._max_file_open_duration = 900.0  # seconds (:461)
        self._max_file_size = 1 << 30  # 1 GiB (:462)
        self._max_expected_throughput = 300_000  # records/s (:463)
        self._offset_tracker_page_size = 300_000  # (:466)
        self._offset_tracker_max_open_pages: int | None = None  # derived (:735-746)
        self._max_queued_records = 100_000  # (:468)
        self._fetch_max_records = 2000  # per broker fetch (seed when autotuned)
        # batch-native ingest: RecordBatch handoff broker -> queue -> wire
        # shredder (contiguous buffer + offsets, no per-record objects);
        # engages automatically when the broker offers fetch_batch AND the
        # wire fast path is live, else the per-record Record route runs
        self._batch_ingest = True
        # backpressure autotuning: derive fetch size / queue depth / poll
        # batch from measured stage rates (off = reference's fixed knobs)
        self._autotune = False
        self._block_size = 128 * 1024 * 1024  # (:473)
        self._page_size = 1024 * 1024  # sane default; NOT the reference quirk
        self._codec = 0  # UNCOMPRESSED (:484)
        self._compression_level: int | None = None  # codec default
        self._consumer_config: dict | None = None  # KPW.java:627-631 analog
        self._filesystem_config: dict | None = None  # KPW.java:662-666 analog
        self._enable_dictionary = True  # (:489)
        self._delta_fallback = False  # BASELINE config 3 opt-in (legacy)
        # adaptive per-column encodings (core/select_encoding.py):
        # stats-driven chooser pinned per file + explicit override map
        self._adaptive_encodings = False
        self._encodings: dict | None = None
        self._encoder_threads = 0  # native column-parallel encode (0 = auto)
        self._page_checksums = False  # parquet-mr 1.10 parity: no page CRCs
        # query-ready files (core/index.py): PARQUET-922 page indexes on
        # by default (parquet-mr 1.11 parity), bloom filters + sort-order
        # declarations opt-in
        self._page_index = True
        self._native_assembly = True  # nogil page assembly (native builds)
        self._bloom_columns: tuple | None = None
        self._bloom_fpp = 0.01
        self._bloom_max_bytes = 128 * 1024
        self._sorting_columns: tuple = ()
        # reference default yyyyMMdd-HHmmssSSS (:486-487): %3f is this
        # framework's millisecond token (strftime has none; %f would be
        # 6-digit microseconds and change the file-name shape)
        self._file_date_time_pattern = "%Y%m%d-%H%M%S%3f"
        self._directory_date_time_pattern: str | None = None
        self._file_extension = ".parquet"  # (:488)
        self._group_id: str | None = None
        self._metric_registry = None
        self._filesystem: FileSystem | None = None
        self._backend = "cpu"
        # 3-stage ingest/encode/flush overlap; None = auto (on for
        # multicore hosts, inline when there is only one core to share —
        # thread hand-offs between stages then cost ~5-10% and add
        # run-to-run variance instead of overlapping anything)
        self._pipeline: bool | None = None
        self._batch_size = 4096
        self._on_parse_error = "raise"  # parity: poison pill kills the worker
        self._clean_abandoned_tmp = False  # opt-in tmp GC at start()
        # robustness: IO retry policy (None = default RetryPolicy — infinite
        # attempts, backoff+jitter, fatal-errno classification) and opt-in
        # worker supervision (the reference never restarts a dead worker)
        self._retry_policy = None
        self._supervise = False
        self._max_worker_restarts = 5
        self._restart_backoff = 0.1  # seconds; doubles per restart, cap 5 s
        # degraded operation (all opt-in; the reference has no answer to a
        # hung write, a full-then-cleared disk, or an unbounded close):
        # hung-IO watchdog, and fatal-errno pause/resume
        self._watchdog = False
        self._io_stall_deadline = 30.0
        self._watchdog_poll: float | None = None  # derived from the deadline
        self._abandon_stalled = False
        self._degraded_mode = False
        self._pause_probe_interval = 0.5
        self._pause_probe_max = 5.0
        self._max_pause: float | None = None  # None = pause indefinitely
        # durability: crash-consistent publish (fsync-before-rename +
        # dir-fsync) and independent structural verification.  All off by
        # default — fsync costs real milliseconds per publish (measured in
        # bench.py --crash) and the reference never fsyncs
        self._durable_publish = False
        self._verify_on_publish = False
        self._verify_on_startup = False
        # observability: span-timeline tracing (utils/tracing.py).  Off by
        # default — the disabled stage() path is a true no-op
        self._tracing = False
        self._trace_span_capacity = 65536
        self._trace_path: str | None = None
        # crash flight recorder (runtime/telemetry.py): bounded black box
        # of fault-path events, dumped as one JSON post-mortem on watchdog
        # kills, fatal-sink pauses, and poison quarantines.  ON by default
        # — it costs nothing until a fault path actually fires
        self._flightrec = True
        self._flightrec_dir: str | None = None  # None = <target_dir>
        # partitioned output (opt-in; the reference emits one flat stream):
        # record -> relative partition dir ahead of file assignment, with a
        # bound on concurrently open partition files per worker (LRU
        # close-and-publish eviction past it)
        self._partitioner = None
        self._max_open_partitions = 8
        # small-file compaction service (opt-in): background merge of
        # published under-size files into ~target-size files (io/compact.py)
        self._compaction: dict | None = None
        # process-parallel workers (opt-in): N spawned worker subprocesses
        # fed batches zero-copy through a shared-memory ring
        # (runtime/procworkers.py); 0 = thread workers (thread_count)
        self._proc_workers = 0
        self._proc_ring_slots = 16
        self._proc_slot_bytes = 1 << 20
        self._proc_max_inflight = 8
        # multi-tenant routes (runtime/multiwriter.py): route() specs;
        # build() returns a MultiWriter when any exist.  _queue_listener
        # is the consumer's queue-occupancy seam the MultiWriter wires
        # per route (the shared quota ledger's charge/credit source).
        self._routes: list[dict] = []
        self._queue_listener = None
        # consumer-group cooperative rebalance (ingest/broker.py group
        # coordination): how long a revocation may wait for in-flight
        # files holding revoked partitions' rows to flush+publish+ack
        # before the consumer confirms the handoff anyway (the abandoned
        # rows redeliver through the new owner — at-least-once either way)
        self._rebalance_drain_deadline = 5.0

    # -- required ----------------------------------------------------------
    def broker(self, broker) -> "Builder":
        """Record source: a FakeBroker or any object with the same interface
        (the reference requires `consumerConfig`; the broker client carries
        that role here)."""
        self._broker = broker
        return self

    def topic(self, topic: str) -> "Builder":
        self._topic = topic
        return self

    def proto_class(self, cls) -> "Builder":
        self._proto_class = cls
        return self

    def parser(self, fn) -> "Builder":
        """bytes -> message.  Defaults to proto_class.FromString."""
        self._parser = fn
        return self

    def target_dir(self, path: str) -> "Builder":
        self._target_dir = path
        return self

    # -- identity / scale --------------------------------------------------
    def instance_name(self, name: str) -> "Builder":
        self._instance_name = name
        return self

    def thread_count(self, n: int) -> "Builder":
        self._thread_count = n
        return self

    def group_id(self, gid: str) -> "Builder":
        self._group_id = gid
        return self

    def rebalance_drain_deadline_seconds(self, seconds: float) -> "Builder":
        """Cooperative-rebalance drain bound: how long a revocation may
        wait for this instance's in-flight files holding revoked
        partitions' rows to flush, publish and ack before the consumer
        confirms the handoff anyway (the still-open rows are then
        abandoned un-acked and redeliver through the new owner —
        at-least-once either way).  Only meaningful against a broker
        running group coordination (``FakeBroker(session_timeout_s=...)``
        or a real cluster)."""
        if seconds <= 0:
            raise ValueError("rebalance drain deadline must be > 0 "
                             f"(got {seconds})")
        self._rebalance_drain_deadline = seconds
        return self

    # -- rotation ----------------------------------------------------------
    def max_file_open_duration_seconds(self, seconds: float) -> "Builder":
        self._max_file_open_duration = seconds
        return self

    def max_file_size(self, nbytes: int) -> "Builder":
        self._max_file_size = nbytes
        return self

    # -- consumer sizing ---------------------------------------------------
    def max_expected_throughput_per_second(self, rps: int) -> "Builder":
        self._max_expected_throughput = rps
        return self

    def offset_tracker_page_size(self, n: int) -> "Builder":
        self._offset_tracker_page_size = n
        return self

    def offset_tracker_max_open_pages_per_partition(self, n: int) -> "Builder":
        self._offset_tracker_max_open_pages = n
        return self

    def max_queued_records_in_consumer(self, n: int) -> "Builder":
        self._max_queued_records = n
        return self

    def fetch_max_records(self, n: int) -> "Builder":
        """Records per broker fetch round (the reference's fetch sizing is
        Kafka client config; here it is explicit).  With :meth:`autotune`
        this is only the seed — the live value follows the measured drain
        rate."""
        if n < 1:
            raise ValueError("fetch_max_records must be >= 1")
        self._fetch_max_records = n
        return self

    def batch_ingest(self, flag: bool) -> "Builder":
        """Batch-native zero-copy ingest (default ON): the consumer fetches
        ``RecordBatch`` pages (one contiguous payload buffer + offset
        table per fetch, no per-record ``Record`` construction), the
        bounded queue carries them intact, acks ride their (partition,
        start, count) runs, and the wire shredder consumes buffer+offsets
        directly.  Requires a batch-capable broker (``fetch_batch``) and
        the wire fast path; anything else silently rides the per-record
        compatibility route, which also remains the poison-pill fallback.
        Pin False to force the per-record ``Record`` path everywhere
        (byte-identical output — pinned by test_batch_ingest)."""
        self._batch_ingest = flag
        return self

    def autotune(self, flag: bool = True) -> "Builder":
        """Backpressure autotuning (default OFF — reference parity is the
        fixed constants): derive the ingest knobs from measured stage
        rates instead of ``fetch_max_records`` / ``max_queued_records`` /
        ``batch_size`` as configured.  The fetcher sizes each fetch to
        ~50 ms of the queue's measured drain rate and the queue bound to
        ~0.5 s of it (never above the configured ``max_queued_records`` —
        that stays a hard ceiling); each worker sizes its poll batch to
        ~50 ms of its own measured shred+append rate, still clipped by
        the rotation-overshoot cap.  Tuned values and the rates that
        produced them are surfaced in ``stats()['consumer']['autotune']``
        and per-worker ``poll_batch``/``proc_rate_rps``."""
        self._autotune = flag
        return self

    # -- parquet properties ------------------------------------------------
    def block_size(self, nbytes: int) -> "Builder":
        self._block_size = nbytes
        return self

    def page_size(self, nbytes: int) -> "Builder":
        self._page_size = nbytes
        return self

    def compression(self, codec) -> "Builder":
        """name ('snappy', 'zstd', 'gzip', 'uncompressed') or Codec value."""
        self._codec = codec_from_name(codec)
        return self

    def compression_level(self, level: int | None) -> "Builder":
        """Codec compression level for level-capable codecs (zstd -22..22,
        default 3; gzip 0-9, default 6).  None = codec default.  Setting a
        level with snappy/uncompressed is rejected at build() (those codecs
        have no level knob; a silently-ignored setting would mask a config
        mistake) — parity with parquet-mr's codec-level configuration
        surface."""
        self._compression_level = level  # validated against the codec in build()
        return self

    def enable_dictionary(self, flag: bool) -> "Builder":
        self._enable_dictionary = flag
        return self

    def page_checksums(self, flag: bool) -> "Builder":
        """Write the optional CRC-32 field (gzip polynomial, PARQUET-1539)
        in every page header so readers that verify checksums (e.g. pyarrow
        page_checksum_verification) detect torn/corrupt pages.  Off by
        default — parity with parquet-mr 1.10, which doesn't write page
        CRCs."""
        self._page_checksums = flag
        return self

    def page_index(self, flag: bool) -> "Builder":
        """Emit PARQUET-922 ColumnIndex/OffsetIndex sections (per-page
        min/max/null-count + page locations, ``core/index.py``) in every
        published file, so selective readers prune pages without reading
        them.  ON by default (parquet-mr 1.11 parity); off restores the
        exact pre-index file bytes."""
        self._page_index = flag
        return self

    def native_assembly(self, flag: bool) -> "Builder":
        """Nogil batch page assembly (native/src/assemble.cc): the native
        and TPU backends lower each chunk's resolved page plan to flat
        tables and assemble (gather + RLE + compress + CRC + page stats)
        in ONE GIL-released native call per column, so the shared assembly
        pool and worker threads scale across real cores.  ON by default
        wherever the extension loads and the codec is covered
        (uncompressed / snappy / zstd); ``False`` opts out, restoring the
        pure-Python page loops byte-identically (the output file bytes are
        pinned equal either way)."""
        self._native_assembly = flag
        return self

    def bloom_filters(self, columns=(), *, fpp: float = 0.01,
                      max_bytes: int = 128 * 1024) -> "Builder":
        """Split-block bloom filters (parquet SBBF, xxhash64) per column
        chunk.  ``columns=()`` (the default when called) auto-selects
        string columns plus any column whose chunk dictionary-encoded —
        the dictionary build's exact distinct set makes population a
        k-hash pass; a tuple of field names pins the set; ``None``
        disables (the Builder default).  ``fpp`` sizes the filter
        (parquet-mr's bits formula), ``max_bytes`` caps it (rounded down
        to a power of two).  Off by default: filters cost file bytes and
        the reference writes none."""
        if columns is not None:
            if isinstance(columns, str):
                columns = (columns,)
            columns = tuple(columns)
            if not 0.0 < fpp < 1.0:
                raise ValueError("fpp must be in (0, 1)")
            if max_bytes < 32:
                raise ValueError("max_bytes must be >= 32")
        self._bloom_columns = columns
        self._bloom_fpp = fpp
        self._bloom_max_bytes = max_bytes
        return self

    def sort_order(self, *columns, descending: bool = False,
                   nulls_first: bool = False) -> "Builder":
        """Declare ``sorting_columns`` row-group metadata: every published
        row group claims its rows are ordered by these schema leaves (in
        the given precedence).  A DECLARATION, not a sort — the writer
        streams records in arrival order, so use this when the upstream
        feed is ordered (or let sort-on-compact, ``io/compact.py``,
        physically sort and declare on merge).  The structural verifier
        cross-checks the declaration against the page index's boundary
        order, so a false claim fails verify-on-publish instead of
        poisoning downstream readers."""
        if not columns:
            raise ValueError("sort_order needs at least one column name")
        self._sorting_columns = tuple(
            (c, descending, nulls_first) for c in columns)
        return self

    def delta_fallback(self, flag: bool) -> "Builder":
        """Use DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY instead of
        PLAIN when a column's dictionary is rejected (high cardinality).

        LEGACY SPELLING: since the adaptive-encoding chooser landed
        (core/select_encoding.py) this is a forced per-type override rule
        inside it, kept for back-compat (same bytes as before).  Prefer
        :meth:`encodings` — ``adaptive=True`` for the stats-driven
        chooser, or an explicit per-column map."""
        self._delta_fallback = flag
        return self

    def encodings(self, mapping: dict | None = None, *,
                  adaptive: bool | None = None) -> "Builder":
        """Per-column value encodings (core/select_encoding.py).

        ``mapping`` pins columns explicitly: ``{column_name_or_dotted_path:
        Encoding-or-name}`` — e.g. ``{"price": "byte_stream_split",
        "seq": Encoding.DELTA_BINARY_PACKED}``.  A pinned column skips the
        dictionary attempt entirely.  ``adaptive=True`` turns on the
        stats-driven chooser for everything else: the first row group's
        observed stats (cardinality, delta width, value width, null
        density) pick among PLAIN / dictionary / DELTA_BINARY_PACKED /
        DELTA_LENGTH_BYTE_ARRAY / BYTE_STREAM_SPLIT, and the decision is
        pinned for the rest of the file (reader coherence).  Encoding
        values validate here; column names validate against the proto
        schema at :meth:`build` (like sort_order / bloom_filters)."""
        if mapping is not None:
            from ..core.select_encoding import _normalize_overrides

            mapping = _normalize_overrides(mapping)  # raises on bad values
        self._encodings = mapping
        if adaptive is not None:
            self._adaptive_encodings = bool(adaptive)
        return self

    def encoder_threads(self, n: int) -> "Builder":
        """Column-parallel encode threads in the native backend per worker
        (0 = one per core, 1 = sequential).  Orthogonal to thread_count,
        which parallelizes across files like the reference."""
        if n < 0:
            raise ValueError("encoder_threads must be >= 0")
        self._encoder_threads = n
        return self

    # -- naming / placement ------------------------------------------------
    def file_date_time_pattern(self, strftime_pattern: str) -> "Builder":
        """strftime pattern for the published file-name timestamp; ``%3f``
        expands to zero-padded milliseconds (the reference's ``SSS``,
        KPW.java:486-487 — plain strftime has no millisecond token)."""
        self._file_date_time_pattern = strftime_pattern
        return self

    def directory_date_time_pattern(self, strftime_pattern: str | None) -> "Builder":
        self._directory_date_time_pattern = strftime_pattern
        return self

    def file_extension(self, ext: str) -> "Builder":
        self._file_extension = ext
        return self

    # -- pass-through config maps (KPW.java:627-631, :662-666) --------------
    def consumer_config(self, config: dict) -> "Builder":
        """Raw Kafka consumer config map, pass-through parity with the
        reference's ``consumerConfig`` (KafkaProtoParquetWriter.java:627-631).
        When no ``broker()`` is supplied, ``build()`` constructs a real
        ``KafkaBrokerClient`` from it — ``bootstrap.servers`` (or
        ``bootstrap_servers``) is then required; every other key is handed to
        the kafka-python consumer verbatim (dotted Kafka names are translated
        to kafka-python's underscore kwargs)."""
        self._consumer_config = dict(config)
        return self

    def filesystem_config(self, config: dict) -> "Builder":
        """Raw filesystem config map, pass-through parity with the
        reference's ``hadoopConf`` (KafkaProtoParquetWriter.java:662-666).
        When no ``filesystem()`` is supplied, ``build()`` resolves the sink
        from ``fs.defaultFS`` exactly like the reference (KPW.java:137-141):
        ``hdfs://host:port`` -> HdfsFileSystem (remaining keys passed as
        libhdfs extra_conf), ``file://`` or absent -> LocalFileSystem."""
        self._filesystem_config = dict(config)
        return self

    # -- plumbing ----------------------------------------------------------
    def metric_registry(self, registry) -> "Builder":
        self._metric_registry = registry
        return self

    def filesystem(self, fs: FileSystem) -> "Builder":
        self._filesystem = fs
        return self

    def object_store(self, store, bucket: str = "kpw", *,
                     part_size: int = 8 * 1024 * 1024,
                     pipeline_uploads: bool = True,
                     spill_threshold_bytes: int | None = None) -> "Builder":
        """Publish to an S3/GCS-class object store (``io/objectstore.py``):
        the sink becomes an :class:`~kpw_tpu.io.objectstore.
        ObjectStoreFileSystem` over ``store``/``bucket``, whose atomic
        publish is multipart-complete instead of ``durable_rename`` (the
        capability seam — no rename, no fsync on an object store).
        Encoded row groups stream to the store as ``part_size`` parts
        *while each file is still open* (``pipeline_uploads``; upload
        hides under encode — overlap surfaced in
        ``stats()['objectstore']``), so closing a file costs one tail
        part and the publish is one ``complete`` call.  Request/byte
        accounting and the observed-bandwidth gauge ride the canonical
        ``parquet.writer.objstore.*`` names.  ``spill_threshold_bytes``
        bounds each write handle's retained buffer: past it the retained
        file bytes roll to an anonymous local tmp file (seek-back
        re-upload and close-time re-ship stay byte-perfect), so memory
        stays bounded at GiB-rotation scale."""
        self._filesystem = ObjectStoreFileSystem(
            store, bucket, part_size=part_size,
            pipeline_uploads=pipeline_uploads,
            spill_threshold_bytes=spill_threshold_bytes)
        return self

    def encoder_backend(self, backend) -> "Builder":
        """'cpu' | 'native' | 'tpu' | 'auto' | 'mesh' (multi-chip
        mesh-global dictionary merge, parallel/mesh_encoder.py), or an
        object with encode(chunk, offset)."""
        self._backend = backend
        return self

    def batch_size(self, n: int) -> "Builder":
        self._batch_size = n
        return self

    def pipeline(self, flag: bool) -> "Builder":
        """Overlap ingest/shred, row-group encode, and IO in three stages
        per worker (SURVEY.md §2.4 pipeline parallelism — the reference's
        hot loop is serial).  Default is automatic: on when the host has
        more than one core, inline on single-core hosts (the stages then
        contend for the one core instead of overlapping).  Set explicitly
        to pin either mode."""
        self._pipeline = flag
        return self

    def retry_policy(self, policy) -> "Builder":
        """IO retry policy for every write-path seam (worker flush/close/
        publish/dead-letter, consumer fetch/commit).  Default: infinite
        attempts with exponential backoff + decorrelated jitter and
        fatal-by-default classification of non-transient errnos (ENOSPC /
        EROFS / EDQUOT kill the worker instead of spinning).  Pass
        ``RetryPolicy.reference()`` to restore the reference's pure
        fixed-100ms retry-everything loop, or a bounded policy
        (``max_attempts`` / ``deadline``) to cap the spin."""
        from .retry import RetryPolicy

        if policy is not None and not isinstance(policy, RetryPolicy):
            raise TypeError("retry_policy expects a RetryPolicy instance")
        self._retry_policy = policy
        return self

    def supervise(self, flag: bool = True, max_restarts: int = 5,
                  restart_backoff_seconds: float = 0.1) -> "Builder":
        """Supervised worker recovery: detect a dead worker, re-inject its
        never-acked offsets into the shared queue, and restart it — up to
        ``max_restarts`` times per worker slot with exponential backoff
        starting at ``restart_backoff_seconds``.  Redelivery-by-restart
        preserves at-least-once (the dead worker's records were never
        acked).  When every worker is dead with its budget exhausted the
        writer is terminally failed and ``close()`` raises
        ``WriterFailedError``.  Off by default (reference parity: a dead
        worker stays dead until process restart — but death is still
        visible via ``healthy()`` / ``stats()`` / the failed meter)."""
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if restart_backoff_seconds < 0:
            raise ValueError("restart_backoff_seconds must be >= 0")
        self._supervise = flag
        self._max_worker_restarts = max_restarts
        self._restart_backoff = restart_backoff_seconds
        return self

    def watchdog(self, flag: bool = True, *,
                 io_stall_deadline_seconds: float = 30.0,
                 poll_interval_seconds: float | None = None,
                 abandon_stalled: bool = False) -> "Builder":
        """Hung-IO watchdog (``runtime/watchdog.py``): workers and the
        pipelined row-group IO thread publish a progress heartbeat around
        every IO seam, and a supervisor-owned scanner flags any worker
        whose oldest in-flight IO op is older than
        ``io_stall_deadline_seconds`` — storage that HANGS rather than
        errors is otherwise invisible (no errno, no dead thread, no retry
        fires).  A stall flips ``healthy()`` false, marks the
        ``parquet.writer.stalled`` meter once per episode, and surfaces
        per-worker stall age + seam label in ``stats()``.

        With ``abandon_stalled=True`` the stalled worker is condemned:
        declared failed while its thread is still parked in the hung call,
        so the PR-3 supervisor (``Builder.supervise`` — required for the
        restart half) restarts the slot and re-injects the held un-acked
        offset runs.  Redelivery preserves at-least-once; the stuck tmp is
        left un-published and swept on the next start.  An abandon
        consumes a supervisor restart, never a retry budget — the hung
        call never returned, so the policy never saw an attempt fail.  A
        *progressing* retry loop (attempts returning, backoff between
        them) re-stamps the heartbeat and is never treated as a hang.
        Off by default: zero threads, zero heartbeat cost beyond a dict
        store per IO call."""
        if io_stall_deadline_seconds <= 0:
            raise ValueError("io_stall_deadline_seconds must be positive")
        if (poll_interval_seconds is not None
                and poll_interval_seconds <= 0):
            raise ValueError("poll_interval_seconds must be positive")
        self._watchdog = flag
        self._io_stall_deadline = io_stall_deadline_seconds
        self._watchdog_poll = poll_interval_seconds
        self._abandon_stalled = abandon_stalled
        return self

    def degraded_mode(self, flag: bool = True, *,
                      probe_interval_seconds: float = 0.5,
                      probe_backoff_max_seconds: float = 5.0,
                      max_pause_seconds: float | None = None) -> "Builder":
        """Fatal-errno pause/resume: a worker hitting a fatal-classified
        errno (ENOSPC/EROFS/EDQUOT — conditions a restart cannot fix but
        an operator or time often does) PAUSES instead of dying.  The open
        file is abandoned un-acked, intake stops (the shared queue fills,
        the fetcher blocks on the bounded put — backpressure reaches the
        broker session without dropping it), and a probe loop retests the
        sink with exponential backoff (``probe_interval_seconds`` →
        ``probe_backoff_max_seconds``).  On a successful probe the worker
        re-injects its held offset runs (redelivery — the records were
        never acked) and resumes cleanly.  Pause cause/age land in
        ``stats()['degraded']`` and the ``parquet.writer.paused`` gauge
        counts paused workers.  ``max_pause_seconds`` bounds the wait:
        past it the pause converts into the normal fatal worker death
        (supervision/terminal semantics take over).  Off by default —
        reference parity is fatal-errno death, which burns the supervisor
        restart budget on a condition restarting cannot fix."""
        if probe_interval_seconds <= 0:
            raise ValueError("probe_interval_seconds must be positive")
        if probe_backoff_max_seconds < probe_interval_seconds:
            raise ValueError("probe_backoff_max_seconds must be >= "
                             "probe_interval_seconds")
        if max_pause_seconds is not None and max_pause_seconds <= 0:
            raise ValueError("max_pause_seconds must be positive")
        self._degraded_mode = flag
        self._pause_probe_interval = probe_interval_seconds
        self._pause_probe_max = probe_backoff_max_seconds
        self._max_pause = max_pause_seconds
        return self

    def durability(self, fsync: bool = True, *,
                   verify_on_publish: bool = False,
                   verify_on_startup: bool = False) -> "Builder":
        """Crash-consistency discipline for the publish protocol, three
        independent opt-ins (all default off — each costs time on the hot
        rotation path, measured by ``bench.py --crash``):

        * ``fsync`` — publish via durable rename: fsync the tmp file
          BEFORE the atomic rename, fsync the destination directory AFTER
          (``FileSystem.durable_rename``).  Without it a published-then-
          acked file can vanish in a power cut (the rename lived only in
          the page cache) — a plain process ``kill -9`` is already safe
          either way, because the page cache survives process death and
          the ack happens after rename returns.
        * ``verify_on_publish`` — run the independent structural verifier
          (``kpw_tpu.io.verify``) over the closed tmp file before the
          rename.  A file that fails is moved to
          ``{target_dir}/quarantine/`` (never published, never deleted)
          and the worker dies un-acked, so the records are redelivered —
          a corrupt encode can then never be acked.
        * ``verify_on_startup`` — ``start()`` verifies every published
          ``.parquet`` under the target dir and quarantines structural
          failures (torn finals from a previous crash) before new work
          begins; the sweep's manifest lands in ``stats()['recovery']``.
        """
        self._durable_publish = fsync
        self._verify_on_publish = verify_on_publish
        self._verify_on_startup = verify_on_startup
        return self

    def clean_abandoned_tmp(self, flag: bool) -> "Builder":
        """Delete this instance's stale .tmp files at start() (crash
        leftovers the reference never GCs, SURVEY.md §3.5).  Off by default:
        only safe when at most one live writer uses this instance name."""
        self._clean_abandoned_tmp = flag
        return self

    def tracing(self, flag: bool = True,
                span_capacity: int = 65536) -> "Builder":
        """Record per-stage spans while the writer runs: start() installs a
        process-wide StageTimer + SpanRecorder (a bounded ring buffer of
        ``span_capacity`` spans, oldest evicted first) that every
        ``stage(...)`` site feeds; close() uninstalls them.  Read the
        results via ``writer.stats()`` (cumulative stage timers) and
        ``writer.write_trace(path)`` (Chrome/Perfetto timeline JSON).
        Process-wide: two concurrently-started tracing writers would share
        one recorder — enable it on the writer under investigation."""
        self._tracing = flag
        if span_capacity <= 0:
            raise ValueError("span_capacity must be positive")
        self._trace_span_capacity = span_capacity
        return self

    def trace_path(self, path: str | None) -> "Builder":
        """Write the span timeline as Chrome-trace JSON to ``path`` at
        close().  Implies :meth:`tracing`."""
        self._trace_path = path
        if path:
            self._tracing = True
        return self

    def flight_recorder(self, flag: bool = True, *,
                        path: str | None = None) -> "Builder":
        """The crash black box (``runtime/telemetry.py``): a bounded ring
        of fault-path events (stalls, pauses, quarantines, child deaths)
        dumped as one JSON post-mortem — naming the trigger and the
        stalled stage — when the watchdog kills a hung worker, a worker
        pauses on a fatal sink condition, or a file is quarantined.  ON
        by default (zero cost until a fault fires); ``path`` overrides
        the dump directory (default ``<target_dir>/flightrec/`` on the
        LOCAL filesystem — a black box that publishes through the
        possibly-failing sink would lose exactly the crashes it exists
        to explain)."""
        self._flightrec = flag
        self._flightrec_dir = path
        return self

    def partition_by(self, spec, *, time_pattern: str | None = None,
                     time_unit: str = "s",
                     max_open_partitions: int = 8) -> "Builder":
        """Hive-style partitioned output: route each record into a
        partition subdirectory of the target dir ahead of file
        assignment, with per-partition open files and per-partition
        size/time rotation accounting (``runtime/partition.py``).

        ``spec`` is one of:

        * a protobuf **field name** (or tuple of them) — Hive
          ``{field}={value}`` segments from the parsed message; with
          ``time_pattern`` the single named field is instead read as an
          epoch (``time_unit``: ``s``/``ms``/``us``) and bucketed through
          the strftime pattern in UTC (e.g. ``"dt=%Y%m%d/hour=%H"``),
        * a **callable** ``(record, message) -> relative_path``,
        * a prebuilt :class:`~kpw_tpu.runtime.partition.Partitioner`.

        ``max_open_partitions`` bounds the partition files each worker
        holds open at once; routing to a new partition past the bound
        closes-and-publishes the least-recently-written one (metered as
        ``parquet.writer.partitions.evicted``).  Ack granularity becomes
        the checkpoint: offsets commit when every open partition file has
        published (at the latest, each ``max_file_open_duration_seconds``
        — a record's file must be durable before its offset is acked, and
        one poll batch scatters across partitions).  A partitioner that
        raises is handled under the :meth:`on_parse_error` policy.
        Partitioning disqualifies the wire-shred fast path (routing needs
        the parsed message)."""
        from .partition import EventTimePartitioner, make_partitioner

        if max_open_partitions < 1:
            raise ValueError("max_open_partitions must be >= 1")
        if time_pattern is not None:
            if not isinstance(spec, str):
                raise ValueError("time_pattern needs a single epoch field "
                                 "name as the partition spec")
            self._partitioner = EventTimePartitioner(
                spec, pattern=time_pattern, unit=time_unit)
        else:
            self._partitioner = make_partitioner(spec)
        self._max_open_partitions = max_open_partitions
        return self

    def compaction(self, target_size: int, *,
                   scan_interval_seconds: float = 5.0,
                   min_files: int = 2,
                   small_file_ratio: float = 0.5,
                   sort_by=None,
                   bandwidth_bytes_per_s: float | None = None,
                   request_budget_per_round: int | None = None,
                   partition_quota: int | None = None) -> "Builder":
        """Background small-file compaction (``kpw_tpu.io.compact``):
        start() launches a :class:`~kpw_tpu.io.compact.Compactor` over the
        target dir that merges published files smaller than
        ``small_file_ratio * target_size`` (per partition directory, name
        order, >= ``min_files`` per merge) into ~``target_size`` outputs —
        rewritten through the writer's own encode machinery, structurally
        verified BEFORE the ``durable_rename`` publish, inputs then
        retired into the ``compacted/`` tombstone tree (moved, never
        deleted) so a kill -9 at any instant leaves every row in at least
        one verified published file.  ``sort_by`` (a proto field name, or
        ``(field, descending)``) turns on sort-on-compact: merged outputs
        are physically re-sorted by the field and declare
        ``sorting_columns`` row-group metadata, verified against the page
        index's boundary order before publish — streaming output acquires
        its reader-exploitable sort order here, in the background tier.
        Stats land in ``stats()['compactor']``; meters are
        ``parquet.compactor.merged|retired|failed``.  Off by default —
        compaction is a second read+write of every small byte, a cost the
        flat reference never pays.

        The REMOTE tier (object-store targets): ``bandwidth_bytes_per_s``
        throttles merge reads and merge-output writes through one shared
        token bucket so the compactor's traffic stays under the budget;
        ``request_budget_per_round`` defers further merges once a round
        issued that many filesystem requests (per-request cost control);
        ``partition_quota`` caps merges per partition directory per round
        (per-partition fairness).  All None by default (local tier)."""
        if target_size <= 0:
            raise ValueError("target_size must be positive")
        if scan_interval_seconds <= 0:
            raise ValueError("scan_interval_seconds must be positive")
        if min_files < 2:
            raise ValueError("min_files must be >= 2")
        if not 0.0 < small_file_ratio <= 1.0:
            raise ValueError("small_file_ratio must be in (0, 1]")
        if bandwidth_bytes_per_s is not None and bandwidth_bytes_per_s <= 0:
            raise ValueError("bandwidth_bytes_per_s must be positive")
        if (request_budget_per_round is not None
                and request_budget_per_round < 1):
            raise ValueError("request_budget_per_round must be >= 1")
        if partition_quota is not None and partition_quota < 1:
            raise ValueError("partition_quota must be >= 1")
        self._compaction = {
            "target_size": target_size,
            "scan_interval_s": scan_interval_seconds,
            "min_files": min_files,
            "small_file_ratio": small_file_ratio,
            "sort_by": sort_by,
            "bandwidth_bytes_per_s": bandwidth_bytes_per_s,
            "request_budget_per_round": request_budget_per_round,
            "partition_quota": partition_quota,
        }
        return self

    def process_workers(self, n: int, *, ring_slots: int = 16,
                        slot_bytes: int = 1 << 20,
                        max_inflight_units: int = 8) -> "Builder":
        """Process-parallel workers (``runtime/procworkers.py``): run the
        shred → encode → assemble → publish leg in ``n`` **spawned**
        subprocesses instead of ``thread_count`` threads, escaping the
        single-interpreter GIL ceiling.  Batches cross the process
        boundary zero-copy through a ``multiprocessing.shared_memory``
        ring of ``ring_slots`` × ``slot_bytes`` batch slots (parent
        stages the poll batch with one memcpy — the same single copy the
        thread path pays out of the broker log — and the child shreds the
        slot's buffer in place); offsets stay tracked and acked in the
        parent, committed only on the child's published-file
        acknowledgment, so at-least-once is unchanged.  The supervisor
        (``supervise``), watchdog (``watchdog`` — a condemned child is
        SIGKILLed and its slot restarted) and ``stats()`` operate on
        process slots exactly as on threads; per-child rss / ring
        occupancy / restart counts land in ``stats()['procs']``.

        ``max_inflight_units`` bounds un-acked dispatched units per child
        (bounds redelivery work after a kill).  Constraints (validated at
        ``build()``): spawn start method only (fork with live jax threads
        deadlocks), a ``LocalFileSystem`` sink, a protobuf message class
        (children rebuild it from its descriptor), no ``partition_by``,
        and a cpu/native/auto encoder backend.  ``n=0`` restores thread
        workers."""
        if n < 0:
            raise ValueError("process_workers must be >= 0")
        if ring_slots < 2:
            raise ValueError("ring_slots must be >= 2")
        if slot_bytes < 4096:
            raise ValueError("slot_bytes must be >= 4096")
        if max_inflight_units < 1:
            raise ValueError("max_inflight_units must be >= 1")
        self._proc_workers = n
        self._proc_ring_slots = ring_slots
        self._proc_slot_bytes = slot_bytes
        self._proc_max_inflight = max_inflight_units
        return self

    def route(self, topic: str, proto_class, target_dir: str, *,
              name: str | None = None, queue_quota: int | None = None,
              open_file_budget: int | None = None,
              ack_sla_seconds: float | None = None,
              **overrides) -> "Builder":
        """Declare one multi-tenant route (``runtime/multiwriter.py``):
        a (topic, proto, target_dir) triple that shares this builder's
        broker session, encoder pool and compaction service with every
        other route but lives in its own BULKHEAD — its own workers,
        consumer queue, ack frontier and fault domain.  With any route
        declared, ``build()`` returns a
        :class:`~kpw_tpu.runtime.multiwriter.MultiWriter` instead of a
        single writer (the base builder's ``topic``/``proto_class``/
        ``target_dir`` are then unused).

        * ``name`` — the tenant name (defaults to the topic); keys the
          per-tenant stats/quota/status surfaces.
        * ``queue_quota`` — this tenant's queue share: the records it
          may hold in its consumer queue before its OWN fetch gate
          parks (backpressure on the offender, never drop; stall
          episodes metered as ``parquet.writer.tenant.queue.stalls``).
        * ``open_file_budget`` — the PR-8 LRU bound generalized across
          the route's workers: at the budget, opening one more
          partition file first closes-and-publishes the route's own LRU
          open file (``parquet.writer.tenant.files.evicted``).
        * ``ack_sla_seconds`` — the route's declared ack-lag SLA,
          surfaced (and checked live as ``sla_violated``) in
          ``stats()['tenants']`` — the observable ``bench.py --tenants``
          proves noisy neighbors cannot violate.
        * ``**overrides`` — any Builder setter by name, applied to this
          route's cloned builder: a scalar for one-argument setters
          (``thread_count=2``, ``on_parse_error="dead_letter"``), a
          tuple for positional args, a dict for keyword args
          (``durability={"fsync": False, "verify_on_publish": True}``).
        """
        for key in overrides:
            setter = getattr(Builder, key, None)
            if not callable(setter):
                raise ValueError(
                    f"route override {key!r} is not a Builder setter")
        if queue_quota is not None and queue_quota < 1:
            raise ValueError("queue_quota must be >= 1")
        if open_file_budget is not None and open_file_budget < 1:
            raise ValueError("open_file_budget must be >= 1")
        if ack_sla_seconds is not None and ack_sla_seconds <= 0:
            raise ValueError("ack_sla_seconds must be positive")
        rname = name or topic
        if any(r["name"] == rname for r in self._routes):
            raise ValueError(f"duplicate route name {rname!r}")
        self._routes.append({
            "name": rname,
            "topic": topic,
            "proto_class": proto_class,
            "target_dir": target_dir,
            "queue_quota": queue_quota,
            "open_file_budget": open_file_budget,
            "ack_sla_seconds": ack_sla_seconds,
            "overrides": dict(overrides),
        })
        return self

    def on_parse_error(self, policy: str) -> "Builder":
        """'raise' (reference parity: poison pill kills the worker,
        KPW.java:271-275), 'skip' (log + ack), or 'dead_letter' (raw payload
        appended to targetDir/deadletter/{instance}_{worker}.bin, then
        ack)."""
        if policy not in ("raise", "skip", "dead_letter"):
            raise ValueError(
                "on_parse_error must be 'raise', 'skip' or 'dead_letter'")
        self._on_parse_error = policy
        return self

    # -- build -------------------------------------------------------------
    def _broker_from_consumer_config(self):
        """Construct a real KafkaBrokerClient from the pass-through map
        (the reference builds its consumer from consumerConfig the same way,
        KPW.java:153-163)."""
        cfg = {k.replace(".", "_"): v for k, v in self._consumer_config.items()}
        servers = cfg.pop("bootstrap_servers", None)
        if servers is None:
            raise ValueError(
                "consumer_config needs 'bootstrap.servers' when no broker() "
                "is supplied")
        # group.id in the map names the consumer group (KPW.java:158 only
        # defaults it when absent) — route it to the writer's group id, which
        # is what join_group hands the Kafka client; a conflicting explicit
        # group_id() is a config error, not a silent override
        cfg_group = cfg.pop("group_id", None)
        if cfg_group is not None:
            if self._group_id is not None and self._group_id != cfg_group:
                raise ValueError(
                    f"conflicting consumer groups: group_id({self._group_id!r})"
                    f" vs consumer_config group.id {cfg_group!r}")
            self._group_id = cfg_group
        from ..ingest.kafka_client import KafkaBrokerClient

        return KafkaBrokerClient(servers, client_config=cfg)

    def _filesystem_from_config(self):
        """Resolve the sink from fs.defaultFS (KPW.java:137-141 parity)."""
        cfg = dict(self._filesystem_config)
        default_fs = cfg.pop("fs.defaultFS", cfg.pop("fs_defaultFS", ""))
        if default_fs.startswith("hdfs://"):
            from urllib.parse import urlparse

            from ..io.hdfs import HdfsFileSystem

            u = urlparse(default_fs)
            return HdfsFileSystem(host=u.hostname or "default",
                                  port=u.port or 8020,
                                  extra_conf=cfg or None)
        if default_fs and not default_fs.startswith("file://"):
            raise ValueError(f"unsupported fs.defaultFS scheme: {default_fs}")
        return LocalFileSystem()

    def build(self):
        if self._broker is None and self._consumer_config is not None:
            self._broker = self._broker_from_consumer_config()
        if self._routes:
            # multi-tenant mode: the MultiWriter clones this builder per
            # route (topic/proto/target applied there) and shares the
            # broker session, encoder pool and compaction service
            from .multiwriter import MultiWriter

            return MultiWriter(self)
        if self._filesystem is None and self._filesystem_config is not None:
            self._filesystem = self._filesystem_from_config()
        # required fields (reference :729-733)
        missing = [name for name, v in [
            ("broker", self._broker),
            ("topic", self._topic),
            ("proto_class", self._proto_class),
            ("target_dir", self._target_dir),
        ] if v is None]
        if missing:
            raise ValueError(f"missing required builder fields: {missing}")
        if self._compression_level is not None:
            from ..core.schema import Codec

            lo, hi = {Codec.GZIP: (0, 9), Codec.ZSTD: (-22, 22)}.get(
                self._codec, (None, None))
            if lo is None:
                raise ValueError(
                    "compression_level is only meaningful for gzip/zstd "
                    f"(codec={self._codec})")
            if not lo <= self._compression_level <= hi:
                raise ValueError(
                    f"compression_level {self._compression_level} outside "
                    f"[{lo}, {hi}] for this codec")
        if self._max_file_size < MIN_MAX_FILE_SIZE:
            raise ValueError(
                f"max_file_size must be >= {MIN_MAX_FILE_SIZE} bytes "
                f"(got {self._max_file_size})")
        if self._pipeline is None:
            # auto: stage overlap needs a second core to overlap onto —
            # counted from the process's affinity mask (cgroup/taskset
            # limits), not the host's physical core count
            try:
                avail = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                avail = os.cpu_count() or 1
            self._pipeline = avail > 1
        if self._thread_count < 1:
            raise ValueError("thread_count must be >= 1")
        # offset tracker sizing (reference :735-746): open pages must cover
        # max_throughput * max_open_duration outstanding offsets
        need = self._max_expected_throughput * self._max_file_open_duration
        if self._offset_tracker_max_open_pages is None:
            self._offset_tracker_max_open_pages = max(
                1, math.ceil(need / self._offset_tracker_page_size))
        elif (self._offset_tracker_max_open_pages
              * self._offset_tracker_page_size) < need:
            raise ValueError(
                "offset_tracker_max_open_pages_per_partition * page_size must "
                "cover max_expected_throughput * max_file_open_duration "
                f"({self._offset_tracker_max_open_pages} * "
                f"{self._offset_tracker_page_size} < {int(need)})")
        # a custom parser (envelope stripping, transforms) disqualifies the
        # wire-shred fast path: the raw payload is then NOT the message
        # bytes.  Passing the class's own FromString/parser explicitly IS
        # the default parse (README quickstart does exactly that), so it
        # keeps the fast path — ~4x streaming throughput.
        # identity-based: never invokes a user callable's __eq__ (a loose
        # or raising __eq__ must not silently flip the fast path)
        self._parser_is_default = (
            self._parser is None
            or (getattr(self._parser, "__self__", None)
                is self._proto_class
                and getattr(self._parser, "__name__", None) == "FromString"))
        if self._parser is None:
            self._parser = self._proto_class.FromString
        # resolve sort/bloom column names against the proto schema HERE:
        # ParquetFileWriter._resolve_sorting would otherwise first raise
        # inside every worker's background file-open (a supervised
        # restart storm, not a config error), and a misspelled pinned
        # bloom column would silently never match any chunk
        if self._sorting_columns or self._bloom_columns or self._encodings:
            from ..models.proto_bridge import proto_to_schema

            cols = proto_to_schema(self._proto_class).columns
            names = {c.name for c in cols} | {
                ".".join(c.path) for c in cols}
            for name, _, _ in (self._sorting_columns or ()):
                if name not in names:
                    raise ValueError(
                        f"sort_order column {name!r} is not a schema "
                        f"leaf (have {sorted(names)})")
            for name in (self._bloom_columns or ()):
                if name not in names:
                    raise ValueError(
                        f"bloom_filters column {name!r} is not a schema "
                        f"leaf (have {sorted(names)})")
            for name in (self._encodings or ()):
                if name not in names:
                    raise ValueError(
                        f"encodings column {name!r} is not a schema "
                        f"leaf (have {sorted(names)})")
        if self._group_id is None:
            # reference default group id pattern (KPW.java:158)
            self._group_id = f"KafkaProtoParquetWriter-{self._instance_name}"
        if self._filesystem is None:
            self._filesystem = LocalFileSystem()
        if self._proc_workers:
            # process mode crosses an interpreter boundary: everything a
            # child needs must be reconstructible from picklable config.
            # Fail here, at build(), not inside a spawned child.
            if isinstance(self._filesystem, ObjectStoreFileSystem):
                raise ValueError(
                    "process_workers does not support an object-store "
                    "target yet: the multipart upload handle (the staged "
                    "pending uploads + part-uploader thread) lives in the "
                    "parent's adapter and cannot cross the spawn boundary "
                    "— each child would need its own upload session per "
                    "file.  Use thread workers for object-store sinks.")
            if type(self._filesystem) is not LocalFileSystem:
                raise ValueError(
                    "process_workers requires a plain LocalFileSystem sink "
                    "(children open their own file handles; in-memory and "
                    "composite filesystems do not cross a process boundary)")
            if self._partitioner is not None:
                raise ValueError(
                    "process_workers does not support partition_by yet "
                    "(routing needs the parsed message in the parent)")
            if self._backend not in (None, "cpu", "native", "auto"):
                raise ValueError(
                    f"process_workers supports cpu/native/auto encoder "
                    f"backends, not {self._backend!r}")
            if not self._parser_is_default:
                raise ValueError(
                    "process_workers does not support a custom parser(): "
                    "spawned children decode payloads with the wire "
                    "shredder / proto_class.FromString, so a transforming "
                    "parser would be silently ignored")
            from .procworkers import _proto_spec

            _proto_spec(self._proto_class)  # raises if not descriptor-backed
            # a coordinated broker (session_timeout_s set) is SUPPORTED in
            # process mode: the parent owns the group membership and
            # heartbeat (children never talk to the broker) and forwards
            # revocations across the ring as `revoke` fence descriptors —
            # see runtime/procworkers.py.  The rejections above still
            # apply under coordination (a custom parser, object-store or
            # composite sinks, partition_by all stay unsupported in proc
            # mode, coordinated or not).

        from .writer import KafkaProtoParquetWriter

        return KafkaProtoParquetWriter(self)

    def writer_properties(self) -> WriterProperties:
        return WriterProperties(
            row_group_size=self._block_size,
            data_page_size=self._page_size,
            codec=self._codec,
            compression_level=self._compression_level,
            enable_dictionary=self._enable_dictionary,
            delta_fallback=self._delta_fallback,
            adaptive_encodings=self._adaptive_encodings,
            encodings=self._encodings,
            encoder_threads=self._encoder_threads,
            page_checksums=self._page_checksums,
            write_page_index=self._page_index,
            native_assembly=self._native_assembly,
            bloom_columns=self._bloom_columns,
            bloom_fpp=self._bloom_fpp,
            bloom_max_bytes=self._bloom_max_bytes,
            sorting_columns=self._sorting_columns,
        )
