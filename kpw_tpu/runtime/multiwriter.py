"""Multi-tenant writer service: N (topic, proto, target) routes sharing
one broker session, one encoder/assembly pool, and one compaction
service — isolated by per-tenant BULKHEADS.

ROADMAP's top open item: millions of users means many producers with
*different* protos, where the failure mode that matters is a noisy
neighbor, not a dead disk.  ``Builder.route(topic, proto_class,
target_dir, **overrides)`` called N times builds a :class:`MultiWriter`
instead of a single :class:`~kpw_tpu.runtime.writer.
KafkaProtoParquetWriter`; each route is a full writer (its own workers,
consumer queue, offset tracker, ack frontier, target tree) wired into
three SHARED seams:

* **one broker session** — every route's consumer fetches through a
  :class:`_TenantBrokerView` over one shared broker client
  (``_SharedBrokerSession``), so the framework holds one connection
  however many topics it drains (group fan-in: one consumer group, N
  topic memberships);
* **one encoder pool** — the native assembly/encode pool is process-wide
  already (``core/writer.py`` shares its ``assemble_many`` executor per
  encoder options), so routes contend for cores through one pool instead
  of N oversubscribed ones;
* **one compaction service** — :class:`_SharedCompactionService` drives
  every route's Compactor from ONE background thread (round-robin, per
  route cadence preserved) with an optionally SHARED bandwidth budget,
  so background rewrite traffic cannot multiply per tenant.

The BULKHEADS:

* **Per-tenant quotas** (:class:`TenantQuotaLedger`): each route gets a
  queue share (records it may hold in its consumer queue, charged at the
  fetcher's enqueue and credited at worker drain through the consumer's
  ``queue_listener`` seam) and an open-file budget (the PR-8 LRU bound
  generalized across the route's workers).  Enforcement is
  BACKPRESSURE-ON-THE-OFFENDER, never drop: a tenant at its queue share
  parks its own fetch gate (``tenant.quota.wait`` stage, stall episodes
  metered as ``parquet.writer.tenant.queue.stalls``) while sibling
  fetchers proceed; a tenant at its file budget closes-and-publishes its
  own LRU open file (``parquet.writer.tenant.files.evicted``) before
  opening another.  The ledger's per-tenant counters and its global
  total are updated under one lock with a schedcheck preemption point
  between them and an invariant probe (``note_quota_ledger``) at every
  charge/credit — a torn multi-route update raises with both stacks.
* **Per-tenant fault domains**: a route whose sink fails pauses or dies
  ALONE (its own retry policy / degraded-mode pause / supervisor — the
  PR-4/5 seams, instantiated per route); a poison stream dead-letters or
  kills only its own route's workers; a schema turned incompatible
  dead-letters the whole route with a typed reason
  (:class:`SchemaIncompatibleError`) — and in every case sibling routes
  keep their workers, their ack cadence, and their quota headroom
  (proven by ``bench.py --tenants`` from the committed containment
  counters).
* **Per-tenant observability**: ``stats()['tenants'][name]`` carries
  each route's ack-lag, worker liveness, quota snapshot, dead-letter
  count and typed status; the canonical tenant-layer meters/gauges
  (``runtime/metrics.py``) render in both generic exporters with no
  per-metric wiring.
* **Schema evolution, the way parquet readers expect**: at ``start()``
  each route's proto schema is diffed against its published tree
  (``io/verify.py`` ``file_schema``).  Additive fields (new columns) are
  the expected shape — merged-schema reads stay consistent, the
  cross-file audit (``audit_schema_consistency``) reports them without
  flagging; an INCOMPATIBLE change (one dotted leaf path, two physical
  types) flips the route to ``dead_lettering``: every record lands in
  the route's dead-letter file (then acks — the stream keeps draining,
  nothing is lost, nothing poisons the tree) and the typed reason is
  surfaced in the route's status.
"""

from __future__ import annotations

import copy
import logging
import threading
import time

from ..utils import schedcheck
from ..utils.tracing import stage
from . import metrics as M

logger = logging.getLogger(__name__)


class SchemaIncompatibleError(TypeError):
    """A route's proto schema conflicts with its already-published tree
    (one dotted leaf path carrying two physical types): new files would
    break merged-schema readers, so the route dead-letters instead of
    writing.  Deliberately a TypeError subclass, not OSError — the
    bytes are wrong for this tree, and no IO retry can fix that."""


class TenantQuotaLedger:
    """The shared-session quota ledger: per-tenant queue occupancy +
    open-file budgets, with backpressure-on-the-offender enforcement.

    Charges ride the consumer's ``queue_listener`` seam (``on_enqueued``
    under the buffer condition, per admitted slice) and credits ride the
    drain (``on_drained``); the fetch gate (:meth:`wait_turn`) parks the
    offending tenant's fetcher while it is at its share.  Per-tenant
    counters and the global total are updated under ONE lock with a
    schedcheck preemption point between the two writes and the
    ``note_quota_ledger`` invariant probe after them — the torn-update
    bug class is mechanized, not hoped away.  Lock ordering: callers may
    hold their consumer's buffer condition when charging/crediting; the
    ledger only ever takes its own lock (and the meters' leaf locks), so
    the graph stays acyclic."""

    def __init__(self, registry=None) -> None:
        self._cv = threading.Condition()
        self._queued: dict[str, int] = {}
        self._queued_total = 0
        self._quota: dict[str, int | None] = {}
        self._file_budget: dict[str, int | None] = {}
        self._open_files_fn: dict[str, object] = {}
        self._stalls: dict[str, int] = {}
        self._stall_s: dict[str, float] = {}
        self._closed = False
        self._m_stalls = (registry.meter(M.TENANT_QUEUE_STALLS_METER)
                          if registry else M.Meter())
        self._m_stall_ms = (registry.meter(M.TENANT_QUEUE_STALL_MS_METER)
                            if registry else M.Meter())

    def register(self, tenant: str, queue_quota: int | None = None,
                 file_budget: int | None = None,
                 open_files_fn=None) -> None:
        """Declare a tenant's shares.  ``queue_quota`` bounds the records
        it may hold in its consumer queue (None = unquotaed);
        ``file_budget`` bounds its concurrently open partition files
        across workers, counted live through ``open_files_fn`` (a
        zero-arg callable — no incr/decr bookkeeping to drift)."""
        if queue_quota is not None and queue_quota < 1:
            raise ValueError("queue_quota must be >= 1")
        if file_budget is not None and file_budget < 1:
            raise ValueError("open_file_budget must be >= 1")
        with self._cv:
            self._queued.setdefault(tenant, 0)
            self._quota[tenant] = queue_quota
            self._file_budget[tenant] = file_budget
            if open_files_fn is not None:
                self._open_files_fn[tenant] = open_files_fn
            self._stalls.setdefault(tenant, 0)
            self._stall_s.setdefault(tenant, 0.0)

    # -- charge/credit (the consumer queue_listener seam) --------------------
    def on_enqueued(self, tenant: str, n: int) -> None:
        with self._cv:
            self._queued[tenant] = self._queued.get(tenant, 0) + n
            schedcheck.point("tenant.ledger.charge")
            self._queued_total += n
            schedcheck.note_quota_ledger(
                id(self), sum(self._queued.values()), self._queued_total)

    def on_drained(self, tenant: str, n: int) -> None:
        with self._cv:
            take = min(n, self._queued.get(tenant, 0))
            self._queued[tenant] = self._queued.get(tenant, 0) - take
            schedcheck.point("tenant.ledger.credit")
            self._queued_total -= take
            schedcheck.note_quota_ledger(
                id(self), sum(self._queued.values()), self._queued_total)
            self._cv.notify_all()

    # -- enforcement ---------------------------------------------------------
    def _over_quota(self, tenant: str) -> bool:
        q = self._quota.get(tenant)
        return q is not None and self._queued.get(tenant, 0) >= q

    def wait_turn(self, tenant: str, tick_s: float = 0.05) -> float:
        """The fetch gate: park while ``tenant`` is at its queue share.
        Returns seconds stalled (0.0 on the fast path).  Backpressure on
        the offender only — the gate runs in the offending route's own
        fetcher thread, siblings never enter it."""
        with self._cv:
            if self._closed or not self._over_quota(tenant):
                return 0.0
        t0 = time.perf_counter()
        self._m_stalls.mark()
        with stage("tenant.quota.wait"):
            with self._cv:
                self._stalls[tenant] = self._stalls.get(tenant, 0) + 1
                while not self._closed and self._over_quota(tenant):
                    self._cv.wait(tick_s)
                dt = time.perf_counter() - t0
                self._stall_s[tenant] = self._stall_s.get(tenant, 0.0) + dt
        self._m_stall_ms.mark(max(1, int(dt * 1000)))
        return dt

    def files_over_budget(self, tenant: str | None) -> bool:
        """Live verdict for the open-file budget: True when the tenant's
        open-file count (counted through its registered callable —
        lock-free scrape of worker-owned maps, same contract as the
        gauges) has reached its budget.  The caller (the worker about to
        open one more) evicts its own LRU first."""
        if tenant is None:
            return False
        with self._cv:
            budget = self._file_budget.get(tenant)
            fn = self._open_files_fn.get(tenant)
        if budget is None or fn is None:
            return False
        try:
            return fn() >= budget
        # lint: swallowed-exceptions ok — lock-free scrape racing worker
        # dict mutation; a missed enforcement round beats killing the
        # write path, and the next open re-checks
        except Exception:
            return False

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- observability -------------------------------------------------------
    def tenant_snapshot(self, tenant: str) -> dict:
        with self._cv:
            fn = self._open_files_fn.get(tenant)
            out = {
                "queued_records": self._queued.get(tenant, 0),
                "queue_quota": self._quota.get(tenant),
                "open_file_budget": self._file_budget.get(tenant),
                "quota_stalls": self._stalls.get(tenant, 0),
                "quota_stall_s": round(self._stall_s.get(tenant, 0.0), 6),
            }
        if fn is not None:
            try:
                out["open_files"] = int(fn())
            # lint: swallowed-exceptions ok — observability scrape racing
            # worker teardown; the quota fields above are still valid
            except Exception:
                out["open_files"] = None
        return out

    def snapshot(self) -> dict:
        with self._cv:
            tenants = sorted(self._queued)
            total = self._queued_total
        return {
            "queued_total": total,
            "tenants": {t: self.tenant_snapshot(t) for t in tenants},
        }


class _LedgerQueueListener:
    """Binds one route's consumer-queue traffic to its tenant name on
    the shared ledger (the consumer's ``queue_listener`` seam)."""

    __slots__ = ("_ledger", "_tenant")

    def __init__(self, ledger: TenantQuotaLedger, tenant: str) -> None:
        self._ledger = ledger
        self._tenant = tenant

    def on_enqueued(self, n: int) -> None:
        self._ledger.on_enqueued(self._tenant, n)

    def on_drained(self, n: int) -> None:
        self._ledger.on_drained(self._tenant, n)


class _SharedBrokerSession:
    """One broker client shared by every route's consumer — the
    'one session, N topics' seam.  Tracks per-tenant fetch/record
    accounting so the session's traffic split is observable."""

    def __init__(self, broker) -> None:
        self.broker = broker
        self._mu = threading.Lock()
        self._fetches: dict[str, int] = {}
        self._records: dict[str, int] = {}

    def view(self, tenant: str, ledger: TenantQuotaLedger):
        return _TenantBrokerView(self, tenant, ledger)

    def note_fetch(self, tenant: str, n: int) -> None:
        with self._mu:
            self._fetches[tenant] = self._fetches.get(tenant, 0) + 1
            self._records[tenant] = self._records.get(tenant, 0) + n

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "fetches_by_tenant": dict(sorted(self._fetches.items())),
                "records_by_tenant": dict(sorted(self._records.items())),
            }


class _TenantBrokerView:
    """One route's window onto the shared broker session: fetches pass
    the tenant's quota gate first (blocking the OFFENDER's fetcher only),
    everything else delegates.  ``fetch_batch`` is surfaced only when the
    underlying broker has one, so the consumer's batch-ingest feature
    detection keeps working through the view."""

    def __init__(self, session: _SharedBrokerSession, tenant: str,
                 ledger: TenantQuotaLedger) -> None:
        self._session = session
        self._inner = session.broker
        self._tenant = tenant
        self._ledger = ledger
        if callable(getattr(self._inner, "fetch_batch", None)):
            # instance attribute, not a class method: a broker without
            # fetch_batch must keep raising AttributeError through the
            # view (the consumer's feature detection)
            self.fetch_batch = self._gated_fetch_batch

    def fetch(self, topic, partition, offset, max_records):
        self._ledger.wait_turn(self._tenant)
        recs = self._inner.fetch(topic, partition, offset, max_records)
        if recs:
            self._session.note_fetch(self._tenant, len(recs))
        return recs

    def _gated_fetch_batch(self, topic, partition, offset, max_records):
        self._ledger.wait_turn(self._tenant)
        rb = self._inner.fetch_batch(topic, partition, offset, max_records)
        if rb is not None and len(rb):
            self._session.note_fetch(self._tenant, len(rb))
        return rb

    def __getattr__(self, name):
        # join_group/commit/committed/generation/assignment/... delegate;
        # a missing attribute raises AttributeError from the inner broker,
        # preserving feature detection
        return getattr(self._inner, name)


class _Route:
    """One tenant's slot: its spec, its writer, and its typed status."""

    __slots__ = ("name", "spec", "writer", "forced_state", "reason_type",
                 "reason")

    def __init__(self, name: str, spec: dict, writer) -> None:
        self.name = name
        self.spec = spec
        self.writer = writer
        # "dead_lettering" once the schema guard condemned the route;
        # None = derive the live state from the writer
        self.forced_state: str | None = None
        self.reason_type: str | None = None
        self.reason: str | None = None

    def condemn(self, exc: BaseException, state: str) -> None:
        self.forced_state = state
        self.reason_type = type(exc).__name__
        self.reason = str(exc)

    def state(self) -> str:
        if self.forced_state is not None:
            return self.forced_state
        w = self.writer
        if w._terminal is not None:
            return "failed"
        if w._paused:
            return "paused"
        if not w._started:
            return "built"
        if w._closed:
            return "closed"
        return "running"

    def status(self) -> dict:
        return {"state": self.state(), "reason_type": self.reason_type,
                "reason": self.reason}


class _SharedCompactionService:
    """ONE background thread driving every route's Compactor round-robin
    (``recover()`` + ``compact_once()``), each route at ITS OWN
    configured cadence (per-route next-due clocks — a route that chose a
    long ``scan_interval_seconds`` to bound remote request/bandwidth
    cost is never scanned on a sibling's faster schedule), with a fault
    bulkhead per round — one route's compaction failure is logged and
    contained, siblings' rounds still run — and an optionally SHARED
    bandwidth budget: when any route's compaction config names
    ``bandwidth_bytes_per_s``, ONE token bucket throttles every route's
    merge traffic (background rewrite cost cannot multiply per tenant)."""

    def __init__(self, compactors: dict[str, object],
                 intervals: dict[str, float]) -> None:
        self._compactors = compactors
        self._intervals = intervals
        self._tick = min(intervals.values())
        self._closed = threading.Event()
        self._errors: dict[str, str] = {}
        self._thread = threading.Thread(
            target=self._loop, name="KPW-tenant-compaction", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def _loop(self) -> None:
        next_due = {name: 0.0 for name in self._compactors}
        while not self._closed.is_set():
            for name, c in self._compactors.items():
                if self._closed.is_set():
                    return
                if time.monotonic() < next_due[name]:
                    continue
                next_due[name] = time.monotonic() + self._intervals[name]
                try:
                    # one traced round per route: the compaction legs were
                    # the longest untraced gap in the e2e timeline
                    with stage("compactor.round", tenant=name):
                        c.recover()
                        c.compact_once()
                    self._errors.pop(name, None)
                except Exception as e:  # bulkhead: contain per route
                    self._errors[name] = repr(e)
                    logger.exception(
                        "tenant %s compaction round failed (contained; "
                        "sibling rounds continue)", name)
            if self._closed.wait(self._tick):
                return

    def snapshot(self) -> dict:
        return {
            "routes": sorted(self._compactors),
            "last_errors": dict(self._errors),
            "by_tenant": {n: c.compactor_stats()
                          for n, c in self._compactors.items()},
        }


def _tree_physical_types(fs, target_dir: str) -> dict[str, set]:
    """Union of leaf physical types per dotted column path across the
    tree's published files — the ``io/verify.py`` ``tree_schemas`` walk
    (ONE exclude-set/unreadable policy shared with the audit), folded to
    the union the route-level guard compares against."""
    from ..io.verify import tree_schemas

    per_file, _unreadable = tree_schemas(fs, target_dir)
    types: dict[str, set] = {}
    for leaves in per_file.values():
        for col, (pt, _rep, _conv) in leaves.items():
            types.setdefault(col, set()).add(pt)
    return types


class MultiWriter:
    """N per-tenant routes over one broker session, one encoder pool and
    one compaction service — constructed by ``Builder.build()`` when
    ``Builder.route(...)`` was called (see the module docstring for the
    bulkhead contract).  Lifecycle mirrors the single writer: ``start()``
    / ``close()`` / context manager; per-tenant surfaces are
    ``stats()['tenants']``, :meth:`route_stats`, :meth:`ack_lag` and the
    canonical tenant meters."""

    def __init__(self, b) -> None:  # b: runtime.builder.Builder (with routes)
        if not b._routes:
            raise ValueError("MultiWriter needs at least one route()")
        if b._broker is None:
            raise ValueError("routes need a broker (Builder.broker or "
                             "consumer_config)")
        if b._proc_workers:
            raise ValueError(
                "process_workers is not supported with route() yet: the "
                "shared-memory ring and per-child ledgers are per-writer "
                "(one pool per route would multiply rings per tenant); "
                "use thread workers for multi-tenant routes")
        self._b = b
        reg = b._metric_registry
        self.ledger = TenantQuotaLedger(registry=reg)
        self.session = _SharedBrokerSession(b._broker)
        self._routes: dict[str, _Route] = {}
        self._started = False
        self._closed = False
        self._last_close_report: dict | None = None
        self._compaction_svc: _SharedCompactionService | None = None
        compaction_cfgs: dict[str, dict] = {}
        for spec in b._routes:
            name = spec["name"]
            if name in self._routes:
                raise ValueError(f"duplicate route name {name!r}")
            rb = copy.copy(b)
            rb._routes = []
            rb._topic = spec["topic"]
            rb._proto_class = spec["proto_class"]
            rb._target_dir = spec["target_dir"]
            # a base-builder parser cannot apply across different protos;
            # routes re-derive the default (FromString) unless the
            # override re-sets one
            rb._parser = None
            for key, args in spec["overrides"].items():
                setter = getattr(rb, key)
                if isinstance(args, dict):
                    setter(**args)
                elif isinstance(args, tuple):
                    setter(*args)
                else:
                    setter(args)
            rb._broker = self.session.view(name, self.ledger)
            rb._queue_listener = _LedgerQueueListener(self.ledger, name)
            cfg = rb._compaction
            rb._compaction = None  # owned by the shared service, not start()
            if cfg:
                compaction_cfgs[name] = cfg
            writer = rb.build()
            writer.bind_tenant(name, self.ledger)
            route = _Route(name, spec, writer)
            self._routes[name] = route
            self.ledger.register(
                name, queue_quota=spec.get("queue_quota"),
                file_budget=spec.get("open_file_budget"),
                open_files_fn=self._open_files_counter(writer))
        if compaction_cfgs:
            self._compaction_svc = self._build_compaction(compaction_cfgs)
        if reg:
            self._register_aggregate_gauges(reg)

    @staticmethod
    def _open_files_counter(writer):
        def count() -> int:
            n = 0
            for w in writer._workers:
                n += len(w._part_files)
                if w.current_file is not None:
                    n += 1
            return n
        return count

    def _build_compaction(self, cfgs: dict[str, dict]):
        from ..io.compact import Compactor

        shared_budget = None
        for cfg in cfgs.values():
            if cfg.get("bandwidth_bytes_per_s"):
                from ..io.objectstore import BandwidthBudget

                # ONE bucket for every route's merge traffic: the first
                # route naming a budget sets the shared cap
                shared_budget = BandwidthBudget(cfg["bandwidth_bytes_per_s"])
                break
        compactors = {}
        intervals = {name: cfg["scan_interval_s"]
                     for name, cfg in cfgs.items()}
        for name, cfg in cfgs.items():
            route = self._routes[name]
            w = route.writer
            compactors[name] = Compactor(
                w.fs, w.target_dir, route.spec["proto_class"], w.properties,
                target_size=cfg["target_size"],
                small_file_ratio=cfg["small_file_ratio"],
                min_files=cfg["min_files"],
                scan_interval_s=cfg["scan_interval_s"],
                registry=self._b._metric_registry,
                instance_name=f"{self._b._instance_name}-{name}",
                sort_by=cfg["sort_by"],
                request_budget_per_round=cfg["request_budget_per_round"],
                partition_quota=cfg["partition_quota"],
                bandwidth_budget=shared_budget)
        return _SharedCompactionService(compactors, intervals)

    def _register_aggregate_gauges(self, reg) -> None:
        """Re-point the writer-level gauges each route's constructor
        registered (last-one-wins on a shared registry) at AGGREGATE
        providers, and add the tenant-layer gauges."""
        routes = self._routes

        def writers():
            return [r.writer for r in routes.values()]

        reg.gauge(M.ACK_LAG_GAUGE,
                  lambda: sum(w.ack_lag()["unacked_records"]
                              for w in writers()))
        reg.gauge(M.ACK_AGE_GAUGE,
                  lambda: max((w.ack_lag()["oldest_unacked_age_s"]
                               for w in writers()), default=0.0))
        reg.gauge(M.CONSUMER_QUEUE_DEPTH_GAUGE,
                  lambda: sum(w.consumer.queue_depth() for w in writers()))
        reg.gauge(M.WORKERS_ALIVE_GAUGE,
                  lambda: sum(1 for w in writers()
                              for wk in w._workers if wk.alive()))
        reg.gauge(M.PARTITIONS_OPEN_GAUGE,
                  lambda: sum(len(wk._part_files) for w in writers()
                              for wk in w._workers))
        reg.gauge(M.PAUSED_GAUGE,
                  lambda: sum(len(w._paused) for w in writers()))
        reg.gauge(M.TENANT_ROUTES_GAUGE, lambda: len(routes))
        reg.gauge(M.TENANT_ROUTES_DEGRADED_GAUGE,
                  lambda: sum(1 for r in routes.values()
                              if r.state() not in ("running", "built")
                              or not r.writer.healthy()))

    # -- schema evolution guard ----------------------------------------------
    def _schema_guard(self, route: _Route) -> None:
        """Diff the route's proto schema against its published tree.
        Additive columns pass (merged-schema reads stay consistent); a
        physical-type conflict on one dotted leaf path condemns the
        route to ``dead_lettering``: its parser is replaced with a
        :class:`SchemaIncompatibleError` raiser and its parse-error
        policy forced to ``dead_letter``, so every record lands in the
        route's dead-letter file (then acks) instead of poisoning the
        tree — and the wire fast path is disqualified (the flag the
        worker loop reads), so nothing bypasses the raiser."""
        from ..models.proto_bridge import proto_to_schema

        w = route.writer
        try:
            with stage("tenant.schema.audit", tenant=route.name):
                existing = _tree_physical_types(w.fs, w.target_dir)
        except OSError as e:
            logger.warning("route %s: schema guard could not list the "
                           "tree (%r); guard skipped", route.name, e)
            return
        if not existing:
            return
        new = {c.name: c.leaf.physical_type
               for c in proto_to_schema(route.spec["proto_class"]).columns}
        conflicts = [
            (col, sorted(existing[col]), pt)
            for col, pt in sorted(new.items())
            if col in existing and pt not in existing[col]
        ]
        if not conflicts:
            return
        detail = "; ".join(
            f"column {col!r}: published physical type(s) {have} vs proto "
            f"{want}" for col, have, want in conflicts[:3])
        err = SchemaIncompatibleError(
            f"route {route.name!r} ({route.spec['topic']} -> "
            f"{route.spec['target_dir']}): proto schema incompatible with "
            f"the published tree — {detail}")
        route.condemn(err, "dead_lettering")

        def _poison_parser(payload, _e=err):
            raise _e

        b = w._b
        b._parser = _poison_parser
        b._parser_is_default = False  # disqualify the wire fast path
        b._on_parse_error = "dead_letter"
        logger.error("%s — route dead-letters with its typed reason; "
                     "sibling routes unaffected", err)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._started:
            raise ValueError("already started")
        self._started = True
        for route in self._routes.values():
            self._schema_guard(route)
        started: list[_Route] = []
        try:
            for route in self._routes.values():
                with stage("tenant.route.start", tenant=route.name):
                    route.writer.start()
                started.append(route)
        except Exception:
            # a route that cannot even START is a config error, not a
            # runtime fault: unwind the siblings cleanly and surface it.
            # Ledger first — a sibling's fetcher may already be parked
            # in the quota gate, and close() alone never drains the
            # queue that parked it, so without this the daemon thread
            # (and its writer) leak for the life of the process
            self.ledger.close()
            for route in started:
                try:
                    route.writer.close()
                except Exception:  # lint: swallowed-exceptions ok —
                    # best-effort unwind on the construction error path
                    logger.exception("unwind close of route %s failed",
                                     route.name)
            raise
        if self._compaction_svc is not None:
            self._compaction_svc.start()

    def close(self, deadline: float | None = None) -> dict | None:
        """Close every route.  A terminally-failed route NEVER blocks a
        sibling's clean shutdown (the bulkhead holds through close): its
        ``WriterFailedError`` is captured into the report's
        ``terminal_routes`` and re-raised only when EVERY route failed
        terminally.  ``deadline`` bounds the whole shutdown; each route
        gets the remaining budget."""
        if self._closed:
            return self._last_close_report
        self._closed = True
        t0 = time.monotonic()
        t_end = None if deadline is None else t0 + max(0.0, deadline)
        if self._compaction_svc is not None:
            self._compaction_svc.close()
        # quotas stop binding first: a gated fetcher must not park
        # through its consumer's close join
        self.ledger.close()
        reports: dict[str, dict | None] = {}
        terminals: dict[str, str] = {}
        for name, route in self._routes.items():
            rem = (None if t_end is None
                   else max(0.0, t_end - time.monotonic()))
            try:
                with stage("tenant.route.close", tenant=name):
                    reports[name] = route.writer.close(deadline=rem)
            except Exception as e:  # WriterFailedError and kin: contained
                terminals[name] = repr(e)
        report = {
            "deadline_s": deadline,
            "duration_s": round(time.monotonic() - t0, 3),
            "routes": reports,
            "terminal_routes": terminals,
        }
        self._last_close_report = report
        if terminals and len(terminals) == len(self._routes):
            from .writer import WriterFailedError

            raise WriterFailedError(
                f"every route failed terminally: {terminals}")
        return report

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- per-tenant surface ---------------------------------------------------
    @property
    def routes(self) -> dict:
        """name -> the route's underlying writer (read-only use)."""
        return {n: r.writer for n, r in self._routes.items()}

    def route(self, name: str):
        return self._routes[name].writer

    def route_status(self, name: str) -> dict:
        return self._routes[name].status()

    def route_stats(self, name: str) -> dict:
        """The full single-writer stats() of one route."""
        return self._routes[name].writer.stats()

    def healthy(self) -> bool:
        if not self._started or self._closed:
            return False
        return all(r.writer.healthy() for r in self._routes.values())

    def ack_lag(self) -> dict:
        """Aggregate plus per-tenant ack lag (the per-tenant halves are
        the SLA observable bench.py --tenants samples)."""
        per = {n: r.writer.ack_lag() for n, r in self._routes.items()}
        return {
            "unacked_records": sum(p["unacked_records"]
                                   for p in per.values()),
            "oldest_unacked_age_s": max(
                (p["oldest_unacked_age_s"] for p in per.values()),
                default=0.0),
            "by_tenant": per,
        }

    def stats(self) -> dict:
        # ONE ledger snapshot per scrape: the per-tenant quota dicts are
        # shared into each tenant block instead of re-snapshotting per
        # route (a 25 ms sampling loop would otherwise double the ledger
        # lock traffic against the hot charge/credit path)
        ledger = self.ledger.snapshot()
        tenants = {}
        for name, route in self._routes.items():
            w = route.writer
            sla = route.spec.get("ack_sla_seconds")
            lag = w.ack_lag()
            tenants[name] = {
                "topic": route.spec["topic"],
                "target_dir": route.spec["target_dir"],
                **route.status(),
                "healthy": w.healthy(),
                "ack": lag,
                "ack_sla_seconds": sla,
                "sla_violated": (sla is not None
                                 and lag["oldest_unacked_age_s"] > sla),
                "workers_alive": sum(1 for wk in w._workers if wk.alive()),
                "workers_dead": sum(1 for wk in w._workers if wk.failed),
                "restarts_total": sum(w._restart_counts),
                "deadletter_records": w._deadletter_route.count,
                # this route's OWN time-to-durable distribution (seconds,
                # p50/p99): the route-local histogram, not the canonical
                # one a shared registry merges across tenants
                "ack_latency": w._ack_latency_route.snapshot(),
                "quota": ledger["tenants"].get(name, {}),
            }
        out = {
            "healthy": self.healthy(),
            "tenants": tenants,
            "quota_ledger": ledger,
            "session": self.session.snapshot(),
        }
        if self._compaction_svc is not None:
            out["compaction"] = self._compaction_svc.snapshot()
        return out
