"""Writer runtime: Builder config API, orchestrator, worker pool, rotation,
retry, metrics — the reference's L3-L5 layers rebuilt (SURVEY.md §1)."""

from .builder import Builder  # noqa: F401
from .export import registry_to_json, registry_to_prometheus  # noqa: F401
from .metrics import Gauge, MetricRegistry  # noqa: F401
from .parquet_file import ParquetFile  # noqa: F401
from .partition import (  # noqa: F401
    CallablePartitioner,
    EventTimePartitioner,
    FieldPartitioner,
    Partitioner,
)
from .retry import (  # noqa: F401
    RetryBudgetExceeded,
    RetryInterrupted,
    RetryPolicy,
)
from .writer import (  # noqa: F401
    KafkaProtoParquetWriter,
    PublishVerificationError,
    WriterFailedError,
)
from .multiwriter import (  # noqa: F401
    MultiWriter,
    SchemaIncompatibleError,
    TenantQuotaLedger,
)
