"""Partitioning seam: record -> relative partition path.

The reference writer emits one flat stream of rotated files per worker;
production ingest serving scan-heavy readers writes Hive-style partitioned
layouts (``dt=20260803/hour=14`` or keyed by a record field) so that
predicate pruning can skip whole directories.  A :class:`Partitioner` maps
one consumed record (the raw broker :class:`~kpw_tpu.ingest.broker.Record`
plus its parsed protobuf message) to a RELATIVE directory path under the
writer's target dir; the worker runtime (``runtime/writer.py``) routes the
record into that partition's open file ahead of file assignment.

Three built-in shapes (``Builder.partition_by`` constructs them):

* :class:`FieldPartitioner` — Hive-style ``{field}={value}`` from one
  protobuf field of the parsed message (multi-field = pass a tuple).
* :class:`EventTimePartitioner` — an integer epoch field bucketed through
  a strftime pattern (``dt=%Y%m%d/hour=%H`` by default); ``unit`` scales
  ``s``/``ms``/``us`` epochs.  Buckets in UTC — partition layout must not
  depend on the writer host's timezone.
* :class:`CallablePartitioner` — any user callable ``(record, message) ->
  str`` for layouts the built-ins cannot express.

Every produced path is normalized through :func:`normalize_partition_path`
before it touches the filesystem: relative, no ``..``/empty segments, and
field values are sanitized to a conservative charset — a partitioner must
never be able to climb out of the target dir or smuggle a path separator
inside one value.  A partitioner that raises is handled by the worker
under the same policy as an unparseable record (``Builder.on_parse_error``):
a record whose partition cannot be derived is the same class of poison
pill as one whose bytes cannot be parsed.
"""

from __future__ import annotations

import re
from datetime import datetime, timezone

# conservative value charset: everything else becomes "_" so a field value
# can never introduce a separator, a relative segment, or shell-hostile
# bytes into the directory layout
_VALUE_BAD = re.compile(r"[^A-Za-z0-9._\-=]")
# one sanitized path SEGMENT: like a value but '=' allowed ("dt=20260803")
# and never "."/".." (normalize_partition_path rejects those explicitly)
_TIME_UNITS = {"s": 1.0, "ms": 1e3, "us": 1e6}
# the writer's working subtrees under the target dir: a partition routed
# here would publish acked data into a tree verify_dir, the compactor
# scan and every convention-following reader EXCLUDE — acked-but-
# invisible rows, rejected up front
RESERVED_SEGMENTS = frozenset(
    ("tmp", "quarantine", "compacted", "deadletter"))


def sanitize_value(value) -> str:
    """One partition VALUE as a safe path fragment (hostile characters
    collapse to ``_``; empty stays visible as ``_``)."""
    s = _VALUE_BAD.sub("_", str(value))
    return s if s else "_"


def normalize_partition_path(path: str) -> str:
    """Validate + normalize a partitioner-produced relative path.

    Accepts ``a/b/c`` shapes; rejects (``ValueError``) anything absolute,
    empty, or containing ``.``/``..``/empty segments — the partitioner is
    user code and must not be able to direct a publish outside the target
    directory.  Segments are NOT re-sanitized here (the built-ins already
    sanitize their values; a CallablePartitioner owns its own charset),
    only structurally validated."""
    if not isinstance(path, str):
        raise ValueError(
            f"partitioner must return a str path, got {type(path).__name__}")
    p = path.strip("/")
    if not p or path.startswith("/") or "\\" in path or "\x00" in path:
        raise ValueError(f"invalid partition path {path!r}: must be a "
                         f"relative, non-empty POSIX path")
    segs = p.split("/")
    for seg in segs:
        if seg in ("", ".", ".."):
            raise ValueError(f"invalid partition path {path!r}: "
                             f"segment {seg!r} not allowed")
    if segs[0] in RESERVED_SEGMENTS:
        raise ValueError(
            f"invalid partition path {path!r}: {segs[0]!r} is a reserved "
            f"working directory of the writer (records routed there would "
            f"be acked but excluded from the published set)")
    return "/".join(segs)


class Partitioner:
    """record -> relative partition path (e.g. ``dt=20260803/hour=14``)."""

    def partition_for(self, record, message) -> str:
        raise NotImplementedError


class FieldPartitioner(Partitioner):
    """Hive-style ``{field}={value}`` from the parsed message's field(s).

    ``fields`` is one field name or a tuple of them (one path segment per
    field, in order): ``("region", "tier")`` -> ``region=eu/tier=gold``."""

    def __init__(self, fields) -> None:
        self.fields = ((fields,) if isinstance(fields, str)
                       else tuple(fields))
        if not self.fields:
            raise ValueError("FieldPartitioner needs at least one field")

    def partition_for(self, record, message) -> str:
        return "/".join(f"{f}={sanitize_value(getattr(message, f))}"
                        for f in self.fields)


class EventTimePartitioner(Partitioner):
    """Epoch field -> strftime-bucketed path, UTC.

    ``field`` must hold an integer/float epoch in ``unit`` (``s``/``ms``/
    ``us``).  Default pattern ``dt=%Y%m%d/hour=%H`` is the classic
    Hive daily/hourly layout."""

    def __init__(self, field: str, pattern: str = "dt=%Y%m%d/hour=%H",
                 unit: str = "s") -> None:
        if unit not in _TIME_UNITS:
            raise ValueError(f"unit must be one of {sorted(_TIME_UNITS)}, "
                             f"got {unit!r}")
        self.field = field
        self.pattern = pattern
        self._div = _TIME_UNITS[unit]

    def partition_for(self, record, message) -> str:
        epoch = getattr(message, self.field) / self._div
        return datetime.fromtimestamp(epoch, tz=timezone.utc).strftime(
            self.pattern)


class CallablePartitioner(Partitioner):
    """Wrap a user callable ``(record, message) -> str``."""

    def __init__(self, fn) -> None:
        if not callable(fn):
            raise TypeError("CallablePartitioner needs a callable")
        self.fn = fn

    def partition_for(self, record, message) -> str:
        return self.fn(record, message)


def make_partitioner(spec) -> Partitioner:
    """Coerce a ``Builder.partition_by`` spec into a Partitioner: a
    Partitioner passes through, a str/tuple becomes a FieldPartitioner,
    any other callable becomes a CallablePartitioner."""
    if isinstance(spec, Partitioner):
        return spec
    if isinstance(spec, (str, tuple, list)):
        return FieldPartitioner(spec)
    if callable(spec):
        return CallablePartitioner(spec)
    raise TypeError(
        f"partition_by expects a field name, a (record, message) callable "
        f"or a Partitioner, got {type(spec).__name__}")
