"""Metrics: written-vs-flushed meters + file size histogram.

Mirrors the reference's Dropwizard registration (KafkaProtoParquetWriter.java:
111-119,144-151,337-341): ``parquet.writer.written.records|bytes`` mark on
every accepted record (buffered), ``flushed.*`` only after a file is durably
published, ``parquet.writer.file.size`` histogram per finalized file.  The
written≠flushed distinction (buffered vs durable) is load-bearing and kept.
"""

from __future__ import annotations

import math
import random
import threading
import time

_TICK_INTERVAL = 5.0  # seconds per EWMA tick (Dropwizard's constant)


class _EWMA:
    """One exponentially-weighted moving average over a fixed window,
    advanced in discrete 5-second ticks (Dropwizard EWMA semantics: the
    first tick seeds the rate with the instantaneous value; later ticks
    blend with alpha = 1 - e^(-interval/window))."""

    def __init__(self, window_minutes: float) -> None:
        self._alpha = 1.0 - math.exp(-_TICK_INTERVAL / (window_minutes * 60.0))
        self._rate = 0.0
        self._initialized = False
        self._uncounted = 0

    def update(self, n: int) -> None:
        self._uncounted += n

    def tick(self) -> None:
        inst = self._uncounted / _TICK_INTERVAL
        self._uncounted = 0
        if self._initialized:
            self._rate += self._alpha * (inst - self._rate)
        else:
            self._rate = inst
            self._initialized = True

    @property
    def rate(self) -> float:
        return self._rate


class Meter:
    """Monotonic counter + Dropwizard-fidelity moving-average rates.

    The reference registers Dropwizard ``Meter``s (KafkaProtoParquetWriter.
    java:111-119): a count plus 1/5/15-minute exponentially-weighted rates
    ticked every 5 seconds, and a lifetime mean rate.  Rates advance lazily
    (on mark or read) like Dropwizard's ``tickIfNecessary``; an idle gap
    replays the missed ticks so rates decay exactly as if ticked on time."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._count = 0
        self._lock = threading.Lock()
        self._start = clock()
        self._last_tick = self._start
        self._m1 = _EWMA(1.0)
        self._m5 = _EWMA(5.0)
        self._m15 = _EWMA(15.0)

    def _tick_if_necessary(self) -> None:
        age = self._clock() - self._last_tick
        if age < _TICK_INTERVAL:
            return
        ticks = int(age // _TICK_INTERVAL)
        self._last_tick += ticks * _TICK_INTERVAL
        for _ in range(ticks):
            self._m1.tick()
            self._m5.tick()
            self._m15.tick()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._tick_if_necessary()
            self._count += n
            self._m1.update(n)
            self._m5.update(n)
            self._m15.update(n)

    @property
    def count(self) -> int:
        # locked like the rate getters: a bare int read is atomic in
        # CPython, but a reader racing mark() could otherwise observe the
        # count before the EWMA update it belongs with — take the same
        # lock so concurrent readers see a consistent counter
        with self._lock:
            return self._count

    def _rate(self, ewma: _EWMA) -> float:
        with self._lock:
            self._tick_if_necessary()
            return ewma.rate

    @property
    def one_minute_rate(self) -> float:
        return self._rate(self._m1)

    @property
    def five_minute_rate(self) -> float:
        return self._rate(self._m5)

    @property
    def fifteen_minute_rate(self) -> float:
        return self._rate(self._m15)

    @property
    def mean_rate(self) -> float:
        with self._lock:
            elapsed = self._clock() - self._start
            return self._count / elapsed if elapsed > 0 else 0.0

    def snapshot(self) -> dict:
        """Count + all rates in one lock round (a stats() scrape reading
        the four properties separately would tick four times and could
        interleave with a concurrent mark)."""
        with self._lock:
            self._tick_if_necessary()
            elapsed = self._clock() - self._start
            return {
                "count": self._count,
                "mean_rate": self._count / elapsed if elapsed > 0 else 0.0,
                "m1_rate": self._m1.rate,
                "m5_rate": self._m5.rate,
                "m15_rate": self._m15.rate,
            }


_RESCALE_SECONDS = 3600.0  # Dropwizard ExponentiallyDecayingReservoir


class Histogram:
    """File-size histogram with Dropwizard's exponentially-decaying
    reservoir (KPW.java:118 registers a default ``Histogram``, whose
    reservoir is ``ExponentiallyDecayingReservoir(1028, 0.015)``): samples
    carry forward-decay weights ``e^(alpha*(t-landmark))`` with priority
    ``weight/uniform()``, the lowest-priority sample is evicted at
    capacity, and the landmark rescales hourly so priorities never
    overflow.  Snapshot quantiles are weight-based (Dropwizard
    ``WeightedSnapshot``), which biases toward the most recent ~5 minutes
    of data under load instead of the uniform all-history view."""

    def __init__(self, reservoir: int = 1028, alpha: float = 0.015,
                 clock=time.monotonic) -> None:
        self._size = reservoir
        self._alpha = alpha
        self._clock = clock
        self._count = 0
        self._lock = threading.Lock()
        # priority -> (value, weight); kept small (<= size+1), so O(n)
        # min-eviction beats a heap's constant factor at n ~ 1k
        self._samples: dict[float, tuple[float, float]] = {}
        self._start = clock()
        self._next_rescale = self._start + _RESCALE_SECONDS

    def _rescale_if_needed(self, now: float) -> None:
        if now < self._next_rescale:
            return
        old_start, self._start = self._start, now
        self._next_rescale = now + _RESCALE_SECONDS
        factor = math.exp(-self._alpha * (now - old_start))
        self._samples = {
            k * factor: (v, w * factor)
            for k, (v, w) in self._samples.items() if w * factor > 0.0
        }

    def update(self, value: float) -> None:
        with self._lock:
            now = self._clock()
            self._rescale_if_needed(now)
            self._count += 1
            weight = math.exp(self._alpha * (now - self._start))
            priority = weight / max(random.random(), 1e-12)
            if len(self._samples) < self._size:
                self._samples[priority] = (value, weight)
            else:
                lowest = min(self._samples)
                if priority > lowest:
                    # on a priority collision, overwrite the incumbent —
                    # Dropwizard's ExponentiallyDecayingReservoir keeps one
                    # of the two rather than dropping the new sample
                    if priority not in self._samples:
                        del self._samples[lowest]
                    self._samples[priority] = (value, weight)

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> dict:
        with self._lock:
            self._rescale_if_needed(self._clock())
            entries = sorted(self._samples.values())  # by value
            count = self._count  # same lock round: count matches quantiles
        if not entries:
            return {"min": 0, "max": 0, "mean": 0, "p50": 0, "p95": 0,
                    "p99": 0, "count": count}
        total_w = sum(w for _, w in entries)

        def q(p: float) -> float:
            # Dropwizard WeightedSnapshot: first value whose cumulative
            # normalized weight crosses the quantile
            acc = 0.0
            for v, w in entries:
                acc += w / total_w
                if acc >= p:
                    return v
            return entries[-1][0]

        return {
            "min": entries[0][0],
            "max": entries[-1][0],
            "mean": sum(v * w for v, w in entries) / total_w,
            "p50": q(0.5),
            "p95": q(0.95),
            # file-size tails: rotation-band verification needs the p99
            # (one oversized file in a hundred is exactly what the ~1%
            # overshoot bound is about)
            "p99": q(0.99),
            "count": count,
        }


class Gauge:
    """Point-in-time value: either set explicitly (``set``) or backed by a
    callable sampled at read time (``set_function`` — the pull-based shape:
    the live structure is read only when something scrapes the registry).
    Dropwizard registers gauges the same two ways."""

    def __init__(self, fn=None) -> None:
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def set_function(self, fn) -> None:
        """Back the gauge with a zero-arg callable, sampled on read."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            # a dying provider (e.g. a closed writer's structures) must
            # never take the scrape down with it
            return float("nan")


class MetricRegistry:
    """Name -> metric; the registry users may pass to the Builder."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def meter(self, name: str) -> Meter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Meter()
                self._metrics[name] = m
            return m

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._metrics.get(name)
            if h is None:
                h = Histogram()
                self._metrics[name] = h
            return h

    def gauge(self, name: str, fn=None) -> Gauge:
        """Get-or-create a gauge; ``fn`` (optional zero-arg callable)
        installs/replaces the read-time provider."""
        with self._lock:
            g = self._metrics.get(name)
            if g is None:
                g = Gauge()
                self._metrics[name] = g
            elif not isinstance(g, Gauge):
                # fail intelligibly, not with an AttributeError later
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(g).__name__}, not Gauge")
        if fn is not None:
            g.set_function(fn)
        return g

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> list[str]:
        return sorted(self._metrics)


# metric names (reference KPW.java:111-119)
WRITTEN_RECORDS_METER = "parquet.writer.written.records"
FLUSHED_RECORDS_METER = "parquet.writer.flushed.records"
WRITTEN_BYTES_METER = "parquet.writer.written.bytes"
FLUSHED_BYTES_METER = "parquet.writer.flushed.bytes"
FILE_SIZE_HISTOGRAM = "parquet.writer.file.size"
# observability layer (beyond the reference, which has no gauges):
# at-least-once ack lag — records accepted (written) but not yet durably
# acked, and the age of the oldest unacked offset — plus rotation-cause
# meters and the shared consumer queue's live depth
ACK_LAG_GAUGE = "parquet.writer.ack.lag.records"
ACK_AGE_GAUGE = "parquet.writer.ack.oldest.age.seconds"
ROTATED_SIZE_METER = "parquet.writer.rotated.size"
ROTATED_TIME_METER = "parquet.writer.rotated.time"
CONSUMER_QUEUE_DEPTH_GAUGE = "consumer.queue.depth"
# robustness layer: retry/backoff accounting, worker deaths + supervised
# restarts, live-worker gauge, and the startup recovery sweep's GC count
RETRIES_METER = "parquet.writer.retries"
RETRY_BACKOFF_MS_METER = "parquet.writer.retry.backoff.ms"
FAILED_METER = "parquet.writer.failed"
RESTARTS_METER = "parquet.writer.worker.restarts"
WORKERS_ALIVE_GAUGE = "parquet.writer.workers.alive"
TMP_SWEPT_METER = "parquet.writer.tmp.swept"
# durability layer: independent structural verification (io/verify.py) of
# published files — verified counts clean passes (startup recovery +
# publish-time), verify.failed counts files the verifier condemned, and
# quarantined counts condemned finals moved to {target_dir}/quarantine/
# (moved, never deleted)
VERIFIED_METER = "parquet.writer.verified"
VERIFY_FAILED_METER = "parquet.writer.verify.failed"
QUARANTINED_METER = "parquet.writer.quarantined"
# degraded-operation layer: hung-IO watchdog stall episodes, workers
# currently paused on a fatal-but-healable sink condition (gauge), and the
# spillover failover filesystem's spill/reconcile accounting (finals
# published onto the fallback, spills migrated back to the primary, and
# verify-failures-quarantined + migration retries)
STALLED_METER = "parquet.writer.stalled"
PAUSED_GAUGE = "parquet.writer.paused"
SPILLED_METER = "parquet.writer.spilled"
RECONCILED_METER = "parquet.writer.reconciled"
RECONCILE_FAILED_METER = "parquet.writer.reconcile.failed"
# partitioned-output layer: partition files currently open across workers
# (gauge, bounded by max_open_partitions per worker) and LRU
# close-and-publish evictions of the least-recently-written partition
PARTITIONS_OPEN_GAUGE = "parquet.writer.partitions.open"
PARTITIONS_EVICTED_METER = "parquet.writer.partitions.evicted"
# compaction layer (io/compact.py): merged counts published merge outputs,
# retired counts input files tombstoned to {target_dir}/compacted/ (moved,
# never deleted), failed counts verify failures + aborted merge rounds
COMPACTOR_MERGED_METER = "parquet.compactor.merged"
COMPACTOR_RETIRED_METER = "parquet.compactor.retired"
COMPACTOR_FAILED_METER = "parquet.compactor.failed"
# query-ready-files layer (core/index.py): indexed counts published files
# carrying PARQUET-922 page-index sections; bloom.bytes counts serialized
# split-block bloom filter bytes (header + bitset) landed in those files
INDEXED_METER = "parquet.writer.indexed"
BLOOM_BYTES_METER = "parquet.writer.bloom.bytes"
# nogil-assembly layer (native/src/assemble.cc): column chunks and pages
# whose page assembly ran as one GIL-released native call instead of the
# Python page loops — the evidence the assembly pool actually shards
# columns across cores (zero on backends without the extension or with
# Builder.native_assembly(False))
NATIVE_ASM_CHUNKS_METER = "parquet.writer.assembly.native.chunks"
NATIVE_ASM_PAGES_METER = "parquet.writer.assembly.native.pages"
# object-store layer (io/objectstore.py): every store request the sink
# served (create/put/get/head/list/copy/delete + the multipart trio),
# bytes moved in+out across them, multipart parts uploaded (the
# upload-hidden-under-encode pipeline's unit), multipart uploads aborted
# (orphan recovery + staged-tmp sweeps), and the store's observed rolling
# bandwidth in bytes/s (gauge, 5 s trailing window)
OBJSTORE_REQUESTS_METER = "parquet.writer.objstore.requests"
OBJSTORE_BYTES_METER = "parquet.writer.objstore.bytes"
OBJSTORE_PARTS_METER = "parquet.writer.objstore.parts"
OBJSTORE_ABORTED_METER = "parquet.writer.objstore.aborted"
OBJSTORE_BANDWIDTH_GAUGE = "parquet.writer.objstore.bandwidth"
# process-parallel-workers layer (runtime/procworkers.py): the
# shared-memory batch ring's slot count and live free slots, records
# dispatched-but-unacked across children, aggregate child rss, and live
# child process count — registered when Builder.process_workers is on
PROC_RING_SLOTS_GAUGE = "worker.proc.ring.slots"
PROC_RING_FREE_GAUGE = "worker.proc.ring.free"
PROC_INFLIGHT_GAUGE = "worker.proc.inflight.records"
PROC_RSS_GAUGE = "worker.proc.rss.bytes"
PROC_ALIVE_GAUGE = "worker.proc.alive"
# multi-tenant layer (runtime/multiwriter.py): the shared-session quota
# ledger's backpressure evidence — quota-stall episodes (one fetch gate
# blocked because its tenant was at its queue share) and the cumulative
# stall milliseconds across them, open files evicted because a tenant hit
# its open-file budget (the generalized PR-8 LRU bound), records appended
# to dead-letter files (poison payloads + schema-incompatible routes),
# plus live route counts: total routes and routes currently degraded
# (paused / dead-lettering / failed) — marked across tenants (per-tenant
# breakdowns ride stats()['tenants'], names stay canonical)
TENANT_QUEUE_STALLS_METER = "parquet.writer.tenant.queue.stalls"
TENANT_QUEUE_STALL_MS_METER = "parquet.writer.tenant.queue.stall.ms"
TENANT_FILES_EVICTED_METER = "parquet.writer.tenant.files.evicted"
DEADLETTER_METER = "parquet.writer.deadletter.records"
TENANT_ROUTES_GAUGE = "parquet.writer.tenant.routes"
TENANT_ROUTES_DEGRADED_GAUGE = "parquet.writer.tenant.routes.degraded"
# telemetry-plane layer (runtime/telemetry.py): end-to-end ack latency —
# seconds from a batch's ingest into the shared queue to its offsets
# being durably acked (the time-to-durable histogram the cluster bench
# needs: percentiles in SECONDS, not record-count lag proxies) — plus the
# cross-process aggregation gauges: child-origin written/flushed record
# counts summed over the live shm telemetry cells PLUS the banked totals
# of dead children (a respawn banks the dead child's final counts first,
# so the merged scrape is monotonic and a dead cell never poisons it),
# cumulative child stage-time seconds, child span counts (recorded /
# dropped), and the crash flight recorder's dump count
ACK_LATENCY_HISTOGRAM = "parquet.writer.ack.latency"
CHILD_WRITTEN_RECORDS_GAUGE = "worker.proc.child.written.records"
CHILD_FLUSHED_RECORDS_GAUGE = "worker.proc.child.flushed.records"
CHILD_STAGE_SECONDS_GAUGE = "worker.proc.child.stage.seconds"
CHILD_SPANS_GAUGE = "worker.proc.child.spans"
CHILD_SPANS_DROPPED_GAUGE = "worker.proc.child.spans.dropped"
FLIGHTREC_DUMPS_METER = "parquet.writer.flightrec.dumps"
# consumer-group rebalance layer (ingest/broker.py group coordination +
# ingest/consumer.py cooperative revocation): generation bumps observed by
# this instance's consumer, files rotated early because their open file held
# a revoked partition's rows (the drain-window flush), ack commits the
# broker rejected with a stale-generation fence (the zombie backstop), and
# open files abandoned unpublished because their partitions were LOST
# (session expiry / drain timeout — publishing would only earn a fenced
# commit)
REBALANCES_METER = "parquet.writer.rebalances"
ROTATED_REVOKE_METER = "parquet.writer.rotated.revoke"
FENCED_ACKS_METER = "parquet.writer.rebalance.fenced.acks"
FENCE_ABANDONS_METER = "parquet.writer.rebalance.abandons"
# process-mode rebalance (runtime/procworkers.py): child-side fence
# activity folded into the merged scrape through the PR-17 telemetry
# cells — files a child flushed under a revoke fence and open files it
# abandoned on revoke/lost, summed live + banked like the other
# worker.proc.child.* gauges
CHILD_REBALANCE_FENCED_GAUGE = "worker.proc.child.rebalance.fenced"
CHILD_REBALANCE_ABANDONED_GAUGE = "worker.proc.child.rebalance.abandoned"

# the canonical registry docs cite from (tools/check_docs.py verifies
# every doc-cited metric name is listed here)
METRIC_NAMES = (
    WRITTEN_RECORDS_METER,
    FLUSHED_RECORDS_METER,
    WRITTEN_BYTES_METER,
    FLUSHED_BYTES_METER,
    FILE_SIZE_HISTOGRAM,
    ACK_LAG_GAUGE,
    ACK_AGE_GAUGE,
    ROTATED_SIZE_METER,
    ROTATED_TIME_METER,
    CONSUMER_QUEUE_DEPTH_GAUGE,
    RETRIES_METER,
    RETRY_BACKOFF_MS_METER,
    FAILED_METER,
    RESTARTS_METER,
    WORKERS_ALIVE_GAUGE,
    TMP_SWEPT_METER,
    VERIFIED_METER,
    VERIFY_FAILED_METER,
    QUARANTINED_METER,
    STALLED_METER,
    PAUSED_GAUGE,
    SPILLED_METER,
    RECONCILED_METER,
    RECONCILE_FAILED_METER,
    PARTITIONS_OPEN_GAUGE,
    PARTITIONS_EVICTED_METER,
    COMPACTOR_MERGED_METER,
    COMPACTOR_RETIRED_METER,
    COMPACTOR_FAILED_METER,
    INDEXED_METER,
    BLOOM_BYTES_METER,
    NATIVE_ASM_CHUNKS_METER,
    NATIVE_ASM_PAGES_METER,
    OBJSTORE_REQUESTS_METER,
    OBJSTORE_BYTES_METER,
    OBJSTORE_PARTS_METER,
    OBJSTORE_ABORTED_METER,
    OBJSTORE_BANDWIDTH_GAUGE,
    PROC_RING_SLOTS_GAUGE,
    PROC_RING_FREE_GAUGE,
    PROC_INFLIGHT_GAUGE,
    PROC_RSS_GAUGE,
    PROC_ALIVE_GAUGE,
    TENANT_QUEUE_STALLS_METER,
    TENANT_QUEUE_STALL_MS_METER,
    TENANT_FILES_EVICTED_METER,
    DEADLETTER_METER,
    TENANT_ROUTES_GAUGE,
    TENANT_ROUTES_DEGRADED_GAUGE,
    ACK_LATENCY_HISTOGRAM,
    CHILD_WRITTEN_RECORDS_GAUGE,
    CHILD_FLUSHED_RECORDS_GAUGE,
    CHILD_STAGE_SECONDS_GAUGE,
    CHILD_SPANS_GAUGE,
    CHILD_SPANS_DROPPED_GAUGE,
    FLIGHTREC_DUMPS_METER,
    REBALANCES_METER,
    ROTATED_REVOKE_METER,
    FENCED_ACKS_METER,
    FENCE_ABANDONS_METER,
    CHILD_REBALANCE_FENCED_GAUGE,
    CHILD_REBALANCE_ABANDONED_GAUGE,
)
