"""Metrics: written-vs-flushed meters + file size histogram.

Mirrors the reference's Dropwizard registration (KafkaProtoParquetWriter.java:
111-119,144-151,337-341): ``parquet.writer.written.records|bytes`` mark on
every accepted record (buffered), ``flushed.*`` only after a file is durably
published, ``parquet.writer.file.size`` histogram per finalized file.  The
written≠flushed distinction (buffered vs durable) is load-bearing and kept.
"""

from __future__ import annotations

import threading
import time


class Meter:
    """Monotonic counter + exponentially-weighted 1-minute rate."""

    def __init__(self) -> None:
        self._count = 0
        self._lock = threading.Lock()
        self._rate = 0.0
        self._last = time.monotonic()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = time.monotonic()
            dt = now - self._last
            if dt > 0:
                inst = n / dt if dt < 60 else 0.0
                alpha = min(1.0, dt / 60.0)
                self._rate += alpha * (inst - self._rate)
                self._last = now
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    @property
    def one_minute_rate(self) -> float:
        return self._rate


class Histogram:
    def __init__(self, reservoir: int = 1024) -> None:
        self._values: list[float] = []
        self._reservoir = reservoir
        self._count = 0
        self._lock = threading.Lock()

    def update(self, value: float) -> None:
        import random

        with self._lock:
            self._count += 1
            if len(self._values) < self._reservoir:
                self._values.append(value)
            else:
                i = random.randrange(self._count)
                if i < self._reservoir:
                    self._values[i] = value

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> dict:
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return {"min": 0, "max": 0, "mean": 0, "p50": 0, "p95": 0}

        def q(p: float) -> float:
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return {
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": q(0.5),
            "p95": q(0.95),
        }


class MetricRegistry:
    """Name -> metric; the registry users may pass to the Builder."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def meter(self, name: str) -> Meter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Meter()
                self._metrics[name] = m
            return m

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._metrics.get(name)
            if h is None:
                h = Histogram()
                self._metrics[name] = h
            return h

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> list[str]:
        return sorted(self._metrics)


# metric names (reference KPW.java:111-119)
WRITTEN_RECORDS_METER = "parquet.writer.written.records"
FLUSHED_RECORDS_METER = "parquet.writer.flushed.records"
WRITTEN_BYTES_METER = "parquet.writer.written.bytes"
FLUSHED_BYTES_METER = "parquet.writer.flushed.bytes"
FILE_SIZE_HISTOGRAM = "parquet.writer.file.size"
