"""Writer orchestrator + worker runtime: the reference's L4/L3 layers.

``KafkaProtoParquetWriter`` owns one smart-commit consumer and N workers
(KafkaProtoParquetWriter.java:63-214); each worker runs the poll → parse →
write → rotate → publish → ack loop (:253-292) with size/time rotation
(:297-308), tmp→rename atomic publish (:359-378), deferred acks strictly
after publish (:347-350 — the at-least-once anchor), policy-driven IO
retry (runtime/retry.py — reference :410-443 semantics by default, plus
fatal-errno classification), and close semantics that abandon the open tmp
file so unacked records are redelivered (:381-398).

Beyond the reference (robustness PR): worker death is observable
(``healthy()``, the failed meter, per-worker exit reasons in ``stats()``),
and ``Builder.supervise`` adds a supervisor that re-injects a dead
worker's never-acked offsets into the shared queue and restarts the slot
with capped, backed-off restarts — terminal exhaustion raises
``WriterFailedError`` at ``close()``.
"""

from __future__ import annotations

import logging
import random
import re
import struct
import threading
import time
from datetime import datetime

from ..core.select_encoding import encoding_name
from ..core.writer import PipelineError
from ..io.compact import Compactor
from ..io.fs import publish_file
from ..io.verify import verify_dir, verify_file
from ..ingest.autotune import IngestAutotuner
from ..ingest.broker import RecordBatch, StaleGenerationError
from ..ingest.consumer import SmartCommitConsumer
from ..ingest.offsets import PartitionOffset
from ..models.proto_bridge import ProtoColumnarizer, WireShredError
from ..utils import tracing
from ..utils.tracing import stage
from . import metrics as M
from .export import registry_to_json
from .parquet_file import ParquetFile
from .partition import normalize_partition_path
from .procworkers import ProcessWorkerPool
from .retry import RetryInterrupted, RetryPolicy
from .telemetry import ChildTelemetry, FlightRecorder
from .watchdog import Heartbeat, Watchdog

logger = logging.getLogger(__name__)


class WriterFailedError(Exception):
    """Terminal writer failure: every worker died and (with supervision
    enabled) the restart budget is exhausted.  Raised by ``close()`` so a
    writer that silently stopped making progress cannot masquerade as a
    clean shutdown; the unacked records are redelivered to the next
    instance (at-least-once)."""


class PublishVerificationError(Exception):
    """A closed tmp file failed the independent structural verifier at
    publish time (``Builder.durability(verify_on_publish=True)``).  The
    file was quarantined, never published; deliberately NOT an OSError —
    the bytes are wrong, so the IO retry loop must not spin on it.  The
    worker dies un-acked and the records are redelivered."""


def _format_now(pattern: str) -> str:
    """strftime of now, plus ``%3f`` = zero-padded milliseconds — the
    reference's file-name pattern is yyyyMMdd-HHmmssSSS (KPW.java:486-487)
    and strftime has no millisecond directive (%f is microseconds)."""
    now = datetime.now()
    if "%3f" in pattern:
        pattern = pattern.replace("%3f", f"{now.microsecond // 1000:03d}")
    return now.strftime(pattern)


def publish_rename(fs, retried, tmp_path: str, dest_dir: str, name: str,
                   durable: bool) -> str:
    """The publish tail shared by the thread worker and the process-mode
    child (procworkers._ChildWorker) so the protocol cannot drift:

    * millisecond timestamps can collide when one worker finalizes twice
      in the same tick; rename overwrites (os.replace / HDFS-adapter
      replace), which would silently destroy an already-acked published
      file — disambiguate with a numeric suffix instead (the suffix only
      ever appears under collision);
    * the destination is computed ONCE, outside the retried closure: a
      durable publish can fail AFTER its rename landed (the trailing dir
      fsync), and the retry must resume the SAME (src, dst) pair —
      recomputing a fresh timestamped name would orphan the renamed file
      and spin on the vanished tmp.

    ``retried(fn, label)`` is the caller's retry seam.  Returns the
    published destination path.

    The protocol itself is the target filesystem's capability
    (``io/fs.py`` ``publish_file``, the one decision point): a
    rename-capable sink gets the (durable) tmp→rename protocol — fsync
    tmp → atomic rename → fsync dest dir when ``durable``, so the ack
    that follows can never point at a file the disk forgot — while an
    object-store sink (``supports_rename`` False) publishes by
    completing its staged multipart upload at the destination key.
    Both are retry-safe for the fixed (src, dest) pair."""
    dest = f"{dest_dir}/{name}"
    seq = 0
    while fs.exists(dest):
        seq += 1
        stem, ext = (name.rsplit(".", 1) + [""])[:2]
        dest = (f"{dest_dir}/{stem}-{seq}.{ext}" if ext
                else f"{dest_dir}/{stem}-{seq}")

    def do() -> None:
        publish_file(fs, tmp_path, dest, durable=durable)
        logger.info("Published %s", dest)

    retried(do, "publish")
    return dest


def _rotation_batch_cap(max_file_size: int,
                        est_record_bytes: float = 64.0) -> int:
    """Rotation granularity: get_data_size() only moves per flushed batch,
    so both the poll batch and the encode batch are capped at ~1/16 of the
    size threshold (keeps the reference's ~1% overshoot bound at small
    maxFileSize without giving up vectorized encode at the 1 GiB default).
    One definition, used by the worker loop and the file opener."""
    return max(64, int(max_file_size / 16 / est_record_bytes))


class _RebalanceListener:
    """Writer-side cooperative-revocation hooks, fired on the consumer's
    fetcher thread (``SmartCommitConsumer.set_rebalance_listener``
    documents the surface + threading contract: nothing here may block).

    The revocation drain is a fetcher→worker seam: ``on_partitions_revoked``
    posts a fence request to every worker; each worker services it at its
    next loop iteration by flushing-and-publishing its open file early when
    the file holds a revoked partition's rows (the drain window keeps this
    member's commits for those partitions acceptable).  The consumer polls
    ``revocation_drained`` and only confirms the handoff once no worker
    holds revoked runs.  LOST partitions (session expiry) and drain
    timeouts switch to abandon: publishing would only earn a fenced
    commit, so the open file is dropped and the new owner redelivers."""

    def __init__(self, writer: "KafkaProtoParquetWriter") -> None:
        self._w = writer

    def _note(self, kind: str, **fields) -> None:
        rec = self._w._flightrec
        if rec is not None:
            rec.note(kind, **fields)

    def on_generation(self, gen: int, revoked, added) -> None:
        self._w._rebalances.mark()
        self._note("rebalance_generation", generation=gen,
                   revoked=sorted(revoked), added=sorted(added))

    def on_partitions_revoked(self, parts) -> None:
        self._note("rebalance_revoke_begin", partitions=sorted(parts))
        ps = frozenset(parts)
        for wk in self._w._workers:
            wk.request_fence(ps)

    def revocation_drained(self, parts) -> bool:
        ps = set(parts)
        for wk in self._w._workers:
            try:
                held = wk.held_runs()
            # lint: swallowed-exceptions ok — held_runs scrapes worker-
            # mutated lists lock-free (the ack-lag precedent); a torn read
            # just means "not drained yet", re-polled a tick later
            except RuntimeError:
                return False
            if any(p in ps for p, _, _ in held):
                return False
        for wk in self._w._workers:
            wk.fence_clear(ps)
        self._note("rebalance_drain_complete", partitions=sorted(parts))
        return True

    def on_revocation_timeout(self, parts) -> None:
        self._note("rebalance_drain_timeout", partitions=sorted(parts))
        ps = frozenset(parts)
        for wk in self._w._workers:
            wk.request_abandon(ps)

    def on_partitions_lost(self, parts) -> None:
        self._note("rebalance_partitions_lost", partitions=sorted(parts))
        ps = frozenset(parts)
        for wk in self._w._workers:
            wk.request_abandon(ps)


class KafkaProtoParquetWriter:
    """Streaming writer: Kafka topic -> rotated parquet files.  Construct via
    ``kpw_tpu.Builder``; lifecycle = ``start()`` / ``close()`` (Closeable
    parity, KPW.java:171-196)."""

    def __init__(self, b) -> None:  # b: runtime.builder.Builder
        self._b = b
        self.fs = b._filesystem
        self.target_dir = b._target_dir.rstrip("/")
        self.columnarizer = ProtoColumnarizer(b._proto_class)
        self.properties = b.writer_properties()
        self._encoder_factory = self._make_encoder_factory(b._backend)
        # one retry policy instance for the writer's IO seams (workers +
        # consumer broker IO): infinite-attempt backoff with fatal-errno
        # classification by default; Builder.retry_policy overrides
        self.retry_policy = b._retry_policy or RetryPolicy()
        # backpressure autotuning (opt-in): one tuner shared by the
        # consumer's fetch loop (fetch size, queue depth) and the workers'
        # poll sizing, all derived from measured stage rates
        self.autotuner = (IngestAutotuner(b._fetch_max_records,
                                          b._max_queued_records)
                          if b._autotune else None)
        self.consumer = SmartCommitConsumer(
            broker=b._broker,
            group_id=b._group_id,
            page_size=b._offset_tracker_page_size,
            max_open_pages_per_partition=b._offset_tracker_max_open_pages,
            max_queued_records=b._max_queued_records,
            fetch_max_records=b._fetch_max_records,
            retry_policy=self.retry_policy,
            batch_ingest=b._batch_ingest,
            autotuner=self.autotuner,
            queue_listener=getattr(b, "_queue_listener", None),
            drain_deadline_s=getattr(b, "_rebalance_drain_deadline", 5.0),
        )
        self.consumer.subscribe(b._topic)
        # cooperative-rebalance seam: revocations fence the workers' open
        # files through the drain window before the consumer confirms the
        # handoff.  Registered unconditionally — the consumer only fires
        # it when the broker runs group coordination.  Process mode uses
        # the same listener: _ProcWorkerSlot duck-types the fence surface
        # (request_fence / request_abandon / fence_clear / held_runs) and
        # forwards the fence as a `revoke` ring-protocol descriptor; the
        # coordinated heartbeat stays parent-owned — children never talk
        # to the broker.
        self.consumer.set_rebalance_listener(_RebalanceListener(self))
        self._workers: list = []
        self._started = False
        self._closed = False
        # process-parallel mode (Builder.process_workers): the pool owns
        # the shared-memory ring + dispatcher/collector threads; its
        # slots ARE self._workers, so supervision/watchdog/stats operate
        # on process slots through the same surface as threads
        self._procpool: ProcessWorkerPool | None = None
        # supervision state: restart counts per worker index (kept across
        # replacements), the death-notice the supervisor sleeps on, and the
        # terminal verdict once every restart budget is exhausted
        self._restart_counts: list[int] = (
            [0] * (b._proc_workers or b._thread_count))
        self._dead_notice = threading.Event()
        self._close_event = threading.Event()
        self._supervisor: threading.Thread | None = None
        self._terminal: WriterFailedError | None = None
        # metrics (registered iff a registry is supplied — KPW.java:144-151 —
        # but always counted for the programmatic getters :201-210)
        reg = b._metric_registry
        self._written_records = reg.meter(M.WRITTEN_RECORDS_METER) if reg else M.Meter()
        self._written_bytes = reg.meter(M.WRITTEN_BYTES_METER) if reg else M.Meter()
        self._flushed_records = reg.meter(M.FLUSHED_RECORDS_METER) if reg else M.Meter()
        self._flushed_bytes = reg.meter(M.FLUSHED_BYTES_METER) if reg else M.Meter()
        self._file_size_histogram = (reg.histogram(M.FILE_SIZE_HISTOGRAM)
                                     if reg else M.Histogram())
        # rotation-cause meters + pull-sampled gauges (observability layer;
        # the reference has neither — its only rotation evidence is the
        # published file names).  The gauges are function-backed: the live
        # structures are read only when the registry is scraped.
        self._rotated_size = reg.meter(M.ROTATED_SIZE_METER) if reg else M.Meter()
        self._rotated_time = reg.meter(M.ROTATED_TIME_METER) if reg else M.Meter()
        # consumer-group rebalance meters: generation bumps seen, files
        # rotated early to drain a revoked partition, acks the broker
        # fenced (stale generation), open files abandoned for LOST
        # partitions
        self._rebalances = reg.meter(M.REBALANCES_METER) if reg else M.Meter()
        self._rotated_revoke = (reg.meter(M.ROTATED_REVOKE_METER)
                                if reg else M.Meter())
        self._fenced_acks = reg.meter(M.FENCED_ACKS_METER) if reg else M.Meter()
        self._fence_abandons = (reg.meter(M.FENCE_ABANDONS_METER)
                                if reg else M.Meter())
        # robustness meters — always counted (satellite: worker death must
        # be visible even without supervision enabled)
        self._retries = reg.meter(M.RETRIES_METER) if reg else M.Meter()
        self._retry_backoff_ms = (reg.meter(M.RETRY_BACKOFF_MS_METER)
                                  if reg else M.Meter())
        self._failed = reg.meter(M.FAILED_METER) if reg else M.Meter()
        self._restarts = reg.meter(M.RESTARTS_METER) if reg else M.Meter()
        self._tmp_swept = reg.meter(M.TMP_SWEPT_METER) if reg else M.Meter()
        # durability meters + the recovery manifest (what the startup pass
        # verified/quarantined, surfaced verbatim in stats()["recovery"])
        # query-ready-files meters: published files carrying page-index
        # sections, and serialized bloom bytes landed in them
        self._indexed = reg.meter(M.INDEXED_METER) if reg else M.Meter()
        self._bloom_bytes_meter = (reg.meter(M.BLOOM_BYTES_METER)
                                   if reg else M.Meter())
        # nogil-assembly meters: chunks/pages assembled by the GIL-released
        # native call (native/src/assemble.cc) across published files
        self._native_asm_chunks = (reg.meter(M.NATIVE_ASM_CHUNKS_METER)
                                   if reg else M.Meter())
        self._native_asm_pages = (reg.meter(M.NATIVE_ASM_PAGES_METER)
                                  if reg else M.Meter())
        # adaptive-encoding observability: the most recent published
        # file's per-column chooser decisions (core/select_encoding.py) —
        # dotted path -> chosen encoding + trigger stats, per-file pinned
        self._last_encoding_info: dict = {}
        self._verified = reg.meter(M.VERIFIED_METER) if reg else M.Meter()
        self._verify_failed = (reg.meter(M.VERIFY_FAILED_METER)
                               if reg else M.Meter())
        self._quarantined = (reg.meter(M.QUARANTINED_METER)
                             if reg else M.Meter())
        self._recovery_manifest: dict = {"verified_files": 0,
                                         "quarantined_files": []}
        # degraded-operation state: the hung-IO watchdog (started at
        # start() when configured), and the fatal-errno pause bookkeeping
        # (worker index -> {cause, since}; workers enter/exit under _b's
        # degraded_mode, the paused gauge counts the live set)
        self._watchdog_obj: Watchdog | None = None
        self._stalled = reg.meter(M.STALLED_METER) if reg else M.Meter()
        # partitioned output: records route to per-partition open files
        # ahead of file assignment (runtime/partition.py); evictions count
        # LRU close-and-publish past the open-partitions bound.  The
        # compaction service (io/compact.py) is built at start() when
        # Builder.compaction is configured.
        self.partitioner = b._partitioner
        self._partitions_evicted = (reg.meter(M.PARTITIONS_EVICTED_METER)
                                    if reg else M.Meter())
        # multi-tenant bulkhead seam (runtime/multiwriter.py): the tenant
        # name + shared quota ledger a MultiWriter binds via bind_tenant
        # (None on a plain single-route writer — zero cost), the
        # open-file-budget eviction meter, and the dead-letter meters —
        # the canonical one aggregates across routes on a shared
        # registry, the local one keeps this route's own count
        self._tenant: str | None = None
        self._tenant_ledger = None
        self._tenant_files_evicted = (reg.meter(M.TENANT_FILES_EVICTED_METER)
                                      if reg else M.Meter())
        self._deadlettered = (reg.meter(M.DEADLETTER_METER)
                              if reg else M.Meter())
        self._deadletter_route = M.Meter()
        # end-to-end ack latency: batch-ingest wall time -> durable ack,
        # observed on the consumer's ack path (the ingest stamp rides the
        # queue and, in process mode, the ring unit descriptor).  Dual
        # histograms like the dead-letter meters: the canonical one
        # merges every route on a shared registry, the local one keeps
        # this route's own distribution for the per-tenant block.
        self._ack_latency = (reg.histogram(M.ACK_LATENCY_HISTOGRAM)
                             if reg else M.Histogram())
        self._ack_latency_route = M.Histogram()
        self.consumer.set_latency_observer(self._observe_ack_latency)
        self._compactor: Compactor | None = None
        self._paused: dict[int, dict] = {}
        self._pause_lock = threading.Lock()
        self._pause_count = 0
        self._resume_count = 0
        self._paused_total_s = 0.0
        self._last_close_report: dict | None = None
        # cross-process telemetry plane (runtime/telemetry.py): the
        # merged child-counter view + multi-pid trace merger are built
        # at start() in process mode; the crash flight recorder is built
        # HERE so pre-start faults (startup-verify quarantines) land in
        # the black box too
        self._child_telemetry: ChildTelemetry | None = None
        self.trace_merger: tracing.MultiProcessTrace | None = None
        self._flightrec: FlightRecorder | None = None
        if b._flightrec:
            self._flightrec = FlightRecorder(
                b._flightrec_dir or self.target_dir,
                b._instance_name,
                meter=(reg.meter(M.FLIGHTREC_DUMPS_METER)
                       if reg else M.Meter()))
            self._flightrec.set_gather(self._flightrec_gather)
        # object-store sink: bind the canonical request/byte/part meters
        # + the bandwidth gauge to the registry so both generic exporters
        # render them (io/objectstore.py holds and marks them)
        if reg and hasattr(self.fs, "bind_registry"):
            self.fs.bind_registry(reg)
        if reg:
            reg.gauge(M.PAUSED_GAUGE, lambda: len(self._paused))
            reg.gauge(M.ACK_LAG_GAUGE,
                      lambda: self.ack_lag()["unacked_records"])
            reg.gauge(M.ACK_AGE_GAUGE,
                      lambda: self.ack_lag()["oldest_unacked_age_s"])
            reg.gauge(M.CONSUMER_QUEUE_DEPTH_GAUGE, self.consumer.queue_depth)
            reg.gauge(M.WORKERS_ALIVE_GAUGE,
                      lambda: sum(1 for w in self._workers if w.alive()))
            reg.gauge(M.PARTITIONS_OPEN_GAUGE,
                      lambda: sum(len(w._part_files) for w in self._workers))
        # tracing owned by this writer when the Builder asked for it
        # (installed at start(), uninstalled at close() iff still ours)
        self.stage_timer: tracing.StageTimer | None = None
        self.span_recorder: tracing.SpanRecorder | None = None

    def bind_tenant(self, tenant: str, ledger) -> None:
        """Join this writer to a multi-tenant quota ledger
        (``runtime/multiwriter.py``) as ``tenant``: the open-file-budget
        enforcement (``_file_budget_exceeded``) starts consulting the
        ledger, and the tenant block appears in stats()."""
        self._tenant = tenant
        self._tenant_ledger = ledger

    def _file_budget_exceeded(self) -> bool:
        """True when this writer's tenant is at its open-file budget
        (the PR-8 LRU bound generalized across the route's workers) —
        the worker about to open one more file evicts its own LRU
        first.  Always False on an unbound (single-route) writer."""
        led = self._tenant_ledger
        return led is not None and led.files_over_budget(self._tenant)

    def _make_encoder_factory(self, backend):
        if backend == "cpu" or backend is None:
            return lambda: None  # ParquetFileWriter builds the CPU encoder
        if backend in ("tpu", "native", "auto", "mesh"):
            if backend == "tpu":  # fail fast at construction, not in a worker
                try:
                    from ..ops import backend as _ops_backend  # noqa: F401
                except ImportError as e:
                    raise NotImplementedError(
                        "TPU encoder backend unavailable in this build") from e
            if backend == "mesh":  # same fail-fast: a worker-thread
                # ImportError is not retried and would kill workers silently
                try:
                    from ..parallel import mesh_encoder as _mesh  # noqa: F401
                except ImportError as e:
                    raise NotImplementedError(
                        "mesh encoder backend unavailable in this build") from e
            from .select import make_encoder

            opts = self.properties.encoder_options()
            return lambda: make_encoder(opts, backend)
        if callable(getattr(backend, "encode", None)):
            return lambda: backend
        raise ValueError(f"unknown encoder backend: {backend!r}")

    # -- lifecycle (KPW.java:171-196) --------------------------------------
    def start(self) -> None:
        if self._started:
            raise ValueError("already started")
        self._started = True
        logger.info("Starting tpu parquet writer '%s'", self._b._instance_name)
        if self._b._tracing:
            # process-wide install (the stage() seam is global); the writer
            # owns these instances and removes them at close() unless
            # something else replaced them first
            self.stage_timer = tracing.StageTimer()
            self.span_recorder = tracing.SpanRecorder(
                capacity=self._b._trace_span_capacity)
            tracing.set_tracer(self.stage_timer)
            tracing.set_span_recorder(self.span_recorder)
        if self._b._clean_abandoned_tmp:
            self._gc_abandoned_tmp()
        if self._b._verify_on_startup:
            self._verify_published()
        self.consumer.start()
        if self._b._proc_workers:
            self._procpool = ProcessWorkerPool(self)
            self._workers = self._procpool.slots
            self._procpool.start()
            pool = self._procpool
            # merged child-counter view over the pool's shm TM cells:
            # every slot index stays a readable cell (dead-but-unbanked
            # cells keep counting until respawn/finalize banks them, so
            # the merged totals are monotonic across child restarts)
            self._child_telemetry = ChildTelemetry(
                pool.ring, lambda: range(len(pool.slots)))
            if self.span_recorder is not None:
                # multi-pid timeline: children drain their span rings over
                # the ack channel; the merger aligns them on epoch_wall
                self.trace_merger = tracing.MultiProcessTrace(
                    self.span_recorder)
            reg = self._b._metric_registry
            if reg:
                reg.gauge(M.PROC_RING_SLOTS_GAUGE, lambda: pool.ring.slots)
                reg.gauge(M.PROC_RING_FREE_GAUGE, pool.ring_free)
                reg.gauge(M.PROC_INFLIGHT_GAUGE,
                          lambda: sum(s.inflight_units()
                                      for s in pool.slots))
                reg.gauge(M.PROC_RSS_GAUGE,
                          lambda: sum(s.rss_bytes() for s in pool.slots))
                reg.gauge(M.PROC_ALIVE_GAUGE,
                          lambda: sum(1 for s in pool.slots if s.alive()))
                # child-origin counters, merged banked+live at scrape
                # time: one parent-side registry_to_prometheus() /
                # registry_to_json() call covers the whole process tree
                ct = self._child_telemetry
                reg.gauge(M.CHILD_WRITTEN_RECORDS_GAUGE,
                          lambda: ct.field("written_records"))
                reg.gauge(M.CHILD_FLUSHED_RECORDS_GAUGE,
                          lambda: ct.field("flushed_records"))
                reg.gauge(M.CHILD_STAGE_SECONDS_GAUGE,
                          lambda: ct.field("stage_time_us") / 1e6)
                reg.gauge(M.CHILD_SPANS_GAUGE,
                          lambda: ct.field("spans_recorded"))
                reg.gauge(M.CHILD_SPANS_DROPPED_GAUGE,
                          lambda: ct.field("spans_dropped"))
                # child-side rebalance activity in the same merged scrape
                reg.gauge(M.CHILD_REBALANCE_FENCED_GAUGE,
                          lambda: ct.field("rebalance_fenced"))
                reg.gauge(M.CHILD_REBALANCE_ABANDONED_GAUGE,
                          lambda: ct.field("rebalance_abandoned"))
        else:
            for i in range(self._b._thread_count):
                w = _Worker(self, i)
                self._workers.append(w)
                w.start()
        if self._b._supervise:
            self._supervisor = threading.Thread(
                target=self._supervise_loop,
                name=f"KPW-supervisor-{self._b._instance_name}",
                daemon=True)
            self._supervisor.start()
        if self._b._watchdog:
            self._watchdog_obj = Watchdog(
                lambda: list(self._workers),
                deadline_s=self._b._io_stall_deadline,
                poll_interval_s=self._b._watchdog_poll,
                on_stall=self._on_watchdog_stall)
            self._watchdog_obj.start()
        if self._b._compaction:
            self._compactor = Compactor(
                self.fs, self.target_dir, self._b._proto_class,
                self.properties, registry=self._b._metric_registry,
                instance_name=self._b._instance_name,
                **self._b._compaction)
            self._compactor.start()

    def _gc_abandoned_tmp(self) -> None:
        """Remove .tmp files left by a previous run of THIS instance name
        (the reference never GCs these — SURVEY.md §3.5; opt-in because a
        second live writer sharing the instance name would lose its open
        file).  Scoped to the ``{instance}_`` prefix so other instances
        writing to the same target directory are untouched."""
        tmp_dir = f"{self.target_dir}/tmp"
        # strict tmp-name shape '{instance}_{worker}_{rand}.tmp' — a bare
        # prefix test would also match instance names that extend ours
        # (e.g. 'ingest' deleting live 'ingest_backup_0_*.tmp')
        pat = re.compile(
            re.escape(self._b._instance_name) + r"_\d+_\d+\.tmp$")
        try:
            # recursive: partitioned mode keeps its tmps under per-partition
            # subdirs (tmp/{partition}/...); the basename pattern still
            # scopes the sweep to THIS instance's worker files
            stale = [p for p in self.fs.list_files(tmp_dir, extension=".tmp",
                                                   recursive=True)
                     if pat.fullmatch(p.rsplit("/", 1)[-1])]
        except FileNotFoundError:
            return
        swept = 0
        for p in stale:
            try:
                self.fs.delete(p)
                self._tmp_swept.mark()
                swept += 1
                logger.info("Removed abandoned tmp file %s", p)
            except OSError:
                logger.warning("Could not remove abandoned tmp file %s", p)
        if swept and self._flightrec is not None:
            # rebalance-drill evidence: a restarted instance aborting the
            # dead instance's debris (incl. SIGKILLed proc-mode children's
            # tmps — their '{instance}_{worker}_{rand}.tmp' names match)
            self._flightrec.note("rebalance_orphan_swept", files=swept)

    def _verify_published(self) -> None:
        """Startup recovery, the read-back half of the durability story:
        structurally verify every published ``.parquet`` under the target
        dir (``tmp/`` and ``quarantine/`` excluded) with the independent
        verifier and move every failure to ``{target_dir}/quarantine/`` —
        moved, NEVER deleted: a torn final may still hold recoverable row
        groups, and deleting data on a heuristic is how recovery tools
        destroy evidence.  A verify failure here is expected exactly once
        per torn publish (power cut mid-rename with durability off, a
        crash-window tear); the quarantined records were by construction
        never acked OR are redelivered duplicates, so removing the file
        from the published set preserves at-least-once.  The manifest of
        what happened lands in ``stats()['recovery']``."""
        reports = verify_dir(self.fs, self.target_dir)
        for rep in reports:
            if rep.ok:
                self._verified.mark()
            else:
                self._verify_failed.mark()
                qpath = self._quarantine(rep.path)
                self._recovery_manifest["quarantined_files"].append({
                    "path": rep.path,
                    "quarantined_to": qpath,
                    "errors": list(rep.errors[:5]),
                })
        self._recovery_manifest["verified_files"] = sum(
            1 for r in reports if r.ok)

    def _quarantine(self, path: str) -> str:
        """Move a condemned file to ``{target_dir}/quarantine/`` (same
        filesystem, atomic rename; name collisions get a numeric suffix).
        Returns the quarantine path."""
        qdir = f"{self.target_dir}/quarantine"
        self.fs.mkdirs(qdir)
        name = path.rsplit("/", 1)[-1]
        dest = f"{qdir}/{name}"
        seq = 0
        while self.fs.exists(dest):
            seq += 1
            dest = f"{qdir}/{name}.{seq}"
        self.fs.rename(path, dest)
        self._quarantined.mark()
        logger.warning("Quarantined structurally-invalid file %s -> %s",
                       path, dest)
        if self._flightrec is not None:
            self._flightrec.note("quarantine", path=path,
                                 quarantined_to=dest)
            self._flightrec.dump("quarantine", path=path,
                                 quarantined_to=dest)
        return dest

    # -- degraded operation: watchdog + pause/resume -------------------------
    def _on_watchdog_stall(self, index: int, worker: "_Worker",
                           age: float, label: str | None) -> None:
        """One stall episode crossed the deadline: meter it, and — opt-in
        — condemn the stuck worker so the supervisor restarts the slot
        (redelivery preserves at-least-once) and tell a failover
        filesystem its primary hangs (a hang never raises an errno, so
        the composite cannot see it on its own)."""
        self._stalled.mark()
        if self._flightrec is not None:
            self._flightrec.note("watchdog_stall", worker=index,
                                 stalled_stage=label or "io",
                                 stall_age_s=round(age, 3))
        logger.error(
            "watchdog: worker %d stalled %.1fs in %s (deadline %.1fs)",
            index, age, label or "io", self._b._io_stall_deadline)
        if not self._b._abandon_stalled:
            return
        if hasattr(self.fs, "declare_primary_down"):
            self.fs.declare_primary_down(
                f"worker {index} IO hung {age:.1f}s in {label or 'io'}")
        self._condemn_worker(index, worker, age, label)

    def _condemn_worker(self, index: int, w: "_Worker", age: float,
                        label: str | None) -> None:
        # condemn the worker the watchdog actually SCANNED: if the slot
        # was replaced meanwhile (hung call returned, worker died for
        # real, supervisor restarted it), condemning the fresh occupant
        # would burn a restart on a healthy worker
        if (index >= len(self._workers) or self._workers[index] is not w
                or w.failed or w.condemned):
            return
        w.condemn(f"stalled: IO hung {age:.1f}s in {label or 'io'} "
                  f"(> io_stall_deadline "
                  f"{self._b._io_stall_deadline}s); abandoned by watchdog")
        self._failed.mark()
        if self._flightrec is not None:
            # the black box: what was the tree doing when the watchdog
            # abandoned this slot, and which stage was it stuck in
            self._flightrec.dump("watchdog_stall_kill",
                                 stalled_stage=label or "io",
                                 worker=index, stall_age_s=round(age, 3))
        self._notify_worker_death()

    def _enter_pause(self, index: int, exc: BaseException) -> None:
        with self._pause_lock:
            self._paused[index] = {"cause": repr(exc),
                                   "since": time.monotonic()}
            self._pause_count += 1
        logger.error(
            "worker %d PAUSED on fatal sink condition (%r); intake stops, "
            "probing for recovery", index, exc)
        if self._flightrec is not None:
            # best-effort stage attribution: a sink OSError's message
            # often names the failing op ("injected fault: write call
            # #6", "flush of ..."); a bare errno degrades to "sink"
            stage_name = "sink"
            text = str(exc)
            for op in ("open", "write", "flush", "close", "publish",
                       "rename"):
                if op in text:
                    stage_name = op
                    break
            self._flightrec.note("fatal_sink_pause", worker=index,
                                 stalled_stage=stage_name, cause=repr(exc))
            self._flightrec.dump("fatal_sink_pause",
                                 stalled_stage=stage_name, worker=index,
                                 cause=repr(exc))

    def _exit_pause(self, index: int) -> None:
        with self._pause_lock:
            info = self._paused.pop(index, None)
            if info is not None:
                self._paused_total_s += time.monotonic() - info["since"]
                self._resume_count += 1
        logger.warning("worker %d resumed from pause", index)

    def _probe_sink(self, index: int) -> bool:
        """One write-path probe against the sink: the paused worker's
        recovery test.  Create + write + close + delete under the tmp dir
        — the same op mix whose fatal failure caused the pause."""
        path = (f"{self.target_dir}/tmp/"
                f".probe_{self._b._instance_name}_{index}")
        try:
            self.fs.mkdirs(f"{self.target_dir}/tmp")
            with self.fs.open_write(path) as f:
                f.write(b"kpw pause probe")
            self.fs.delete(path)
            return True
        except OSError:
            return False

    # -- cross-process telemetry plane (runtime/telemetry.py) ----------------
    def _observe_ack_latency(self, seconds: float, count: int) -> None:
        """Consumer ack-path callback: one contiguous run of ``count``
        records became durable ``seconds`` after its batch was ingested.
        One histogram update per run, not per record — runs are the
        consumer's ack granularity, and per-record updates would just
        replicate one latency value ``count`` times into the reservoir.
        Never raises into the ack path."""
        try:
            self._ack_latency.update(seconds)
            self._ack_latency_route.update(seconds)
        except Exception:
            logger.exception("ack-latency observation failed (ignored)")

    def _bank_child_telemetry(self, index: int) -> None:
        """Fold a dead child's final shm counter cell into the banked
        totals (procworkers calls this before clearing the cell for the
        slot's successor, and at pool finalize).  No-op outside process
        mode."""
        if self._child_telemetry is not None:
            self._child_telemetry.bank(index)

    def _absorb_child_telemetry(self, widx: int, payload: dict) -> None:
        """One low-rate side-channel snapshot from child ``widx`` (the
        ``("telemetry", widx, payload)`` ack-queue descriptor): keep the
        registry view for stats() and merge the drained span batch into
        the multi-pid trace.  Never raises into the collector thread."""
        try:
            if self._child_telemetry is not None:
                self._child_telemetry.absorb_snapshot(widx, payload)
            spans = (payload.get("spans")
                     if isinstance(payload, dict) else None)
            if spans and self.trace_merger is not None:
                self.trace_merger.absorb(spans)
        except Exception:
            logger.exception("child telemetry absorb failed (ignored)")

    def _flightrec_gather(self) -> dict:
        """The flight recorder's live-state hook: recent spans (a
        non-draining snapshot — the final trace still gets them), merged
        child counters, ack lag, per-worker observability, the
        watchdog's stall set, and the full registry snapshot —
        everything a post-mortem needs to say what the tree was doing
        when the fault fired.  Exceptions here are the recorder's
        problem: dump() degrades to the event ring."""
        out: dict = {"ack": self.ack_lag(),
                     "workers": [w.observability() for w in self._workers]}
        rec = self.span_recorder
        if rec is not None:
            out["recent_spans"] = [
                {"name": n, "thread": tname, "tid": tid,
                 "start_s": round(st, 6), "duration_s": round(du, 6),
                 "attrs": at}
                for n, tname, tid, st, du, at in rec.snapshot()[-128:]]
        if self._child_telemetry is not None:
            out["children_merged"] = self._child_telemetry.totals()
        if self._watchdog_obj is not None:
            out["watchdog"] = self._watchdog_obj.snapshot()
        reg = self._b._metric_registry
        if reg is not None:
            out["metrics"] = registry_to_json(reg)
        return out

    # -- supervision (beyond the reference: a dead reference worker is a
    # silent log line until process restart) ---------------------------------
    def _notify_worker_death(self, index: int | None = None,
                             reason: str | None = None) -> None:
        """Wake the supervisor.  When the caller knows WHICH worker died
        unexpectedly (process mode: kill -9 / OOM leaves no goodbye
        message), the black box is dumped too, with the stalled stage
        read from the dead child's heartbeat cell — the cell survives
        the death and is only cleared later by the respawn, so the dump
        can name the op the child was inside when it was killed."""
        if self._flightrec is not None:
            if index is None:
                self._flightrec.note("worker_death")
            else:
                stage_name = "idle"
                try:
                    if self._procpool is not None:
                        stage_name = (self._procpool.ring.hb_label(index)
                                      or "idle")
                except Exception:
                    logger.exception(
                        "heartbeat attribution failed (stage=idle)")
                self._flightrec.note("worker_death", worker=index,
                                     reason=reason,
                                     stalled_stage=stage_name)
                self._flightrec.dump("worker_death",
                                     stalled_stage=stage_name,
                                     worker=index, reason=reason)
        self._dead_notice.set()

    def _make_worker(self, i: int):
        """Replace worker slot ``i`` with a fresh (not yet started) one —
        a thread ``_Worker`` or, in process mode, a respawned
        ``_ProcWorkerSlot`` (the pool reclaims the dead child's un-drained
        ring slots first).  Both land in ``self._workers[i]``."""
        if self._procpool is not None:
            return self._procpool.respawn_slot(i)
        nw = _Worker(self, i)
        self._workers[i] = nw
        return nw

    def _supervise_loop(self) -> None:
        """Detect dead workers and restart them with capped restarts +
        exponential backoff.  A restarted worker's held (unacked) offsets
        are re-injected into the shared queue first — the records were never
        acked, so redelivery-by-restart preserves at-least-once.  When every
        worker is dead with its budget exhausted, the writer is terminally
        failed: close() raises WriterFailedError."""
        try:
            self._supervise_loop_inner()
        except RetryInterrupted:
            pass  # close() interrupted a redelivery retry
        except Exception:
            logger.exception("supervisor thread died; no further restarts")

    def _supervise_loop_inner(self) -> None:
        b = self._b
        while not self._close_event.is_set():
            if not self._dead_notice.wait(0.2):
                continue
            self._dead_notice.clear()
            for i in range(len(self._workers)):
                if self._close_event.is_set():
                    return
                w = self._workers[i]
                if not w.failed:
                    continue
                if self._restart_counts[i] >= b._max_worker_restarts:
                    self._check_terminal()
                    continue
                # let the dying worker finish its cleanup (file abandon)
                # before reading its held runs — unless it is HUNG in an
                # IO call that may never return (watchdog condemnation):
                # waiting 10 s per restart would serialize recovery behind
                # the very stall being recovered from.  Process slots join
                # the child process; a condemned one was SIGKILLed, so the
                # short join suffices either way.
                w.join(timeout=0.2 if w.condemned else 10)
                delay = min(b._restart_backoff
                            * (2 ** self._restart_counts[i]), 5.0)
                if self._close_event.wait(delay):
                    return
                self._restart_counts[i] += 1
                self._restarts.mark()
                # replacement FIRST, then redelivery: re-injection blocks
                # on the bounded queue when it is full, and with
                # thread_count=1 the replacement is the only consumer that
                # can make space — the reverse order deadlocks
                nw = self._make_worker(i)
                nw.start()
                try:
                    for part, start, end in w.held_runs():
                        self.consumer.redeliver_run(
                            part, start, end - start,
                            stop_event=self._close_event)
                except RetryInterrupted:
                    return  # close() during redelivery: clean exit
                logger.warning(
                    "supervisor: restarted worker %d (restart %d/%d) after "
                    "%s", i, self._restart_counts[i], b._max_worker_restarts,
                    w.exit_reason)
                # re-arm: another worker may have died while we restarted
                self._dead_notice.set()

    def _check_terminal(self) -> None:
        b = self._b
        exhausted = all(
            w.failed and self._restart_counts[i] >= b._max_worker_restarts
            for i, w in enumerate(self._workers))
        if exhausted and self._terminal is None:
            self._terminal = WriterFailedError(
                f"writer '{b._instance_name}': all {len(self._workers)} "
                f"worker(s) dead, restart budget "
                f"({b._max_worker_restarts}) exhausted; last errors: "
                f"{[w.exit_reason for w in self._workers]}")
            logger.error("%s", self._terminal)

    def healthy(self) -> bool:
        """Liveness verdict for callers that never read stats(): True while
        the writer is started, not closed, not terminally failed, every
        worker thread is alive and neither stalled past the watchdog
        deadline nor paused on a fatal sink condition, and the consumer's
        fetcher is running.  False during a supervised restart window (a
        worker is down until its replacement starts), while degraded
        (stalled/paused), and permanently once anything died for good."""
        if not self._started or self._closed or self._terminal is not None:
            return False
        if self._watchdog_obj is not None and self._watchdog_obj.any_stalled():
            return False
        if self._paused:
            return False
        if self._procpool is not None and not self._procpool.healthy():
            return False
        return (all(w.alive() and not w.failed for w in self._workers)
                and self.consumer.fetcher_alive())

    def close(self, deadline: float | None = None) -> dict | None:
        """Stop the writer.  ``deadline=None`` (the default) keeps the
        historical semantics exactly: wait up to the fixed per-component
        timeouts, abandon every open tmp un-acked, raise the terminal
        verdict if there is one.

        ``deadline=<seconds>`` bounds the WHOLE shutdown: each join gets
        only the remaining budget, a worker still parked in a hung IO
        call past its slice is left behind (daemon thread; its open tmp
        is NOT touched — the hung thread owns the sink — and stays
        un-published/un-acked, swept and redelivered on the next start),
        and close() returns a report of what was flushed vs abandoned
        instead of blocking forever behind a stuck pipeline.  Un-hangable
        by construction: no step waits longer than the remaining budget
        (pinned by ``test_close_deadline_returns_under_hung_write``).

        A terminally-failed writer still raises ``WriterFailedError``
        (the PR-3 contract: terminal failure must never masquerade as a
        clean shutdown) — deadline or not; the report, including its
        ``terminal`` field, remains retrievable from a second ``close()``
        call, which returns it without re-raising.
        """
        if self._closed:
            return self._last_close_report
        t0 = time.monotonic()
        t_end = None if deadline is None else t0 + max(0.0, deadline)

        def rem(default: float) -> float:
            if t_end is None:
                return default
            return max(0.0, min(default, t_end - time.monotonic()))

        self._closed = True
        self._close_event.set()
        if self._watchdog_obj is not None:
            self._watchdog_obj.close(timeout=rem(5))
        if self._compactor is not None:
            # pending merges are crash-recoverable by the plan protocol;
            # nothing to flush here beyond stopping the scan loop
            self._compactor.close(timeout=rem(5))
        if self._supervisor is not None:
            self._supervisor.join(timeout=rem(30))
        if self._procpool is not None:
            # stop dispatch FIRST: no new units reach the ring while the
            # children drain their queues and exit on poison
            self._procpool.close(timeout=rem(10))
        hung_workers: list[int] = []
        for w in self._workers:
            # deadline mode never abandons a file whose (possibly hung)
            # thread may still be inside the sink — the default mode keeps
            # the historical behavior verbatim
            clean = w.close(timeout=rem(30),
                            abandon_if_hung=(deadline is None))
            if not clean:
                hung_workers.append(w.index)
        if self._procpool is not None:
            # children are joined (or killed): drain the last acks, stop
            # the collector, unlink the shared-memory ring
            self._procpool.finalize(timeout=rem(5))
        self.consumer.close(timeout=rem(10))
        report = {
            "deadline_s": deadline,
            "duration_s": round(time.monotonic() - t0, 3),
            "deadline_met": (t_end is None
                             or time.monotonic() <= t_end + 0.05),
            "flushed_records": self._flushed_records.count,
            "flushed_bytes": self._flushed_bytes.count,
            "hung_workers": hung_workers,
            "abandoned_unacked_records":
                self.ack_lag()["unacked_records"],
            # a worker hung before its first write still holds its polled
            # batch: those records are abandoned too (redelivered next
            # start), and the written-but-unacked gauge alone would say 0
            "abandoned_held_records": sum(
                e - s
                for w in self._workers if w.index in hung_workers
                for _, s, e in w.held_runs()),
            "terminal": (str(self._terminal)
                         if self._terminal is not None else None),
        }
        self._last_close_report = report
        if hung_workers:
            logger.error(
                "close(deadline=%s): worker(s) %s still hung in IO at the "
                "deadline; their open tmp files were left un-published "
                "(%d written-but-unacked record(s) will be redelivered)",
                deadline, hung_workers, report["abandoned_unacked_records"])
        if self.span_recorder is not None:
            if self._b._trace_path:
                try:
                    # the multi-pid merger (process mode) writes ONE
                    # timeline covering parent + children, aligned on
                    # epoch_wall; child span batches were absorbed by the
                    # collector up through finalize() above
                    sink = self.trace_merger or self.span_recorder
                    sink.write_chrome_trace(self._b._trace_path)
                    logger.info("Wrote span timeline to %s",
                                self._b._trace_path)
                except OSError:
                    logger.exception("Could not write trace to %s",
                                     self._b._trace_path)
            # uninstall only what is still ours: a second writer (or the
            # user) may have installed its own tracer meanwhile
            if tracing.get_span_recorder() is self.span_recorder:
                tracing.set_span_recorder(None)
            if tracing.get_tracer() is self.stage_timer:
                tracing.set_tracer(None)
        logger.info("Writer '%s' closed", self._b._instance_name)
        if self._terminal is not None:
            # a writer whose every worker died with the restart budget
            # exhausted must not report a clean shutdown — the caller is
            # the only one left who can act (alert, restart the process)
            raise self._terminal
        return report

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    def hard_kill(self) -> None:
        """In-process kill -9 analog AT THE PROTOCOL LEVEL (the real
        SIGKILL drill is tests/crash_child.py): stop every thread without
        flushing, publishing, or leaving the group — the broker learns of
        the death only through the missed heartbeat window (session
        expiry), exactly like a machine that dropped off the network.
        Open tmp files stay on disk un-published, held runs are never
        acked (the surviving group members redeliver them after the
        expiry rebalance).  Python threads cannot be preempted
        mid-bytecode, so an ack already in flight completes atomically
        with its publish — a real SIGKILL could tear between rename and
        commit (an at-least-once duplicate); this analog cannot, and a
        straggler ack landing AFTER the session expired is fenced by the
        broker's generation check and un-published by the backstop."""
        if self._closed:
            return
        self._closed = True
        self._close_event.set()
        if self._watchdog_obj is not None:
            self._watchdog_obj.close(timeout=1)
        if self._procpool is not None:
            # whole-instance kill, process edition: the children get a
            # REAL SIGKILL (orphaned mid-file, tmps left on disk for the
            # restarted instance's startup sweep), the dispatcher and
            # collector stop abruptly (units in the ring abandoned
            # un-acked), and the ring is torn down for shm hygiene — the
            # segment is parent-owned and a dead instance must not leak
            # it.  No leave_group, no final commit: the group
            # coordinator must discover the death by session timeout.
            self._procpool._stop.set()
            for s in self._workers:
                try:
                    s._proc.kill()
                except (OSError, ValueError):
                    pass
            self.consumer.hard_kill()
            self._procpool._closed = True
            self._procpool._dispatcher.join(timeout=5)
            self._procpool._collector.join(timeout=5)
            for s in self._workers:
                s.join(timeout=5)
            self._procpool.ring.close()
            self._procpool.ring.unlink()
        else:
            for w in self._workers:
                w._stop.set()
            # no leave_group, no final commit: the group coordinator must
            # discover the death by session timeout
            self.consumer.hard_kill()
            for w in self._workers:
                w.join(timeout=5)
            for w in self._workers:
                # free pipeline threads + sinks; tmps stay un-published
                w._abandon_open_files("error")
        if self._flightrec is not None:
            self._flightrec.note("hard_kill",
                                 instance=self._b._instance_name)

    # -- observability (beyond the reference: SURVEY.md §5 had only
    # lifecycle logging) ----------------------------------------------------
    def ack_lag(self) -> dict:
        """The load-bearing at-least-once observable: records accepted
        (written into an open file) whose offsets have NOT been durably
        acked yet — they would be redelivered on a crash right now — and
        the age of the oldest such record's first write.  Zero lag means
        every accepted record's file has been published and its offsets
        committed."""
        now = time.time()
        lag = 0
        oldest: float | None = None
        for w in self._workers:
            lag += w._unacked_count
            ts = w._oldest_unacked_ts
            if ts is not None and (oldest is None or ts < oldest):
                oldest = ts
        return {
            "unacked_records": lag,
            "oldest_unacked_age_s": (round(now - oldest, 6)
                                     if oldest is not None else 0.0),
        }

    def stats(self) -> dict:
        """One pull-based snapshot of the whole pipeline, JSON-serializable
        by construction: meters (keyed by their canonical metric names),
        the file-size histogram, rotation-cause counts, ack lag, the
        health verdict + supervision block (worker liveness, death and
        restart counts, terminal failure), the recovery sweep count, the
        consumer's queue/tracker state, per-worker row-group pipeline
        gauges (stage busy seconds + queue depth / high-watermark / stall)
        plus per-worker retry/last-error accounting, and — when tracing is
        installed — the cumulative stage timers and span-buffer occupancy.
        written ≠ flushed ≠ acked: written counts records buffered into an
        open file, flushed counts records in published files, acked means
        the offsets are committed."""
        b = self._b
        out: dict = {
            "meters": {
                M.WRITTEN_RECORDS_METER: self._written_records.snapshot(),
                M.WRITTEN_BYTES_METER: self._written_bytes.snapshot(),
                M.FLUSHED_RECORDS_METER: self._flushed_records.snapshot(),
                M.FLUSHED_BYTES_METER: self._flushed_bytes.snapshot(),
                M.RETRIES_METER: self._retries.snapshot(),
                M.RETRY_BACKOFF_MS_METER: self._retry_backoff_ms.snapshot(),
                M.FAILED_METER: self._failed.snapshot(),
                M.RESTARTS_METER: self._restarts.snapshot(),
                M.TMP_SWEPT_METER: self._tmp_swept.snapshot(),
                M.VERIFIED_METER: self._verified.snapshot(),
                M.VERIFY_FAILED_METER: self._verify_failed.snapshot(),
                M.QUARANTINED_METER: self._quarantined.snapshot(),
                M.STALLED_METER: self._stalled.snapshot(),
                M.PARTITIONS_EVICTED_METER:
                    self._partitions_evicted.snapshot(),
                M.INDEXED_METER: self._indexed.snapshot(),
                M.BLOOM_BYTES_METER: self._bloom_bytes_meter.snapshot(),
                M.NATIVE_ASM_CHUNKS_METER:
                    self._native_asm_chunks.snapshot(),
                M.NATIVE_ASM_PAGES_METER:
                    self._native_asm_pages.snapshot(),
                M.DEADLETTER_METER: self._deadlettered.snapshot(),
            },
            "file_size": self._file_size_histogram.snapshot(),
            # end-to-end time-to-durable (seconds): batch ingest ->
            # published+acked, one reservoir update per acked run.  The
            # route-local histogram (this writer's own distribution,
            # independent of registry sharing) — the canonical
            # ACK_LATENCY_HISTOGRAM merges routes on a shared registry
            "ack_latency": self._ack_latency_route.snapshot(),
            "rotations": {
                "size": self._rotated_size.count,
                "time": self._rotated_time.count,
            },
            "ack": self.ack_lag(),
            "healthy": self.healthy(),
            "supervision": {
                "enabled": b._supervise,
                "max_restarts": b._max_worker_restarts,
                "workers_alive": sum(1 for w in self._workers if w.alive()),
                "workers_dead": sum(1 for w in self._workers if w.failed),
                "restart_counts": list(self._restart_counts),
                "restarts_total": sum(self._restart_counts),
                "terminal_failure": (str(self._terminal)
                                     if self._terminal is not None else None),
            },
            "recovery": {
                "tmp_swept": self._tmp_swept.count,
                "verified": self._verified.count,
                "verify_failed": self._verify_failed.count,
                "quarantined": self._quarantined.count,
                "manifest": {
                    "verified_files":
                        self._recovery_manifest["verified_files"],
                    "quarantined_files": [
                        dict(q) for q in
                        self._recovery_manifest["quarantined_files"]],
                },
            },
            "consumer": self.consumer.stats(),
            "workers": [w.observability() for w in self._workers],
        }
        # degraded-operation block: pause/resume accounting always (cheap,
        # and "not degraded" is itself load-bearing evidence), the
        # watchdog's live stall set when one is running, and the failover
        # composite's spill/reconcile snapshot when the sink is one
        now = time.monotonic()
        with self._pause_lock:
            out["degraded"] = {
                "enabled": b._degraded_mode,
                "paused_workers": [
                    {"worker": i, "cause": info["cause"],
                     "paused_age_s": round(now - info["since"], 3)}
                    for i, info in sorted(self._paused.items())],
                "pause_count": self._pause_count,
                "resume_count": self._resume_count,
                "paused_total_s": round(
                    self._paused_total_s
                    + sum(now - info["since"]
                          for info in self._paused.values()), 3),
            }
        if self._watchdog_obj is not None:
            out["watchdog"] = self._watchdog_obj.snapshot()
        if hasattr(self.fs, "failover_stats"):
            out["failover"] = self.fs.failover_stats()
        # object-store sink block (mirrors failover: only when the sink
        # is one): store request/byte accounting + the upload-pipelining
        # overlap breakdown (upload hidden under encode vs exposed at
        # close) — the evidence bench.py --objstore commits
        if hasattr(self.fs, "objectstore_stats"):
            out["objectstore"] = self.fs.objectstore_stats()
        # partitioned-output block always (like degraded: "not partitioned"
        # is itself evidence); the compactor block only when the service
        # is configured, mirroring watchdog/failover
        # query-ready-files block always (like partitions: "not indexed"
        # is itself evidence an operator wants visible)
        # nogil-assembly block always (same rationale: "assembly stayed in
        # Python" is itself evidence — e.g. an unsupported codec or a
        # missing extension on a box expected to have it)
        out["assembly"] = {
            "native_enabled": self.properties.native_assembly,
            "native_chunks": self._native_asm_chunks.count,
            "native_pages": self._native_asm_pages.count,
        }
        out["index"] = {
            "page_index": self.properties.write_page_index,
            "bloom_columns": (list(self.properties.bloom_columns)
                              if self.properties.bloom_columns is not None
                              else None),
            "sorting_columns": [list(s) for s in
                                self.properties.sorting_columns],
            "files_indexed": self._indexed.count,
            "bloom_bytes": self._bloom_bytes_meter.count,
        }
        # adaptive-encoding block always (same rationale: "everything
        # stayed PLAIN/dictionary" is itself evidence): the chooser config
        # plus the most recent published file's per-column decisions
        out["encodings"] = {
            "adaptive": self.properties.adaptive_encodings,
            "overrides": {k: encoding_name(v) for k, v in
                          (self.properties.encodings or {}).items()},
            "delta_fallback": self.properties.delta_fallback,  # legacy
            "last_file": self._last_encoding_info,
        }
        out["partitions"] = {
            "enabled": self.partitioner is not None,
            "max_open_per_worker": b._max_open_partitions,
            "open": sum(len(w._part_files) for w in self._workers),
            "evicted": self._partitions_evicted.count,
            "open_by_worker": [w.open_partitions() for w in self._workers],
        }
        if self._compactor is not None:
            out["compactor"] = self._compactor.compactor_stats()
        # multi-tenant block only when a MultiWriter bound this writer to
        # a shared quota ledger (mirrors watchdog/failover/compactor):
        # this route's tenant name, its quota snapshot, and its own
        # dead-letter count (the canonical meter aggregates across
        # routes on a shared registry)
        if self._tenant_ledger is not None:
            out["tenant"] = {
                "name": self._tenant,
                "quota": self._tenant_ledger.tenant_snapshot(self._tenant),
                "deadletter_records": self._deadletter_route.count,
            }
        # process-mode block only when the pool exists (mirrors
        # watchdog/failover/compactor): ring occupancy, per-child rss +
        # in-flight units + restart counts, dispatcher/collector counters
        if self._procpool is not None:
            out["procs"] = self._procpool.snapshot()
        # cross-process telemetry block (process mode): the merged
        # banked+live child counters plus each child's last side-channel
        # snapshot; and the flight recorder's state whenever one exists
        # ("no dumps yet" is itself evidence)
        if self._child_telemetry is not None:
            out["telemetry"] = self._child_telemetry.snapshot()
        if self._flightrec is not None:
            out["flightrec"] = self._flightrec.snapshot()
        # writer-OWNED tracing only: the process-global seam may hold a
        # different writer's (or the user's) instruments, and attributing
        # their timings to this writer would be misdirection — users who
        # installed their own tracer already hold its handle
        if self.stage_timer is not None:
            out["stages"] = self.stage_timer.summary()
        if self.span_recorder is not None:
            out["spans"] = {"buffered": len(self.span_recorder),
                            "dropped": self.span_recorder.dropped,
                            "capacity": self.span_recorder.capacity}
            if self.trace_merger is not None:
                # every pid the merged timeline covers (parent + every
                # child that shipped at least one span batch)
                out["spans"]["merged_pids"] = self.trace_merger.pids()
        return out

    def write_trace(self, path: str) -> None:
        """Dump the span timeline recorded so far as Chrome-trace JSON
        (requires Builder.tracing; close() also writes it when a
        trace_path was configured)."""
        if self.span_recorder is None:
            raise ValueError("tracing not enabled on this writer "
                             "(Builder.tracing)")
        (self.trace_merger or self.span_recorder).write_chrome_trace(path)

    # -- programmatic metrics (KPW.java:201-210) ---------------------------
    @property
    def total_written_records(self) -> int:
        return self._written_records.count

    @property
    def total_written_bytes(self) -> int:
        return self._written_bytes.count

    @property
    def total_flushed_records(self) -> int:
        return self._flushed_records.count

    @property
    def total_flushed_bytes(self) -> int:
        return self._flushed_bytes.count


class _Worker:
    """One writer thread: private current file, shared consumer
    (KPW.java:216-399)."""

    def __init__(self, parent: KafkaProtoParquetWriter, index: int) -> None:
        self.p = parent
        self.index = index
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run,
            name=f"KafkaProtoParquetWriter-{parent._b._instance_name}-{index}",
            daemon=True,
        )
        self.current_file: ParquetFile | None = None
        # partitioned mode (Builder.partition_by): partition path -> open
        # file, insertion order == LRU order (reinserted on every write);
        # bounded by max_open_partitions with close-and-publish eviction.
        # Mutated by this worker thread only; scrapes read it lock-free
        self._part_files: dict[str, ParquetFile] = {}
        # death visibility (satellite: a dead worker must be observable
        # even without supervision): set in the _run except path before the
        # thread exits, read by healthy()/stats()/the supervisor
        self.failed = False
        self.exit_reason: str | None = None
        # hung-IO visibility: every IO seam of this slot (the worker
        # thread's _retry calls AND the current file's pipelined IO
        # thread) publishes into this heartbeat; the watchdog ages the
        # oldest pending op.  `condemned` flips when the watchdog abandons
        # a hung slot: the thread may still be parked in the stuck call,
        # but it is already declared dead (failed=True), its held runs
        # redelivered and its slot restarted — if the hung call ever
        # returns, the zombie sees its stop event and exits WITHOUT
        # acking (duplicates allowed, loss impossible)
        self.heartbeat = Heartbeat()
        self.condemned = False
        # per-worker retry accounting fed by the policy's on_retry hook
        self.retries = 0
        self.backoff_s = 0.0
        self.last_error: str | None = None
        # acks held until publish, as contiguous runs [partition, start, end)
        # — poll batches arrive as runs, and per-record PartitionOffset
        # bookkeeping was a measurable slice of the hot loop
        self._written_runs: list[list[int]] = []
        # the poll batch currently being processed, as (partition, start,
        # count) runs: consumed from the queue but not yet folded into
        # _written_runs — on death these must be redelivered too, or the
        # commit frontier stalls behind them forever
        self._inflight_runs: list = []
        self._file_records = 0
        # encoded-bytes/record estimate carried across rotations so every
        # file (not just the first's successors) rotates tightly
        self._carry_est = 64.0
        # measured shred+append rate (records/s EWMA) and the poll batch
        # it produced — the worker half of backpressure autotuning
        self._proc_rate = 0.0
        self._last_poll_batch = 0
        # ack-lag accounting: records in _written_runs (written, not yet
        # acked) and when the oldest of them was first written.  Written by
        # this worker thread only; the parent's ack_lag() reads them
        # lock-free (a slightly stale int is fine for a gauge)
        self._unacked_count = 0
        self._oldest_unacked_ts: float | None = None
        # cumulative pipeline stats of rotated-away files, folded at each
        # finalize/abandon so high watermarks and stall time survive
        # rotation (a per-file snapshot alone would reset every ~1 GiB)
        self._pipe_totals: dict = {"files": 0, "split_assembly": False,
                                   "stage_busy_s": {}, "queues": {}}
        # cooperative-rebalance fence requests (ingest/consumer.py drain
        # protocol): frozensets of partition ids posted by the fetcher
        # thread's _RebalanceListener, serviced by THIS thread at the next
        # loop iteration (GIL-atomic reference swaps — same lock-free
        # single-writer discipline as the ack-lag fields).  ``_fence_req``
        # = flush-and-publish early (the drain window still accepts our
        # commits); ``_fence_abandon_req`` = the partitions are LOST, drop
        # the open file un-published (a publish would only earn a fenced
        # commit)
        self._fence_req: frozenset | None = None
        self._fence_abandon_req: frozenset | None = None

    def start(self) -> None:
        self._thread.start()

    def alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: float | None = None) -> None:
        """Common slot surface with the process-mode worker: the
        supervisor joins a dead slot before reading its held runs."""
        self._thread.join(timeout)

    def held_runs(self) -> list[tuple[int, int, int]]:
        """Every offset run this worker consumed but never acked, as
        (partition, start, end) — written-but-unpublished runs plus the
        in-flight poll batch.  Read by the supervisor AFTER joining the
        dead thread (single-writer discipline: only the worker thread
        mutates these)."""
        runs = [(p, s, e) for p, s, e in self._written_runs]
        runs.extend((p, s, s + c) for p, s, c in self._inflight_runs)
        return runs

    # -- cooperative-revocation fence (fetcher-thread setters) ---------------
    def request_fence(self, parts: frozenset) -> None:
        """Revoked partitions entered their drain window: flush-and-publish
        this worker's open file at the next loop iteration if it holds any
        of their rows."""
        cur = self._fence_req
        self._fence_req = parts if cur is None else frozenset(cur | parts)

    def request_abandon(self, parts: frozenset) -> None:
        """The partitions are LOST (session expiry / drain timeout):
        abandon their rows un-published — and supersede any pending flush
        request for them, which could no longer commit anyway."""
        cur = self._fence_abandon_req
        self._fence_abandon_req = (parts if cur is None
                                   else frozenset(cur | parts))
        req = self._fence_req
        if req is not None:
            self._fence_req = frozenset(req - parts) or None

    def fence_clear(self, parts) -> None:
        """Drain complete for ``parts``: retire their fence requests."""
        ps = frozenset(parts)
        req = self._fence_req
        if req is not None:
            self._fence_req = frozenset(req - ps) or None
        aband = self._fence_abandon_req
        if aband is not None:
            self._fence_abandon_req = frozenset(aband - ps) or None

    def _service_fence(self) -> None:
        """Service pending cooperative-revocation fence requests (posted
        by the fetcher thread's _RebalanceListener, drained here so only
        this thread ever touches the file/run state).

        Abandon first: LOST partitions' rows must not publish — drop the
        open file(s) un-published, clear every held run, and redeliver the
        runs this member still owns from a side thread (this worker is the
        queue consumer; the _pause_until_recovered precedent).  Then the
        flush flavor: revoked partitions with rows already in the open
        file force an early "revoke" rotation — publish + ack NOW, inside
        the drain window where the broker still accepts this member's
        commits for them — which is what lets the consumer confirm the
        handoff with zero lost and zero duplicated rows."""
        aband = self._fence_abandon_req
        if aband:
            held = self.held_runs()
            if any(p in aband for p, _, _ in held):
                retained = [(p, s, e) for p, s, e in held if p not in aband]
                dropped = sum(e - s for p, s, e in held if p in aband)
                self.p._fence_abandons.mark()
                rec = self.p._flightrec
                if rec is not None:
                    rec.note("rebalance_abandon", worker=self.index,
                             partitions=sorted(aband),
                             dropped_records=dropped,
                             retained_runs=len(retained))
                self._abandon_open_files("revoke")
                self._written_runs.clear()
                self._inflight_runs = []
                self._unacked_count = 0
                self._oldest_unacked_ts = None
                if retained:
                    threading.Thread(
                        target=self._redeliver_runs, args=(retained,),
                        name=f"KPW-fence-redeliver-{self.index}",
                        daemon=True).start()
            self._fence_abandon_req = None
        req = self._fence_req
        if req and any(r[0] in req for r in self._written_runs):
            if self.p.partitioner is not None:
                self._finalize_partitions("revoke")
            else:
                self._finalize_current_file("revoke")

    def _retry(self, fn, label: str = ""):
        """Policy-driven retry for this worker's IO: stop-aware, metered
        (retry count, backoff time, last error) via the on_retry hook.
        The whole call publishes a heartbeat-pending op — a call that
        never returns is a hang the watchdog can age; each retry attempt
        that DOES return re-stamps it via the hook (a live backoff loop
        is the retry policy's business, never a hang)."""
        hb_token = self.heartbeat.io_started(label or "io")
        try:
            return self.p.retry_policy.call(fn, stop_event=self._stop,
                                            on_retry=self._on_retry,
                                            label=label)
        finally:
            self.heartbeat.io_finished(hb_token)

    def _on_retry(self, attempt: int, exc: BaseException,
                  sleep_s: float) -> None:
        self.heartbeat.beat()
        self.retries += 1
        self.backoff_s += sleep_s
        self.last_error = repr(exc)
        self.p._retries.mark()
        self.p._retry_backoff_ms.mark(max(1, int(sleep_s * 1000)))

    def condemn(self, reason: str) -> None:
        """Watchdog abandon: declare this worker dead while its thread is
        (probably) still parked in a hung IO call.  The stop event makes
        an eventually-returning zombie exit without acking; `failed`
        makes the supervisor treat the slot exactly like a crashed
        worker (join times out fast, held runs redelivered, slot
        restarted).  The stuck tmp file is left alone — the hung thread
        owns the sink — and is swept un-acked on the next start."""
        self.condemned = True
        self.exit_reason = reason
        self.failed = True
        self._stop.set()

    def close(self, timeout: float = 30.0,
              abandon_if_hung: bool = True) -> bool:
        """Stop; the open tmp file is abandoned, its offsets never acked —
        those records are redelivered on restart (at-least-once;
        KPW.java:381-398 + SURVEY §3.5 note).  Abandoning also stops the
        file's pipeline threads.  Returns False when the thread is still
        alive after ``timeout`` (hung in IO); with
        ``abandon_if_hung=False`` (the deadline-bounded close) the open
        file is then left untouched — the hung thread owns the sink."""
        self._stop.set()
        self._thread.join(timeout=timeout)
        hung = self._thread.is_alive()
        if abandon_if_hung or not hung:
            for f in self._open_files():
                f.rotation_reason = "close"
                f.abandon()
                self._fold_pipe_stats(f)
            self.current_file = None
            self._part_files.clear()
        return not hung

    # -- loop (KPW.java:253-292) -------------------------------------------
    def _run(self) -> None:
        b = self.p._b
        try:
            # one appended batch must stay well under max_file_size or size
            # rotation loses its ~1% bound (same cap as the flush batch)
            poll_batch_base = max(64, b._batch_size)
            # wire fast path: flat schemas shred serialized payloads straight
            # to columnar via the C++ decoder — no Python message objects
            # (the round-1 streaming bottleneck); errors fall back to the
            # exact per-record Python path below, which owns the poison-pill
            # policies.  Only valid when the payload IS the serialized
            # message — a custom parser() transforms payloads, so it
            # disqualifies the raw-bytes path.
            # partitioning also disqualifies the wire path: routing needs
            # the parsed message, which the wire shredder never builds
            use_wire = (getattr(b, "_parser_is_default", False)
                        and self.p.columnarizer.wire_capable
                        and self.p.partitioner is None)
            # batch-native poll: drain RecordBatch views (contiguous buffer
            # + offsets, no Record materialization) straight into the wire
            # shredder — only meaningful when the wire path is live, since
            # the Python parse path needs Records anyway
            use_batch = use_wire and getattr(b, "_batch_ingest", True)
            while not self._stop.is_set():
                try:
                    self._loop_once(b, poll_batch_base, use_wire, use_batch)
                except (OSError, PipelineError) as e:
                    # degraded_mode: a fatal-classified sink condition
                    # (full disk, read-only remount) pauses this worker —
                    # probe until it heals, then resume — instead of dying
                    # into a restart that cannot fix it.  Anything else
                    # keeps the historical death semantics.
                    cause = self._pause_cause(e)
                    if cause is None:
                        raise
                    self._pause_until_recovered(cause)
        except RetryInterrupted:
            pass
        except Exception as e:
            self.exit_reason = repr(e)
            logger.exception("worker %d terminated", self.index)
            # a dying worker must not leak its open files' pipeline threads
            # or sinks; the tmps stay on disk un-published (at-least-once:
            # their offsets were never acked)
            try:
                self._abandon_open_files("error")
            finally:
                # visibility LAST: `failed` flips only after cleanup, so
                # the supervisor's join-then-read of held_runs() is safe.
                # A condemned (watchdog-abandoned) worker was already
                # declared dead and its slot restarted: the zombie must
                # not count a second death or wake the supervisor again
                if not self.condemned:
                    self.p._failed.mark()
                    self.failed = True
                    self.p._notify_worker_death()
        finally:
            # a condemned zombie that eventually escaped its hung call
            # exits through here holding open (unpublishable) files:
            # free their pipeline threads and sinks best-effort — the
            # slot's replacement is long since running
            if self.condemned:
                self._abandon_open_files("error")

    def _loop_once(self, b, poll_batch_base: int, use_wire: bool,
                   use_batch: bool = False) -> None:
        """One poll→parse→write→rotate iteration (the body of the
        reference's worker loop, KPW.java:253-292), extracted so the
        degraded-mode pause seam can wrap exactly one iteration."""
        if (self._fence_req is not None
                or self._fence_abandon_req is not None):
            self._service_fence()
        if self.p.partitioner is not None:
            return self._loop_once_partitioned(b, poll_batch_base)
        if (self.current_file is not None
                and self._is_file_timed_out(self.current_file)):
            self._finalize_current_file("time")
        # batch granularity follows the LIVE bytes/record estimate,
        # not the static 64 B guess: small-record streams (nested
        # cfg7-shaped, ~10 B/record encoded) were capped at 1/16 of
        # the 64 B-based record count — 4-5x smaller batches than
        # the size band needs, and per-batch shred/append overhead
        # dominated the measured rate (VERDICT r3 next #8)
        tuner = self.p.autotuner
        if tuner is not None:
            # autotuned poll sizing: this worker's own measured
            # processing rate over the tuner's poll horizon, instead of
            # the fixed batch_size constant
            poll_batch_base = tuner.poll_batch(self._proc_rate)
        poll_batch = min(poll_batch_base, _rotation_batch_cap(
            b._max_file_size, max(8.0, self._carry_est)))
        self._last_poll_batch = poll_batch
        if use_batch:
            items, runs = self.p.consumer.poll_many_batches(
                self._poll_cap(poll_batch))
            if not items:
                time.sleep(0.001)
                return
            t0 = time.perf_counter()
            # consumed from the queue: from here until these runs are
            # folded into _written_runs (or individually acked) they
            # are redeliverable only through held_runs()
            self._inflight_runs = runs
            if self._try_wire_items(items, runs):
                self._inflight_runs = []
                self._note_proc_rate(sum(c for _, _, c in runs), t0)
                if self._is_file_full(self.current_file):
                    self._finalize_current_file()
                return
            # wire fallback (a record the shredder could not prove clean):
            # materialize Records and re-run the batch on the exact
            # per-record path below, which owns the poison-pill policies
            recs = [r for it in items
                    for r in (it.to_records()
                              if isinstance(it, RecordBatch) else it)]
        else:
            recs, runs = self.p.consumer.poll_many_runs(
                self._poll_cap(poll_batch))
            if not recs:
                time.sleep(0.001)
                return
            t0 = time.perf_counter()
            self._inflight_runs = runs
            if use_wire and self._try_wire_items([recs], runs):
                self._inflight_runs = []
                self._note_proc_rate(len(recs), t0)
                if self._is_file_full(self.current_file):
                    self._finalize_current_file()
                return
        parsed = []  # (record, message) — parsed in bulk so the
        # per-record loop overhead amortizes (design capacity is
        # 300k rec/s/instance, KPW.java:463)
        nbytes = 0
        for rec in recs:
            try:
                parsed.append((rec, b._parser(rec.value)))
                nbytes += len(rec.value)
            except Exception:
                self._handle_record_error(rec, "unparseable")
        if not parsed:
            self._inflight_runs = []  # every record was acked above
            return
        if self.current_file is None:
            self._open_file()
        # append is pure memory; only the (idempotent) flush retries
        self.current_file.append_records([m for _, m in parsed])
        self._retry(self.current_file.flush_if_full, "flush")
        self._note_written(r for r, _ in parsed)
        self._inflight_runs = []
        self.p._written_records.mark(len(parsed))
        self.p._written_bytes.mark(nbytes)
        self._file_records += len(parsed)
        if self._is_file_full(self.current_file):
            self._finalize_current_file()

    def _handle_record_error(self, rec, what: str) -> None:
        """One record the pipeline cannot place — unparseable bytes, or a
        partitioner that raised/returned garbage — under the
        ``on_parse_error`` policy (reference poison-pill parity,
        KPW.java:271-275).  Call from inside the except handler: the
        ``raise`` policy re-raises the active exception."""
        b = self.p._b
        if b._on_parse_error == "dead_letter":
            logger.exception("Dead-lettering %s record %s/%s", what,
                             rec.partition, rec.offset)
            # durability first, like the main path: the raw payload lands
            # in the dead-letter file before ack
            self._retry(lambda: self._dead_letter(rec), "dead_letter")
            self.p._deadlettered.mark()
            self.p._deadletter_route.mark()
            self.p.consumer.ack(PartitionOffset(rec.partition, rec.offset))
        elif b._on_parse_error == "skip":
            logger.exception("Skipping %s record %s/%s", what,
                             rec.partition, rec.offset)
            # no durability dependency: ack now
            self.p.consumer.ack(PartitionOffset(rec.partition, rec.offset))
        else:
            logger.exception(
                "Can not place record; worker %d dies (reference "
                "poison-pill parity, KPW.java:271-275)", self.index)
            raise

    # -- partitioned mode (Builder.partition_by) -----------------------------
    def _loop_once_partitioned(self, b, poll_batch_base: int) -> None:
        """One poll→parse→route→write→rotate iteration of the partitioned
        mode: each record routes to its partition's open file, size
        rotation is per partition, and time rotation is a CHECKPOINT —
        the oldest open file crossing ``max_file_open_duration`` closes
        every open partition file at once.  Per-file time rotation alone
        could defer acks indefinitely under steady multi-partition
        traffic (some open file always holds fresh records, and a poll
        batch's offsets are only coverable by the union of the files it
        scattered into); the checkpoint guarantees an ack point at least
        once per duration window."""
        if self._part_files and any(self._is_file_timed_out(f)
                                    for f in self._part_files.values()):
            self._finalize_partitions("time")
        tuner = self.p.autotuner
        if tuner is not None:
            poll_batch_base = tuner.poll_batch(self._proc_rate)
        poll_batch = min(poll_batch_base, _rotation_batch_cap(
            b._max_file_size, max(8.0, self._carry_est)))
        self._last_poll_batch = poll_batch
        recs, runs = self.p.consumer.poll_many_runs(
            self._poll_cap(poll_batch))
        if not recs:
            time.sleep(0.001)
            return
        t0 = time.perf_counter()
        self._inflight_runs = runs
        groups: dict[str, list] = {}
        written = []
        nbytes = 0
        for rec in recs:
            try:
                msg = b._parser(rec.value)
                pkey = normalize_partition_path(
                    self.p.partitioner.partition_for(rec, msg))
            except Exception:
                self._handle_record_error(rec, "unroutable")
                continue
            groups.setdefault(pkey, []).append(msg)
            written.append(rec)
            nbytes += len(rec.value)
        if not groups:
            self._inflight_runs = []  # every record was acked above
            return
        for pkey, msgs in groups.items():
            f = self._partition_file(pkey)
            f.append_records(msgs)  # pure memory
            self._retry(f.flush_if_full, "flush")
        self._note_written(written)
        self._inflight_runs = []
        self.p._written_records.mark(len(written))
        self.p._written_bytes.mark(nbytes)
        self._note_proc_rate(len(written), t0)
        for pkey in [k for k, f in self._part_files.items()
                     if self._is_file_full(f)]:
            self._finalize_partition(pkey, "size")

    def _partition_file(self, pkey: str) -> ParquetFile:
        """The open file for ``pkey``, moved to most-recently-written;
        opening a NEW partition past the open-files bound first
        closes-and-publishes the least-recently-written one (LRU
        eviction, ``parquet.writer.partitions.evicted``)."""
        f = self._part_files.pop(pkey, None)
        if f is not None:
            self._part_files[pkey] = f  # dict order == LRU order
            return f
        while len(self._part_files) >= self.p._b._max_open_partitions:
            self._finalize_partition(next(iter(self._part_files)), "evict")
        # per-tenant open-file budget (runtime/multiwriter.py — the PR-8
        # LRU bound generalized across the route's workers): at the
        # budget, opening a NEW partition first closes-and-publishes
        # this worker's LRU open file.  Backpressure lands on the
        # offending route (it pays the publish), siblings never see it,
        # and nothing is dropped.  A worker with nothing left to evict
        # proceeds — bounded overshoot of one file per worker, and the
        # next open re-checks.
        while self._part_files and self.p._file_budget_exceeded():
            self.p._tenant_files_evicted.mark()
            self._finalize_partition(next(iter(self._part_files)), "evict")
        f = self._open_new_file(subdir=pkey)
        self._part_files[pkey] = f
        return f

    def _finalize_partitions(self, reason: str) -> None:
        for pkey in list(self._part_files):
            self._finalize_partition(pkey, reason)

    def _finalize_partition(self, pkey: str, reason: str) -> None:
        """Close → publish one partition's open file (``size`` rotation,
        ``time`` checkpoint, or LRU ``evict``), then ack via
        :meth:`_maybe_ack_all`.  The file stays in ``_part_files`` until
        the publish lands: a close/verify/publish failure propagates to
        the worker's death path, whose ``_abandon_open_files`` must still
        find the file to stop its pipeline threads and sink (the flat
        path keeps ``current_file`` set for exactly the same reason)."""
        f = self._part_files[pkey]
        f.rotation_reason = reason
        self._carry_est = f.est_record_bytes
        if f.get_num_written_records() == 0:
            # never publish empty files; just drop the tmp
            self._retry(f.close, "close")
            self._retry(lambda: self.p.fs.delete(f.path), "delete")
            self._fold_pipe_stats(f)
            del self._part_files[pkey]
            return
        self._retry(f.close, "close")
        size = self.p.fs.size(f.path)
        self.p._flushed_records.mark(f.get_num_written_records())
        self.p._flushed_bytes.mark(size)
        self.p._file_size_histogram.update(size)
        self._mark_index_meters(f)
        if reason == "evict":
            self.p._partitions_evicted.mark()
        else:
            (self.p._rotated_time if reason == "time"
             else self.p._rotated_revoke if reason == "revoke"
             else self.p._rotated_size).mark()
        self._rename_and_move(f.path, subdir=pkey)
        self._fold_pipe_stats(f)
        del self._part_files[pkey]
        # ack strictly after durable publish (KPW.java:347-350),
        # generalized to scattered partitions by the checkpoint rule
        self._maybe_ack_all()

    def _mark_index_meters(self, f: ParquetFile) -> None:
        """Per-closed-file accounting: mark ``parquet.writer.indexed``
        when it carries page-index sections, ``parquet.writer.bloom.bytes``
        by the bloom bytes it landed, and the nogil-assembly chunk/page
        meters by what its encoder assembled natively."""
        info = f.index_info()
        if info.get("pages_indexed"):
            self.p._indexed.mark()
        if info.get("bloom_bytes"):
            self.p._bloom_bytes_meter.mark(info["bloom_bytes"])
        asm = f.assembly_info()
        if asm.get("native_chunks"):
            self.p._native_asm_chunks.mark(asm["native_chunks"])
            self.p._native_asm_pages.mark(asm["native_pages"])
        einfo = f.encoding_info()
        if einfo:
            # last published file's chooser decisions (stats()["encodings"])
            self.p._last_encoding_info = einfo

    def _maybe_ack_all(self) -> None:
        """Commit the held offset runs iff NO open file still holds
        unacked records: one poll batch scatters across partitions, so a
        run is durably covered only by the union of the files it landed
        in — all of them must have published."""
        if any(f.get_num_written_records() > 0
               for f in self._part_files.values()):
            return
        pending = list(self._written_runs)
        self._written_runs.clear()
        self._unacked_count = 0
        self._oldest_unacked_ts = None
        for partition, start, end in pending:
            try:
                self.p.consumer.ack_run(partition, start, end - start)
            except StaleGenerationError as e:
                # partitioned files scatter many runs per file, so a
                # fenced run cannot un-publish anything here — drop it
                # (the new owner's redelivery makes its rows
                # at-least-once duplicates) and keep acking the rest
                self.p._fenced_acks.mark()
                rec = self.p._flightrec
                if rec is not None:
                    rec.note("rebalance_fenced_ack_dropped",
                             worker=self.index, partition=partition,
                             run=[start, end], error=repr(e))

    def open_partitions(self) -> list[str]:
        """Scrape-safe snapshot of this worker's open partition keys."""
        try:
            return sorted(self._part_files)
        # lint: swallowed-exceptions ok — lock-free scrape racing the
        # worker thread's dict mutation; a dropped snapshot beats taking
        # down the stats() scrape
        except RuntimeError:
            return []

    def _open_files(self) -> list[ParquetFile]:
        """Every open file this worker owns (flat current file and/or the
        partitioned map) — the cleanup paths' iteration target."""
        out = list(self._part_files.values())
        if self.current_file is not None:
            out.append(self.current_file)
        return out

    def _abandon_open_files(self, reason: str) -> None:
        """Abandon every open file: pipeline threads stopped, sinks
        closed, tmps left un-published and un-acked (swept + redelivered
        later).  Never raises — callers are death/pause/zombie cleanup
        paths that must complete."""
        for f in self._open_files():
            try:
                f.rotation_reason = reason
                f.abandon()
            except Exception:
                logger.exception("worker %d: abandon of %s failed "
                                 "(ignored)", self.index, f.path)
            finally:
                self._fold_pipe_stats(f)
        self.current_file = None
        self._part_files.clear()

    # -- pause/resume (degraded_mode) ---------------------------------------
    def _pause_cause(self, e: BaseException):
        """The fatal OSError behind ``e`` when degraded_mode should pause
        on it, else None.  Covers the direct seam (a fatal errno escaping
        the retry policy) and the pipelined one (a poisoned pipe whose
        cause was a fatal errno in the row-group IO thread)."""
        if not self.p._b._degraded_mode or self._stop.is_set():
            return None
        cand = e
        if isinstance(e, PipelineError):
            cand = e.__cause__
        if not isinstance(cand, OSError):
            return None
        return cand if self.p.retry_policy.is_fatal(cand) else None

    def _pause_until_recovered(self, cause: OSError) -> None:
        """Fatal-errno pause: abandon the open (unpublishable) file
        un-acked, stop intake — the shared queue fills and the fetcher's
        bounded put blocks, so backpressure reaches the consumer while its
        broker session stays alive — and probe the sink with exponential
        backoff until it heals.  On resume the held offset runs are
        re-injected (redelivery; they were never acked) from a side
        thread, because this worker is the consumer that makes queue
        space.  ``max_pause_seconds`` exceeded converts the pause into
        the normal fatal death (supervision semantics take over)."""
        b = self.p._b
        # abandon flushes the sinks and can hit the SAME full-disk
        # condition that triggered the pause — the helper swallows that,
        # which is the whole point of degraded_mode (the tmps are garbage
        # either way)
        self._abandon_open_files("error")
        held = self.held_runs()
        self._written_runs = []
        self._inflight_runs = []
        self._unacked_count = 0
        self._oldest_unacked_ts = None
        self.last_error = repr(cause)
        self.p._enter_pause(self.index, cause)
        try:
            backoff = b._pause_probe_interval
            t0 = time.monotonic()
            while True:
                if self._stop.wait(backoff):
                    raise RetryInterrupted() from cause
                if self.p._probe_sink(self.index):
                    break
                backoff = min(backoff * 2.0, b._pause_probe_max)
                if (b._max_pause is not None
                        and time.monotonic() - t0 > b._max_pause):
                    logger.error(
                        "worker %d: pause exceeded max_pause_seconds "
                        "(%.1fs); converting to fatal death",
                        self.index, b._max_pause)
                    raise cause
        finally:
            self.p._exit_pause(self.index)
        if held:
            threading.Thread(
                target=self._redeliver_runs, args=(held,),
                name=f"KPW-resume-redeliver-{self.index}",
                daemon=True).start()

    def _redeliver_runs(self, runs) -> None:
        try:
            for part, start, end in runs:
                self.p.consumer.redeliver_run(part, start, end - start,
                                              stop_event=self._stop)
        except RetryInterrupted:
            pass
        except Exception:
            logger.exception(
                "resume redelivery failed; the offsets stay un-acked and "
                "redeliver on the next start")

    def _try_wire_items(self, items, runs) -> bool:
        """Shred a poll's worth of queue chunks through the native wire
        decoder and append them columnar.  ``items`` mixes zero-copy
        RecordBatch views (batch-native ingest: buffer + offsets straight
        to the C++ shredder, no per-record bytes lists) and plain Record
        lists (the compatibility route / redelivered runs); ``runs`` is
        the whole poll as (partition, start, count) ack runs — bookkeeping
        and byte metering fold whole runs instead of walking 150k records
        per second in Python.  Returns False when any record needs the
        Python fallback (the whole poll re-runs there; shredder outputs
        are discarded — nothing was appended yet)."""
        col = self.p.columnarizer
        batches = []
        nrecs = 0
        nbytes = 0
        try:
            with stage("worker.shred"):
                for it in items:
                    if isinstance(it, RecordBatch):
                        cb = col.columnarize_buffer(it.payload, it.offsets)
                    else:
                        cb = col.columnarize_payloads([r.value for r in it])
                    batches.append(cb)
                    nrecs += cb.num_rows
                    nbytes += (cb.wire_bytes if cb.wire_bytes is not None
                               else sum(len(r.value) for r in it))
        except WireShredError:
            return False
        if self.current_file is None:
            self._open_file()
        # row order: records a fallback batch left in the file's record
        # buffer are OLDER than this batch — hand them to the writer first
        self._retry(self.current_file.flush_buffered, "flush_buffered")
        with stage("worker.append"):
            for cb in batches:
                self.current_file.append_batch(cb)  # pure memory
        self._retry(self.current_file.maybe_flush_row_group, "flush")
        self._note_written_runs(runs)
        self.p._written_records.mark(nrecs)
        self.p._written_bytes.mark(nbytes)
        self._file_records += nrecs
        return True

    def _note_proc_rate(self, n: int, t0: float) -> None:
        """EWMA of this worker's shred+append processing rate (records/s,
        poll-to-appended) — the autotuner's poll-sizing input."""
        dt = time.perf_counter() - t0
        if dt <= 0 or n <= 0:
            return
        self._proc_rate += 0.3 * (n / dt - self._proc_rate)

    def _note_written(self, records) -> None:
        """Fold records into the held ack runs (extends the last run when
        contiguous in the same partition — the common case, since poll
        batches are fetch-batch slices)."""
        runs = self._written_runs
        run = runs[-1] if runs else None
        n = 0
        for r in records:
            n += 1
            if run is not None and run[0] == r.partition and run[2] == r.offset:
                run[2] += 1
            else:
                run = [r.partition, r.offset, r.offset + 1]
                runs.append(run)
        self._note_unacked(n)

    def _note_written_runs(self, polled_runs) -> None:
        """Fold (partition, start, count) runs from poll_many_runs into the
        held ack runs — O(runs), not O(records)."""
        runs = self._written_runs
        last = runs[-1] if runs else None
        n = 0
        for part, start, count in polled_runs:
            n += count
            if last is not None and last[0] == part and last[2] == start:
                last[2] = start + count
            else:
                last = [part, start, start + count]
                runs.append(last)
        self._note_unacked(n)

    def _note_unacked(self, n: int) -> None:
        """Ack-lag bookkeeping: n more records written but not yet acked;
        stamp the oldest-unacked clock on the 0 -> n transition."""
        if n <= 0:
            return
        if self._oldest_unacked_ts is None:
            self._oldest_unacked_ts = time.time()
        self._unacked_count += n

    def _poll_cap(self, base: int) -> int:
        """Shrink the poll batch as the open file nears its size threshold:
        never ask for more records than the live bytes/record estimate says
        fit in the remaining budget (plus one).  This is what restores the
        reference's ~1% rotation overshoot (KafkaProtoParquetWriterTest.java:
        166-173) without giving up large batches far from the threshold."""
        f = self.current_file
        if f is None and self._part_files:
            # partitioned mode: cap against the FULLEST open partition
            # file — the one that decides the next size rotation
            f = max(self._part_files.values(),
                    key=lambda x: x.get_data_size())
        if f is None:
            return base
        remaining = self.p._b._max_file_size - f.get_data_size()
        if remaining <= 0:
            return 1  # next append rotates immediately
        est = max(f.est_record_bytes, 1.0)
        return max(1, min(base, int(remaining / est) + 1))

    def _is_file_timed_out(self, f: ParquetFile) -> bool:
        return (time.time() - f.get_creation_time()
                >= self.p._b._max_file_open_duration)

    def _is_file_full(self, f: ParquetFile) -> bool:
        return f.get_data_size() >= self.p._b._max_file_size

    def _dead_letter(self, rec) -> None:
        """Append the raw payload to this worker's dead-letter file:
        ``targetDir/deadletter/{instance}_{worker}.bin`` as length-prefixed
        frames of (partition int32, offset int64, payload_len uint32,
        payload).  Real append (never truncate): a failed write can only
        tear the new tail, and frames are self-delimiting so a torn tail is
        detectable; durability-before-ack is delegated to the filesystem's
        close."""
        d = f"{self.p.target_dir}/deadletter"
        self.p.fs.mkdirs(d)
        path = f"{d}/{self.p._b._instance_name}_{self.index}.bin"
        frame = (struct.pack("<iqI", rec.partition, rec.offset,
                             len(rec.value)) + rec.value)
        with self.p.fs.open_append(path) as f:
            f.write(frame)

    # -- observability -----------------------------------------------------
    def _fold_pipe_stats(self, f: ParquetFile) -> None:
        """Fold a finished file's pipeline stats into the worker's running
        totals (stall seconds and put/get counts sum; high watermarks
        max).  Never raises: observability must not take down the
        rotation path."""
        try:
            self._fold_into(self._pipe_totals, f.pipeline_stats())
        except Exception:
            logger.exception("pipeline-stat fold failed (ignored)")

    @staticmethod
    def _fold_into(tot: dict, ps: dict) -> None:
        tot["files"] += 1
        tot["split_assembly"] = (tot["split_assembly"]
                                 or ps.get("split_assembly", False))
        busy = tot["stage_busy_s"]
        for k, v in ps.get("stage_busy_s", {}).items():
            busy[k] = round(busy.get(k, 0.0) + v, 6)
        for qname, qs in ps.get("queues", {}).items():
            agg = tot["queues"].setdefault(
                qname, {"high_watermark": 0, "put_stall_s": 0.0,
                        "get_stall_s": 0.0, "puts": 0, "gets": 0})
            agg["high_watermark"] = max(agg["high_watermark"],
                                        qs.get("high_watermark", 0))
            for k in ("put_stall_s", "get_stall_s"):
                agg[k] = round(agg[k] + qs.get(k, 0.0), 6)
            for k in ("puts", "gets"):
                agg[k] += qs.get(k, 0)

    def observability(self) -> dict:
        """This worker's pull-based snapshot: ack-lag contribution plus
        pipeline totals (rotated-away files folded + the live file's
        stats merged in)."""
        tot = {
            "files": self._pipe_totals["files"],
            "split_assembly": self._pipe_totals["split_assembly"],
            "stage_busy_s": dict(self._pipe_totals["stage_busy_s"]),
            "queues": {q: dict(v)
                       for q, v in self._pipe_totals["queues"].items()},
        }
        try:
            open_files = self._open_files()
        # lint: swallowed-exceptions ok — lock-free scrape racing the
        # worker thread's partition-map mutation; a dropped snapshot
        # beats taking down the stats() scrape
        except RuntimeError:
            open_files = []
        for f in open_files:
            try:
                self._fold_into(tot, f.pipeline_stats())
            # lint: swallowed-exceptions ok — observability fold over
            # files that may be rotating away under us; a racing snapshot
            # is droppable, and raising would take down the stats() scrape
            except Exception:
                pass  # file may be rotating away under us
        ts = self._oldest_unacked_ts
        stall_age, stall_label = self.heartbeat.stall()
        return {
            "worker": self.index,
            "alive": self.alive(),
            "failed": self.failed,
            "condemned": self.condemned,
            "stall_age_s": round(stall_age, 3),
            "stalled_in": stall_label,
            "exit_reason": self.exit_reason,
            "restarts": self.p._restart_counts[self.index],
            "retries": self.retries,
            "retry_backoff_s": round(self.backoff_s, 6),
            "last_error": self.last_error,
            "unacked_records": self._unacked_count,
            "oldest_unacked_age_s": (round(time.time() - ts, 6)
                                     if ts is not None else 0.0),
            "open_partitions": self.open_partitions(),
            "proc_rate_rps": round(self._proc_rate, 1),
            "poll_batch": self._last_poll_batch,
            "pipeline": tot,
        }

    # -- file management ---------------------------------------------------
    def _tmp_path(self, subdir: str | None = None) -> str:
        # targetDir/tmp/{instance}_{idx}_{rand}.tmp (KPW.java:236-239);
        # partitioned files keep their tmp under tmp/{partition}/ so the
        # sweep and a human ls can attribute debris to its partition
        rand = random.getrandbits(63)
        tmp_dir = f"{self.p.target_dir}/tmp" + (f"/{subdir}" if subdir
                                                else "")
        return (f"{tmp_dir}/"
                f"{self.p._b._instance_name}_{self.index}_{rand}.tmp")

    def _open_new_file(self, subdir: str | None = None) -> ParquetFile:
        # flush-batch granularity follows the live bytes/record estimate,
        # same as the poll batch in _run (small-record streams would
        # otherwise split each poll batch into undersized encode batches)
        batch = min(self.p._b._batch_size,
                    _rotation_batch_cap(self.p._b._max_file_size,
                                        max(8.0, self._carry_est)))

        def make() -> ParquetFile:
            self.p.fs.mkdirs(f"{self.p.target_dir}/tmp"
                             + (f"/{subdir}" if subdir else ""))
            return ParquetFile(
                self.p.fs,
                self._tmp_path(subdir),
                self.p.columnarizer,
                self.p.properties,
                batch_size=batch,
                encoder=self.p._encoder_factory(),
                pipeline=self.p._b._pipeline,
                est_record_bytes=self._carry_est,
                retry_policy=self.p.retry_policy,
                heartbeat=self.heartbeat,
            )

        return self._retry(make, "open")

    def _open_file(self) -> None:
        self.current_file = self._open_new_file()
        self._file_records = 0

    def _new_file_name(self) -> str:
        # {timestamp}_{instance}_{workerIdx}{ext} (KPW.java:313-318)
        ts = _format_now(self.p._b._file_date_time_pattern)
        return f"{ts}_{self.p._b._instance_name}_{self.index}{self.p._b._file_extension}"

    def _finalize_current_file(self, reason: str = "size") -> None:
        """Close (flush+footer) -> rename/publish -> ack.  Order is the
        correctness protocol (KPW.java:325-351).  ``reason`` records why
        the file rotated ("size" | "time") for the rotation-cause
        meters."""
        f = self.current_file
        if f is None:
            return
        f.rotation_reason = reason
        self._carry_est = f.est_record_bytes
        if f.get_num_written_records() == 0:
            # never publish empty files; just drop the tmp
            self._retry(f.close, "close")
            self._retry(lambda: self.p.fs.delete(f.path), "delete")
            self._fold_pipe_stats(f)
            self.current_file = None
            return
        self._retry(f.close, "close")
        # pre-publish fence check (side-effect-free broker predicate): a
        # run whose partition this member can no longer commit — the drain
        # window lapsed, or the session expired under us — must not
        # publish, or the new owner's redelivery of those rows becomes a
        # duplicate.  Abandon the closed tmp instead: fenced runs drop
        # (the new owner republishes them), still-owned runs redeliver.
        fenced_parts = {r[0] for r in self._written_runs
                        if not self.p.consumer.commit_allowed(r[0])}
        if fenced_parts:
            self._fence_abandon_closed(f, fenced_parts)
            return
        size = self.p.fs.size(f.path)
        self.p._flushed_records.mark(self._file_records)
        self.p._flushed_bytes.mark(size)
        self.p._file_size_histogram.update(size)
        self._mark_index_meters(f)
        (self.p._rotated_time if reason == "time"
         else self.p._rotated_revoke if reason == "revoke"
         else self.p._rotated_size).mark()
        dest = self._rename_and_move(f.path)
        self._fold_pipe_stats(f)
        self.current_file = None
        # ack strictly after durable publish (KPW.java:347-350).  A fenced
        # commit HERE means ownership moved between the pre-publish check
        # and the ack (the zombie window): with nothing acked yet the file
        # is un-published again and exactly-once is restored.
        pending = list(self._written_runs)
        self._written_runs.clear()
        self._unacked_count = 0
        self._oldest_unacked_ts = None
        i = 0
        try:
            while i < len(pending):
                partition, start, end = pending[i]
                self.p.consumer.ack_run(partition, start, end - start)
                i += 1
        except StaleGenerationError as e:
            self._fenced_ack_cleanup(dest, pending, i, e)

    def _fence_abandon_closed(self, f: ParquetFile,
                              fenced_parts: set) -> None:
        """The pre-publish fence tripped: ``f`` is closed but must not be
        published.  Delete the tmp, drop the fenced partitions' runs (the
        new owner redelivers them), and redeliver the still-owned runs
        whose rows just vanished with the file."""
        retained = [(p, s, e) for p, s, e in self._written_runs
                    if p not in fenced_parts]
        dropped = sum(e - s for p, s, e in self._written_runs
                      if p in fenced_parts)
        self.p._fence_abandons.mark()
        rec = self.p._flightrec
        if rec is not None:
            rec.note("rebalance_fence_abandon", worker=self.index,
                     partitions=sorted(fenced_parts),
                     dropped_records=dropped, retained_runs=len(retained))
        self._retry(lambda: self.p.fs.delete(f.path), "delete")
        self._fold_pipe_stats(f)
        self.current_file = None
        self._written_runs.clear()
        self._unacked_count = 0
        self._oldest_unacked_ts = None
        if retained:
            threading.Thread(
                target=self._redeliver_runs, args=(retained,),
                name=f"KPW-fence-redeliver-{self.index}",
                daemon=True).start()

    def _fenced_ack_cleanup(self, dest: str | None, pending: list,
                            acked: int, exc: Exception) -> None:
        """An ack commit came back fenced (StaleGenerationError) AFTER the
        file published — the zombie backstop.  With zero runs acked the
        published file vouches for nothing: delete it (un-publish) and
        exactly-once is restored — fenced runs redeliver through the new
        owner, still-owned runs through our own side-thread re-injection.
        With some runs already acked the file must stay (those offsets
        point into it); ack what this member still owns and drop the
        fenced rest — their rows become at-least-once duplicates, noted in
        the flight recorder."""
        con = self.p.consumer
        rest = pending[acked:]
        fenced = [r for r in rest if not con.commit_allowed(r[0])]
        retained = [r for r in rest if con.commit_allowed(r[0])]
        self.p._fenced_acks.mark()
        rec = self.p._flightrec
        if acked == 0 and dest is not None:
            self._retry(lambda: self.p.fs.delete(dest), "unpublish")
            if rec is not None:
                rec.note("rebalance_fenced_unpublish", worker=self.index,
                         file=dest,
                         fenced_partitions=sorted({r[0] for r in fenced}),
                         error=repr(exc))
            retained.extend(fenced)  # un-published: every run redelivers
            if retained:
                threading.Thread(
                    target=self._redeliver_runs,
                    args=([(p, s, e) for p, s, e in retained],),
                    name=f"KPW-fence-redeliver-{self.index}",
                    daemon=True).start()
            return
        for p, s, e in retained:
            try:
                con.ack_run(p, s, e - s)
            except StaleGenerationError:
                fenced.append([p, s, e])
        if rec is not None:
            rec.note("rebalance_fenced_ack_dropped", worker=self.index,
                     file=dest, dropped_runs=len(fenced), error=repr(exc))

    def _rename_and_move(self, tmp_path: str,
                         subdir: str | None = None) -> str:
        # (KPW.java:359-378); spanned as one publish stage so the e2e
        # stall breakdown can attribute verify+rename time per file.
        # ``subdir`` = the partition path in partitioned mode.  Returns
        # the published destination path (the fenced-ack un-publish
        # backstop needs the exact dest the rename landed on).
        with stage("worker.publish"):
            return self._rename_and_move_inner(tmp_path, subdir)

    def _rename_and_move_inner(self, tmp_path: str,
                               subdir: str | None = None) -> str:
        if self.p._b._verify_on_publish:
            # independent read-back BEFORE the rename: a structurally
            # invalid tmp (bad encode, torn write a retry never healed)
            # must never become a published file.  Verify failure is a
            # data error, not an IO error — quarantine the tmp and die
            # un-acked (redelivery), instead of retrying a rename that
            # would publish garbage
            rep = verify_file(self.p.fs, tmp_path)
            if rep.ok:
                self.p._verified.mark()
            else:
                self.p._verify_failed.mark()
                qpath = self.p._quarantine(tmp_path)
                raise PublishVerificationError(
                    f"tmp file failed structural verification and was "
                    f"quarantined to {qpath}: {rep.errors[:3]}")

        dest_dir = self.p.target_dir
        if subdir:
            # partition subtree first, then the optional date pattern —
            # readers prune on the partition keys, so they must own the
            # outer directory levels
            dest_dir = f"{dest_dir}/{subdir}"
            self._retry(lambda d=dest_dir: self.p.fs.mkdirs(d), "publish")
        pattern = self.p._b._directory_date_time_pattern
        if pattern:
            dest_dir = f"{dest_dir}/{_format_now(pattern)}"
            self._retry(lambda d=dest_dir: self.p.fs.mkdirs(d), "publish")
        return publish_rename(self.p.fs, self._retry, tmp_path, dest_dir,
                              self._new_file_name(),
                              self.p._b._durable_publish)
