"""Thrift *compact protocol* writer/reader, from scratch.

Parquet footers and page headers are thrift-compact-encoded structs
(parquet-format/src/main/thrift/parquet.thrift).  The reference delegates this
to parquet-mr (see /root/reference ParquetFile.java:42-51 building an
``org.apache.parquet.hadoop.ParquetWriter``); here we own the byte format so
the encode path can be retargeted (numpy CPU reference, TPU kernels) without a
JVM anywhere.

Only the subset of thrift needed by parquet metadata is implemented:
structs, i16/i32/i64 (zigzag varints), binary/string, bool, double, lists.
"""

from __future__ import annotations

import struct

# Compact-protocol type ids
CT_STOP = 0x00
CT_TRUE = 0x01
CT_FALSE = 0x02
CT_BYTE = 0x03
CT_I16 = 0x04
CT_I32 = 0x05
CT_I64 = 0x06
CT_DOUBLE = 0x07
CT_BINARY = 0x08
CT_LIST = 0x09
CT_SET = 0x0A
CT_MAP = 0x0B
CT_STRUCT = 0x0C


def zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def varint_bytes(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class CompactWriter:
    """Streaming thrift-compact encoder.

    Struct nesting is tracked explicitly so field ids can be delta-encoded as
    the protocol requires.
    """

    def __init__(self) -> None:
        self._buf = bytearray()
        self._last_fid: list[int] = []

    # -- low level ---------------------------------------------------------
    def _varint(self, n: int) -> None:
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                self._buf.append(b | 0x80)
            else:
                self._buf.append(b)
                return

    def _zigzag_varint(self, n: int) -> None:
        self._varint(zigzag(n))

    # -- struct / fields ---------------------------------------------------
    def struct_begin(self) -> None:
        self._last_fid.append(0)

    def struct_end(self) -> None:
        self._buf.append(CT_STOP)
        self._last_fid.pop()

    def _field_header(self, fid: int, ctype: int) -> None:
        last = self._last_fid[-1]
        delta = fid - last
        if 0 < delta <= 15:
            self._buf.append((delta << 4) | ctype)
        else:
            self._buf.append(ctype)
            self._zigzag_varint(fid)
        self._last_fid[-1] = fid

    def field_bool(self, fid: int, value: bool) -> None:
        self._field_header(fid, CT_TRUE if value else CT_FALSE)

    def field_byte(self, fid: int, value: int) -> None:
        self._field_header(fid, CT_BYTE)
        self._buf.append(value & 0xFF)

    def field_i16(self, fid: int, value: int) -> None:
        self._field_header(fid, CT_I16)
        self._zigzag_varint(value)

    def field_i32(self, fid: int, value: int) -> None:
        self._field_header(fid, CT_I32)
        self._zigzag_varint(value)

    def field_i64(self, fid: int, value: int) -> None:
        self._field_header(fid, CT_I64)
        self._zigzag_varint(value)

    def field_double(self, fid: int, value: float) -> None:
        self._field_header(fid, CT_DOUBLE)
        self._buf += struct.pack("<d", value)

    def field_binary(self, fid: int, value: bytes) -> None:
        self._field_header(fid, CT_BINARY)
        self._varint(len(value))
        self._buf += value

    def field_string(self, fid: int, value: str) -> None:
        self.field_binary(fid, value.encode("utf-8"))

    def field_struct_begin(self, fid: int) -> None:
        self._field_header(fid, CT_STRUCT)
        self.struct_begin()

    def field_list_begin(self, fid: int, elem_ctype: int, size: int) -> None:
        self._field_header(fid, CT_LIST)
        self.list_begin(elem_ctype, size)

    # -- lists -------------------------------------------------------------
    def list_begin(self, elem_ctype: int, size: int) -> None:
        if size < 15:
            self._buf.append((size << 4) | elem_ctype)
        else:
            self._buf.append(0xF0 | elem_ctype)
            self._varint(size)

    def list_i32(self, value: int) -> None:
        self._zigzag_varint(value)

    def list_bool(self, value: bool) -> None:
        # bools inside lists are the type byte itself (compact protocol);
        # the read side mirrors this in CompactReader.read_value
        self._buf.append(CT_TRUE if value else CT_FALSE)

    def list_i64(self, value: int) -> None:
        self._zigzag_varint(value)

    def list_binary(self, value: bytes) -> None:
        self._varint(len(value))
        self._buf += value

    def append_raw(self, data: bytes) -> None:
        """Splice pre-serialized thrift bytes into the stream verbatim.

        For COMPLETE nested structs composed out-of-band (the direct
        composers in core.metadata): a finished struct confines its
        field-delta state, so its bytes are position-independent and the
        writer's own delta tracking is unaffected.  Public so callers never
        have to reach into the private buffer."""
        self._buf += data

    def getvalue(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


class ThriftDecodeError(ValueError):
    """Malformed or truncated thrift-compact bytes.  The read side's ONE
    error type: the independent file verifier (io/verify.py) decodes
    footers and page headers from possibly-torn files, and corruption must
    surface as a diagnosable failure — never a bare IndexError, an
    unbounded varint, or a RecursionError from garbage nesting."""


# nesting deeper than any parquet metadata struct (schema trees are flat
# lists here; the deepest real chain is FileMetaData>RowGroup>ColumnChunk>
# ColumnMetaData>Statistics = 5) — garbage bytes decoding as ever-nested
# structs fail loudly instead of exhausting the Python stack
_MAX_DEPTH = 32


class CompactReader:
    """Generic compact-protocol decoder, bounds-checked end to end.

    Decodes a struct into ``{field_id: value}``; nested structs become dicts,
    lists become Python lists.  Element types are mapped to Python scalars;
    i16/i32/i64 are indistinguishable after decode, which is fine for
    verification purposes.  Every read is bounds-checked against ``data``
    (and the optional ``limit``) so a truncated or bit-flipped input raises
    :class:`ThriftDecodeError` with the failing byte position.
    """

    def __init__(self, data: bytes, pos: int = 0,
                 limit: int | None = None) -> None:
        if pos < 0:
            # a negative start would wrap around via python indexing and
            # read tail bytes as a struct — corruption, not a window
            raise ThriftDecodeError(f"negative read position {pos}")
        self.data = data
        self.pos = pos
        # a caller-supplied limit comes from an untrusted length field
        # (index/bloom section lengths): never let it exceed the buffer,
        # or the _byte bounds check passes while data[pos] IndexErrors
        self.limit = len(data) if limit is None else min(limit, len(data))

    def _byte(self) -> int:
        if self.pos >= self.limit:
            raise ThriftDecodeError(
                f"truncated thrift: read past byte {self.limit}")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def _varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise ThriftDecodeError(
                    f"varint wider than 64 bits at byte {self.pos}")

    def _zigzag_varint(self) -> int:
        return unzigzag(self._varint())

    def read_value(self, ctype: int, depth: int = 0):
        if ctype in (CT_TRUE, CT_FALSE):
            return ctype == CT_TRUE
        if ctype == CT_BYTE:
            return self._byte()
        if ctype in (CT_I16, CT_I32, CT_I64):
            return self._zigzag_varint()
        if ctype == CT_DOUBLE:
            if self.pos + 8 > self.limit:
                raise ThriftDecodeError(
                    f"truncated double at byte {self.pos}")
            v = struct.unpack_from("<d", self.data, self.pos)[0]
            self.pos += 8
            return v
        if ctype == CT_BINARY:
            n = self._varint()
            if n < 0 or self.pos + n > self.limit:
                raise ThriftDecodeError(
                    f"binary of {n} bytes overruns input at byte {self.pos}")
            v = self.data[self.pos : self.pos + n]
            self.pos += n
            return v
        if ctype == CT_LIST:
            head = self._byte()
            size = head >> 4
            elem = head & 0x0F
            if size == 15:
                size = self._varint()
            if size > self.limit - self.pos:
                # every element consumes >= 1 byte; a size past the input's
                # remainder can only be corruption — fail before looping
                raise ThriftDecodeError(
                    f"list of {size} elements overruns input at byte "
                    f"{self.pos}")
            if elem in (CT_TRUE, CT_FALSE):
                # bools inside lists are encoded as the type byte itself
                return [self._byte() == CT_TRUE for _ in range(size)]
            return [self.read_value(elem, depth) for _ in range(size)]
        if ctype == CT_STRUCT:
            return self.read_struct(depth + 1)
        raise ThriftDecodeError(f"unsupported compact type {ctype}")

    def read_struct(self, depth: int = 0) -> dict:
        if depth > _MAX_DEPTH:
            raise ThriftDecodeError(
                f"struct nesting deeper than {_MAX_DEPTH}")
        out: dict[int, object] = {}
        last_fid = 0
        while True:
            head = self._byte()
            if head == CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            if delta == 0:
                fid = self._zigzag_varint()
            else:
                fid = last_fid + delta
            last_fid = fid
            out[fid] = self.read_value(ctype, depth)
