"""parquet-core: from-scratch Parquet format layer (thrift, encodings, pages,
file writer) — SURVEY.md §7 step 1."""

from .schema import (  # noqa: F401
    Codec,
    ColumnDescriptor,
    ConvertedType,
    Encoding,
    Field,
    PhysicalType,
    Repetition,
    Schema,
    group,
    leaf,
    list_of,
)
from .writer import ColumnBatch, ParquetFileWriter, WriterProperties, columns_from_arrays  # noqa: F401
from .pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions  # noqa: F401
