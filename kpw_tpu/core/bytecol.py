"""ByteColumn — Arrow-style variable-length byte column: one concatenated
``data`` buffer + int64 ``offsets`` (n+1 entries, absolute into ``data``).

The reference materializes strings as JVM objects all the way through
parquet-mr's ColumnWriter (ParquetFile.java:59-62); here byte-array columns
stay in this packed form end to end, so size estimates are O(1), slicing is
zero-copy (offset window), and the native encode primitives
(kpw_byte_array_plain, kpw_dict_build_bytes, delta lengths) consume the
buffers directly with no per-value Python objects.  It quacks like the
``list[bytes]`` it replaces: len/iter/getitem(int|slice) — the numpy oracle
paths keep working unchanged (just at list speed).
"""

from __future__ import annotations

import numpy as np


class ByteColumn:
    __slots__ = ("data", "offsets")

    def __init__(self, data: bytes, offsets: np.ndarray) -> None:
        self.data = data
        self.offsets = offsets  # int64, absolute, len = n + 1

    @classmethod
    def from_list(cls, values: list) -> "ByteColumn":
        n = len(values)
        offsets = np.zeros(n + 1, np.int64)
        if n:
            np.cumsum(np.fromiter(map(len, values), np.int64, count=n),
                      out=offsets[1:])
        return cls(b"".join(values), offsets)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise ValueError("ByteColumn slices must be contiguous")
            return ByteColumn(self.data, self.offsets[start: stop + 1])
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        o = self.offsets
        return self.data[o[i]: o[i + 1]]

    def __iter__(self):
        o = self.offsets
        d = self.data
        for i in range(len(self)):
            yield d[o[i]: o[i + 1]]

    def lens(self) -> np.ndarray:
        return np.diff(self.offsets)

    def payload(self) -> bytes:
        """The bytes of exactly this window."""
        return self.data[self.offsets[0]: self.offsets[-1]]

    def payload_bytes(self) -> int:
        return int(self.offsets[-1] - self.offsets[0])

    def take(self, positions) -> list:
        o = self.offsets
        d = self.data
        return [d[o[p]: o[p + 1]] for p in positions]


def lens_and_payload(values) -> tuple[np.ndarray, bytes]:
    """(int64 lengths, concatenated bytes) for a ByteColumn or list[bytes] —
    the one definition of this extraction (consumed by the native and device
    DELTA_LENGTH_BYTE_ARRAY paths)."""
    if isinstance(values, ByteColumn):
        return values.lens().astype(np.int64), values.payload()
    lens = np.fromiter(map(len, values), np.int64, count=len(values))
    return lens, b"".join(values)
