"""Per-column encoding selection — the ONE place a value encoding is chosen.

ISSUE 16: "An Empirical Evaluation of Columnar Storage Formats" shows the
encoding choice dominates both file size and scan speed, and the stats
machinery here already measures every chunk — so instead of one global
``delta_fallback`` switch, the chooser picks per column among PLAIN /
dictionary+RLE / DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY /
BYTE_STREAM_SPLIT, driven by the FIRST row group's observed stats
(cardinality from the dictionary build, monotone-delta width, value width,
null density).  The decision is **pinned per file** after row group 1 for
reader coherence: later row groups reuse the pin in O(1) — the chooser
costs nothing on the hot path and never rescans values after the first
row group.

Resolution order (first hit wins):

1. the explicit ``Builder.encodings()`` override map (forces the value
   encoding and disables the dictionary attempt for that column),
2. the legacy ``delta_fallback(True)`` switch, re-expressed here as a
   forced per-type override (ints -> DELTA_BINARY_PACKED, byte arrays ->
   DELTA_LENGTH_BYTE_ARRAY) so the old spelling keeps its exact behavior,
3. the per-file pinned adaptive choice (``adaptive_encodings=True``),
   computed once from row group 1,
4. PLAIN (the non-adaptive default — byte-identical pre-chooser output).

Every encoder backend funnels through :meth:`CpuChunkEncoder._fallback_encoding`,
which is a one-line delegation here; ``tools/analyze``'s
``encoding-choice`` pass flags any ``Encoding.`` literal *chosen* outside
this module so a second decision point cannot creep back in.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from .schema import Codec, Encoding, PhysicalType

_ENCODING_NAMES = {v: k for k, v in vars(Encoding).items()
                   if not k.startswith("_")}

# override map: which value encodings a column of each physical type may be
# forced to (dictionary is an acceptance mechanism, not a forced override)
_OVERRIDABLE = {
    PhysicalType.INT32: (Encoding.PLAIN, Encoding.DELTA_BINARY_PACKED,
                         Encoding.BYTE_STREAM_SPLIT),
    PhysicalType.INT64: (Encoding.PLAIN, Encoding.DELTA_BINARY_PACKED,
                         Encoding.BYTE_STREAM_SPLIT),
    PhysicalType.FLOAT: (Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT),
    PhysicalType.DOUBLE: (Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT),
    PhysicalType.BYTE_ARRAY: (Encoding.PLAIN,
                              Encoding.DELTA_LENGTH_BYTE_ARRAY),
    PhysicalType.BOOLEAN: (Encoding.PLAIN,),
    PhysicalType.FIXED_LEN_BYTE_ARRAY: (Encoding.PLAIN,),
    PhysicalType.INT96: (Encoding.PLAIN,),
}

# adaptive rule thresholds (trigger stats are surfaced per decision, so a
# surprising choice is always explainable from the report)
_MIN_ADAPTIVE_ROWS = 8        # below this RG1 carries no signal: PLAIN
# delta wins when the packed miniblock width saves at least one byte per
# value over PLAIN — below that the block headers and the slower decode
# buy nothing (a 61-bit-wide random int64 column stays PLAIN; a 33-bit
# random id column in an INT64 leaf still packs ~2x)
_DELTA_MIN_SAVED_BITS = 8


def encoding_name(encoding: int) -> str:
    return _ENCODING_NAMES.get(encoding, str(encoding))


@dataclass
class EncodingDecision:
    """One column's pinned choice + the stats that triggered it."""

    value_encoding: int            # non-dictionary value encoding
    use_dictionary: bool           # whether later row groups attempt dict
    reason: str                    # "override" / "delta_fallback" / rule
    pinned: bool = False           # True once fixed for the file
    stats: dict = field(default_factory=dict)   # trigger stats (RG1)

    def describe(self) -> dict:
        return {
            "value_encoding": encoding_name(self.value_encoding),
            "use_dictionary": self.use_dictionary,
            "reason": self.reason,
            "pinned": self.pinned,
            "stats": dict(self.stats),
        }


def _normalize_overrides(mapping) -> dict:
    """Builder.encodings() accepts Encoding ints or (case-insensitive)
    spec names; normalize to ints once at construction."""
    out = {}
    for name, spec in (mapping or {}).items():
        if isinstance(spec, str):
            try:
                spec = getattr(Encoding, spec.upper())
            except AttributeError:
                raise ValueError(f"unknown encoding name {spec!r} for "
                                 f"column {name!r}") from None
        if spec not in _ENCODING_NAMES:
            raise ValueError(f"unknown encoding {spec!r} for column {name!r}")
        if spec in (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY,
                    Encoding.RLE, Encoding.BIT_PACKED,
                    Encoding.DELTA_BYTE_ARRAY):
            raise ValueError(
                f"encoding {encoding_name(spec)} cannot be forced per "
                f"column (dictionary is an acceptance mechanism; levels "
                f"are always RLE)")
        out[name] = spec
    return out


class EncodingChooser:
    """Per-file value-encoding decisions for every column of one encoder.

    Thread-safety: ``assemble_many`` shards columns across the assembly
    pool, so pin writes go through a lock; row groups are sequential per
    writer, so row group 2+ always observes row group 1's pins.
    ``begin_file()`` resets the pins — called per ``ParquetFileWriter``
    because a custom Builder backend may hand the SAME encoder object to
    every rotated file (runtime/parquet_file.py), and the pin must be
    per *file* for reader coherence, not per encoder lifetime.
    """

    def __init__(self, options) -> None:
        self.options = options
        self.overrides = _normalize_overrides(
            getattr(options, "encodings", None))
        self.adaptive = bool(getattr(options, "adaptive_encodings", False))
        self._pins: dict = {}            # path tuple -> EncodingDecision
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def begin_file(self) -> None:
        """Reset per-file pin state (new file = new row group 1)."""
        with self._lock:
            self._pins = {}

    # -- resolution --------------------------------------------------------
    def _override_for(self, col) -> int | None:
        if not self.overrides:
            return None
        spec = self.overrides.get(".".join(col.path))
        if spec is None:
            spec = self.overrides.get(col.name)
        if spec is None:
            return None
        pt = col.leaf.physical_type
        if spec not in _OVERRIDABLE.get(pt, (Encoding.PLAIN,)):
            raise ValueError(
                f"encoding {encoding_name(spec)} is not valid for column "
                f"{'.'.join(col.path)!r} (physical type {pt})")
        return spec

    def static_value_encoding(self, pt: int) -> int:
        """The pre-chooser column-independent rule: the legacy
        ``delta_fallback`` spelling, else PLAIN.  Also the terminal
        default for adaptive columns whose stats trigger nothing."""
        if self.options.delta_fallback:
            if pt in (PhysicalType.INT32, PhysicalType.INT64):
                return Encoding.DELTA_BINARY_PACKED
            if pt == PhysicalType.BYTE_ARRAY:
                return Encoding.DELTA_LENGTH_BYTE_ARRAY
        return Encoding.PLAIN

    def _static_decision(self, col, pt: int) -> EncodingDecision | None:
        """A decision resolvable WITHOUT chunk stats, or None when the
        adaptive rules need row group 1 first."""
        forced = self._override_for(col)
        if forced is not None:
            return EncodingDecision(forced, use_dictionary=False,
                                    reason="override", pinned=True)
        if not self.adaptive:
            return EncodingDecision(self.static_value_encoding(pt),
                                    use_dictionary=True,
                                    reason=("delta_fallback"
                                            if self.options.delta_fallback
                                            else "default"),
                                    pinned=True)
        return None

    def peek(self, col) -> EncodingDecision | None:
        """Pinned or statically-forced decision — NEVER computes or pins.
        The pipelined planners use this: row group N+1's launch may run
        before row group 1's assembly pinned anything, in which case the
        planner simply skips pre-planning (correctness lives in encode())."""
        pt = col.leaf.physical_type
        d = self._pins.get(tuple(col.path))
        if d is not None:
            return d
        return self._static_decision(col, pt)

    def dictionary_wanted(self, col) -> bool:
        """Whether this column should still ATTEMPT a dictionary build.
        False once an override forces a value encoding, or once the
        adaptive pin recorded that row group 1's build was rejected (the
        build would be re-rejected anyway — skipping it is the hot-path
        win that makes the chooser free after row group 1)."""
        d = self.peek(col)
        return True if d is None else d.use_dictionary

    def choose(self, chunk, pt: int, *, dict_accepted: bool,
               dict_size: int | None) -> EncodingDecision:
        """Resolve (and pin, in adaptive mode) the decision for ``chunk``'s
        column.  Called from ``encode()`` AFTER the dictionary attempt, so
        cardinality arrives for free from the build; the only extra work
        is one O(n) delta scan for int columns, on row group 1 only."""
        col = chunk.column
        d = self.peek(col)
        if d is not None:
            return d
        d = self._adaptive_decision(chunk, pt, dict_accepted, dict_size)
        with self._lock:
            # first writer wins: columns are unique within a row group and
            # row groups are sequential, so this only guards pool threads
            # racing distinct columns into the dict
            return self._pins.setdefault(tuple(col.path), d)

    # -- the adaptive rules ------------------------------------------------
    def _adaptive_decision(self, chunk, pt: int, dict_accepted: bool,
                           dict_size: int | None) -> EncodingDecision:
        values = chunk.values
        n = len(values)
        stats: dict = {"rows": n}
        # cardinality only when the build was ACCEPTED: a rejected build's
        # count is backend-dependent (the native/mesh paths early-abort at
        # max_k without counting), and the decision stats land in the
        # footer, where every backend must stay byte-identical
        if dict_accepted and dict_size is not None:
            stats["cardinality"] = dict_size
        if chunk.def_levels is not None:
            lv = np.asarray(chunk.def_levels)
            stats["null_density"] = round(
                float((lv < chunk.column.max_def).mean()), 4) if len(lv) else 0.0
        fallback = self.static_value_encoding(pt)
        if n < _MIN_ADAPTIVE_ROWS:
            # no signal: pin the column-independent default, but leave the
            # dictionary attempt OPEN — banning dict off an empty/tiny row
            # group 1 would be a decision made from noise
            return EncodingDecision(fallback, True,
                                    "rg1-too-small", True, stats)
        if pt in (PhysicalType.INT32, PhysicalType.INT64):
            width, value_bits, monotone = _delta_profile(values, pt)
            stats.update(delta_packed_width=width, value_bits=value_bits,
                         monotone=monotone)
            if width + _DELTA_MIN_SAVED_BITS <= value_bits:
                return EncodingDecision(
                    Encoding.DELTA_BINARY_PACKED, dict_accepted,
                    f"delta_width={width}<={value_bits}"
                    f"-{_DELTA_MIN_SAVED_BITS}", True, stats)
            return EncodingDecision(fallback, dict_accepted,
                                    "wide-deltas", True, stats)
        if pt in (PhysicalType.FLOAT, PhysicalType.DOUBLE):
            # BYTE_STREAM_SPLIT has the SAME byte count as PLAIN — the win
            # is compressibility of the grouped byte planes, so it only
            # pays under a codec
            if self.options.codec != Codec.UNCOMPRESSED:
                return EncodingDecision(Encoding.BYTE_STREAM_SPLIT,
                                        dict_accepted,
                                        "float-under-codec", True, stats)
            return EncodingDecision(fallback, dict_accepted,
                                    "uncompressed-float", True, stats)
        if pt == PhysicalType.BYTE_ARRAY:
            # delta-packed lengths beat the 4-byte-per-value PLAIN prefix
            # regardless of content; the payload bytes are identical
            return EncodingDecision(Encoding.DELTA_LENGTH_BYTE_ARRAY,
                                    dict_accepted,
                                    "byte-array-lengths", True, stats)
        return EncodingDecision(fallback, dict_accepted, "no-rule",
                                True, stats)

    # -- surfacing ---------------------------------------------------------
    def report(self) -> dict:
        """Per-column decision + trigger stats, for ``stats()`` and the
        ``encoding_info()`` accessor (keys = dotted column paths)."""
        with self._lock:
            return {".".join(path): d.describe()
                    for path, d in sorted(self._pins.items())}


def _delta_profile(values, pt: int) -> tuple[int, int, bool]:
    """(packed delta bit width, value bits, monotone?) for an int chunk —
    the exact width DELTA_BINARY_PACKED would need for the widest value:
    deltas in ring arithmetic, re-based on the block min (the spec packs
    ``delta - min_delta``)."""
    itype = np.int32 if pt == PhysicalType.INT32 else np.int64
    utype = np.uint32 if pt == PhysicalType.INT32 else np.uint64
    value_bits = 32 if pt == PhysicalType.INT32 else 64
    v = np.asarray(values, itype)
    if len(v) < 2:
        return 0, value_bits, True
    with np.errstate(over="ignore"):
        deltas = v[1:] - v[:-1]
        rel = (deltas - deltas.min()).view(utype)
    width = int(rel.max()).bit_length()
    return width, value_bits, bool((deltas >= 0).all())
