"""Query-ready file metadata: PARQUET-922 page indexes, split-block bloom
filters, and the read-side tooling that proves they pay off.

Files this writer publishes are written once and scanned forever, and scan
cost downstream is dominated by how much a reader can SKIP ("An Empirical
Evaluation of Columnar Storage Formats", PAPERS.md): page-level min/max
lets a selective predicate prune pages without touching them, and a bloom
filter rejects a point-lookup miss without reading any data page at all.
This module owns the three byte formats plus their readers:

* **ColumnIndex / OffsetIndex** (parquet.thrift, PARQUET-922): per-page
  ``null_pages`` / ``min_values`` / ``max_values`` / ``boundary_order`` /
  ``null_counts``, and per-page ``(offset, compressed_page_size,
  first_row_index)`` locations.  Serialized thrift-compact via
  ``core.thrift.CompactWriter``, laid out between the last row group and
  the footer by ``core/writer.py``; the footer's ColumnChunk fields 4-7
  point at them.
* **Split-block bloom filters** (parquet.thrift BloomFilterHeader + the
  SBBF bitset): xxhash64 of the value's plain-encoded bytes, 256-bit
  blocks of 8 salted words.  The dictionary build already owns each
  chunk's exact distinct set — on the device backends that set comes back
  from the mesh/TPU build — so filter population is a hash pass over k
  distinct values, not n rows.  ``bloom_filter_offset``/``length`` live in
  ColumnMetaData fields 14/15.
* **Readers** used by the scan planner (``bench.py --scan``), the
  verifier's structural walk, and tests: footer index-section discovery,
  ColumnIndex/OffsetIndex parse, page selection against a predicate, and
  bloom probe.

Nothing here imports jax: the module is pure numpy + the in-repo thrift
codec, importable from the encode hot path and the jax-free tooling alike.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass

import numpy as np

from .schema import PhysicalType
from .thrift import (CT_BINARY, CT_I64, CT_STRUCT, CT_TRUE, CompactReader,
                     CompactWriter, ThriftDecodeError)

# BoundaryOrder (parquet.thrift)
UNORDERED, ASCENDING, DESCENDING = 0, 1, 2

# ColumnIndex field ids
_CI_NULL_PAGES, _CI_MIN, _CI_MAX, _CI_ORDER, _CI_NULL_COUNTS = 1, 2, 3, 4, 5
# OffsetIndex / PageLocation field ids
_OI_LOCATIONS = 1
_PL_OFFSET, _PL_SIZE, _PL_FIRST_ROW = 1, 2, 3


# ---------------------------------------------------------------------------
# per-page statistics (collected by the encoder while pages are assembled)
# ---------------------------------------------------------------------------

@dataclass
class PageStats:
    """One data page's index ingredients, recorded by the encoder as the
    page is assembled.  ``offset`` is relative to the chunk's first byte
    (the writer only learns the chunk's absolute position at commit);
    ``compressed_size`` includes the page header, per PageLocation's
    contract.  ``min_key``/``max_key`` are python-comparable values (for
    boundary-order computation); ``min_bytes``/``max_bytes`` are the
    plain-encoded statistics bytes the ColumnIndex carries."""

    first_row_index: int
    offset: int
    compressed_size: int
    num_values: int
    null_count: int
    min_bytes: bytes | None = None
    max_bytes: bytes | None = None
    min_key: object = None
    max_key: object = None

    @property
    def is_null_page(self) -> bool:
        # a null PAGE is one whose every value is null — NOT one that
        # merely lacks decodable stats (an all-NaN float page has no
        # min/max but its rows are real; claiming null_pages=true there
        # would let an index-aware reader prune live rows)
        return self.num_values > 0 and self.null_count == self.num_values

    @property
    def has_stats(self) -> bool:
        return self.min_bytes is not None


def boundary_order(pages: list[PageStats]) -> int:
    """BoundaryOrder of a chunk's non-null pages: ASCENDING when both the
    min and max sequences are non-decreasing, DESCENDING when both are
    non-increasing, else UNORDERED.  Null pages are skipped (the spec
    excludes them from the ordering); zero or one comparable page is
    trivially ASCENDING (parquet-mr does the same)."""
    keys = [(p.min_key, p.max_key) for p in pages
            if not p.is_null_page and p.has_stats]
    if len(keys) <= 1:
        return ASCENDING
    asc = all(a[0] <= b[0] and a[1] <= b[1]
              for a, b in zip(keys, keys[1:]))
    if asc:
        return ASCENDING
    desc = all(a[0] >= b[0] and a[1] >= b[1]
               for a, b in zip(keys, keys[1:]))
    return DESCENDING if desc else UNORDERED


def serialize_column_index(pages: list[PageStats]) -> bytes:
    """ColumnIndex thrift-compact bytes for one column chunk.  Null pages
    — and pages with no decodable stats, e.g. all-NaN floats — carry
    empty min/max byte strings (the list fields are required; a reader
    must not prune on an empty entry); ``null_counts`` is always written
    — the encoder knows exact per-page null counts for every path it
    indexes."""
    w = CompactWriter()
    w.struct_begin()
    w.field_list_begin(_CI_NULL_PAGES, CT_TRUE, len(pages))
    for p in pages:
        w.list_bool(p.is_null_page)
    w.field_list_begin(_CI_MIN, CT_BINARY, len(pages))
    for p in pages:
        w.list_binary(p.min_bytes or b"")
    w.field_list_begin(_CI_MAX, CT_BINARY, len(pages))
    for p in pages:
        w.list_binary(p.max_bytes or b"")
    w.field_i32(_CI_ORDER, boundary_order(pages))
    w.field_list_begin(_CI_NULL_COUNTS, CT_I64, len(pages))
    for p in pages:
        w.list_i64(p.null_count)
    w.struct_end()
    return w.getvalue()


def serialize_offset_index(pages: list[PageStats],
                           chunk_file_offset: int) -> bytes:
    """OffsetIndex thrift-compact bytes: page locations made absolute by
    the chunk's final file offset (known only at footer time)."""
    w = CompactWriter()
    w.struct_begin()
    w.field_list_begin(_OI_LOCATIONS, CT_STRUCT, len(pages))
    for p in pages:
        w.struct_begin()
        w.field_i64(_PL_OFFSET, chunk_file_offset + p.offset)
        w.field_i32(_PL_SIZE, p.compressed_size)
        w.field_i64(_PL_FIRST_ROW, p.first_row_index)
        w.struct_end()
    w.struct_end()
    return w.getvalue()


def parse_column_index(data: bytes, offset: int, length: int) -> dict:
    """Decode one ColumnIndex; raises ThriftDecodeError on garbage.
    Returns {null_pages, min_values, max_values, boundary_order,
    null_counts} with python types."""
    r = CompactReader(data, offset, limit=offset + length)
    d = r.read_struct()
    out = {
        "null_pages": d.get(_CI_NULL_PAGES),
        "min_values": d.get(_CI_MIN),
        "max_values": d.get(_CI_MAX),
        "boundary_order": d.get(_CI_ORDER),
        "null_counts": d.get(_CI_NULL_COUNTS),
    }
    if (not isinstance(out["null_pages"], list)
            or not isinstance(out["min_values"], list)
            or not isinstance(out["max_values"], list)):
        raise ThriftDecodeError("ColumnIndex missing a required page list")
    return out


def parse_offset_index(data: bytes, offset: int,
                       length: int) -> list[tuple[int, int, int]]:
    """Decode one OffsetIndex into [(abs_offset, compressed_size,
    first_row_index), ...]; raises ThriftDecodeError on garbage."""
    r = CompactReader(data, offset, limit=offset + length)
    d = r.read_struct()
    locs = d.get(_OI_LOCATIONS)
    if not isinstance(locs, list):
        raise ThriftDecodeError("OffsetIndex has no page_locations list")
    out = []
    for loc in locs:
        if not isinstance(loc, dict):
            raise ThriftDecodeError("PageLocation is not a struct")
        o, s, fr = (loc.get(_PL_OFFSET), loc.get(_PL_SIZE),
                    loc.get(_PL_FIRST_ROW))
        if not all(isinstance(v, int) and not isinstance(v, bool)
                   for v in (o, s, fr)):
            raise ThriftDecodeError("PageLocation fields not integers")
        out.append((o, s, fr))
    return out


# ---------------------------------------------------------------------------
# typed min/max decoding + page selection (the scan planner)
# ---------------------------------------------------------------------------

_FIXED_FMT = {
    PhysicalType.INT32: "<i", PhysicalType.INT64: "<q",
    PhysicalType.FLOAT: "<f", PhysicalType.DOUBLE: "<d",
}


def decode_stat(value: bytes, physical_type: int):
    """Plain-encoded statistics bytes -> python-comparable value (None for
    an empty/undecodable value — null pages carry empty strings)."""
    if not value:
        return None
    fmt = _FIXED_FMT.get(physical_type)
    if fmt is None:
        return bytes(value)  # BYTE_ARRAY/FLBA compare lexicographically
    if len(value) != struct.calcsize(fmt):
        return None
    return struct.unpack(fmt, value)[0]


def select_pages(column_index: dict, physical_type: int,
                 lo=None, hi=None) -> list[int]:
    """Page ordinals whose [min, max] MAY intersect [lo, hi] (either bound
    None = unbounded).  Pages whose stats cannot be decoded are kept —
    pruning must never be unsound.  This is the reader-side payoff the
    bench measures: pages NOT in this list are never read."""
    keep = []
    null_pages = column_index["null_pages"]
    for i, (pmin, pmax) in enumerate(zip(column_index["min_values"],
                                         column_index["max_values"])):
        if i < len(null_pages) and null_pages[i]:
            continue  # only nulls: a value predicate cannot match
        dmin = decode_stat(pmin, physical_type)
        dmax = decode_stat(pmax, physical_type)
        if dmin is None or dmax is None:
            keep.append(i)  # undecodable stats: must read
            continue
        if lo is not None and dmax < lo:
            continue
        if hi is not None and dmin > hi:
            continue
        keep.append(i)
    return keep


# footer fids needed to discover index sections (parquet.thrift; the same
# ids the metadata writer emits)
_FMD_ROW_GROUPS = 4
_RG_COLUMNS, _RG_SORTING = 1, 4
_CC_OFF_IDX_OFF, _CC_OFF_IDX_LEN = 4, 5
_CC_COL_IDX_OFF, _CC_COL_IDX_LEN = 6, 7
_CC_META = 3
_CM_TYPE = 1
_CM_BLOOM_OFF, _CM_BLOOM_LEN = 14, 15


def read_file_index(data: bytes) -> list[list[dict]]:
    """All index sections of a serialized parquet file, per row group per
    column: [{column_index, offset_index, bloom_offset, bloom_length,
    physical_type}].  Entries are None-valued where a section is absent.
    Raises ThriftDecodeError on a malformed footer — callers that must not
    raise (the fuzz harness) catch it."""
    if len(data) < 8 or data[-4:] != b"PAR1":
        raise ThriftDecodeError("no trailing PAR1 magic")
    footer_len = int.from_bytes(data[-8:-4], "little")
    footer_start = len(data) - 8 - footer_len
    if footer_len <= 0 or footer_start < 4:
        raise ThriftDecodeError("footer length does not fit the file")
    fmd = CompactReader(data, footer_start, limit=len(data) - 8).read_struct()
    out: list[list[dict]] = []
    for rg in fmd.get(_FMD_ROW_GROUPS) or []:
        cols = []
        if not isinstance(rg, dict):
            raise ThriftDecodeError("row group is not a struct")
        for cc in rg.get(_RG_COLUMNS) or []:
            if not isinstance(cc, dict):
                raise ThriftDecodeError("column chunk is not a struct")
            meta = cc.get(_CC_META) if isinstance(cc.get(_CC_META),
                                                  dict) else {}
            # same int normalization as ci/oi below: a hostile footer can
            # decode field 14/15 as any thrift type, and a non-int offset
            # handed to bloom_check would TypeError instead of the
            # documented ThriftDecodeError/None contract
            b_off, b_len = meta.get(_CM_BLOOM_OFF), meta.get(_CM_BLOOM_LEN)
            entry = {
                "physical_type": meta.get(_CM_TYPE),
                "column_index": None,
                "offset_index": None,
                "bloom_offset": b_off if isinstance(b_off, int)
                and not isinstance(b_off, bool) else None,
                "bloom_length": b_len if isinstance(b_len, int)
                and not isinstance(b_len, bool) else None,
            }
            ci_off, ci_len = cc.get(_CC_COL_IDX_OFF), cc.get(_CC_COL_IDX_LEN)
            if isinstance(ci_off, int) and isinstance(ci_len, int):
                entry["column_index"] = parse_column_index(data, ci_off,
                                                           ci_len)
            oi_off, oi_len = cc.get(_CC_OFF_IDX_OFF), cc.get(_CC_OFF_IDX_LEN)
            if isinstance(oi_off, int) and isinstance(oi_len, int):
                entry["offset_index"] = parse_offset_index(data, oi_off,
                                                           oi_len)
            cols.append(entry)
        out.append(cols)
    return out


def read_sorting_columns(data: bytes) -> list[list[tuple[int, bool, bool]]]:
    """Declared ``sorting_columns`` per row group: [(column_idx,
    descending, nulls_first), ...] (empty list where undeclared)."""
    if len(data) < 8 or data[-4:] != b"PAR1":
        raise ThriftDecodeError("no trailing PAR1 magic")
    footer_len = int.from_bytes(data[-8:-4], "little")
    footer_start = len(data) - 8 - footer_len
    if footer_len <= 0 or footer_start < 4:
        raise ThriftDecodeError("footer length does not fit the file")
    fmd = CompactReader(data, footer_start, limit=len(data) - 8).read_struct()
    out = []
    for rg in fmd.get(_FMD_ROW_GROUPS) or []:
        decl = []
        for sc in (rg.get(_RG_SORTING) or []) if isinstance(rg, dict) else []:
            if isinstance(sc, dict):
                decl.append((sc.get(1), bool(sc.get(2)), bool(sc.get(3))))
        out.append(decl)
    return out


# ---------------------------------------------------------------------------
# xxhash64 (the bloom filter's hash, parquet.thrift BloomFilterHash.XXHASH)
# ---------------------------------------------------------------------------

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64 of ``data`` — the parquet bloom hash (seed 0).  Pure python;
    bloom population hashes a chunk's DISTINCT set (k values, not n rows),
    and the fixed-width bulk path below covers numeric columns."""
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M64
        v2 = (seed + _P2) & _M64
        v3 = seed & _M64
        v4 = (seed - _P1) & _M64
        while i + 32 <= n:
            k1, k2, k3, k4 = struct.unpack_from("<QQQQ", data, i)
            v1 = (_rotl((v1 + k1 * _P2) & _M64, 31) * _P1) & _M64
            v2 = (_rotl((v2 + k2 * _P2) & _M64, 31) * _P1) & _M64
            v3 = (_rotl((v3 + k3 * _P2) & _M64, 31) * _P1) & _M64
            v4 = (_rotl((v4 + k4 * _P2) & _M64, 31) * _P1) & _M64
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12)
             + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = ((h ^ ((_rotl((v * _P2) & _M64, 31) * _P1) & _M64))
                 * _P1 + _P4) & _M64
    else:
        h = (seed + _P5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        k = struct.unpack_from("<Q", data, i)[0]
        h = (h ^ ((_rotl((k * _P2) & _M64, 31) * _P1) & _M64)) & _M64
        h = (_rotl(h, 27) * _P1 + _P4) & _M64
        i += 8
    if i + 4 <= n:
        h = (h ^ (struct.unpack_from("<I", data, i)[0] * _P1)) & _M64
        h = (_rotl(h, 23) * _P2 + _P3) & _M64
        i += 4
    while i < n:
        h = (h ^ (data[i] * _P5)) & _M64
        h = (_rotl(h, 11) * _P1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h


def _np_rotl(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def xxh64_fixed(arr: np.ndarray) -> np.ndarray:
    """Vectorized XXH64 over a fixed-width numeric array: each element is
    hashed as its 4- or 8-byte plain encoding (exactly what the scalar
    path would see), the whole column in a handful of numpy passes —
    byte-identical to ``xxh64`` per element (pinned in tests)."""
    itemsize = arr.dtype.itemsize
    if itemsize == 8:
        k = np.ascontiguousarray(arr).view(np.uint64)
        with np.errstate(over="ignore"):
            h = np.uint64((_P5 + 8) & _M64)
            h = h ^ (_np_rotl(k * np.uint64(_P2), 31) * np.uint64(_P1))
            h = _np_rotl(h, 27) * np.uint64(_P1) + np.uint64(_P4)
    elif itemsize == 4:
        k = np.ascontiguousarray(arr).view(np.uint32).astype(np.uint64)
        with np.errstate(over="ignore"):
            h = np.uint64((_P5 + 4) & _M64)
            h = h ^ (k * np.uint64(_P1))
            h = _np_rotl(h, 23) * np.uint64(_P2) + np.uint64(_P3)
    else:
        raise ValueError(f"xxh64_fixed needs 4/8-byte items, got {itemsize}")
    with np.errstate(over="ignore"):
        h ^= h >> np.uint64(33)
        h *= np.uint64(_P2)
        h ^= h >> np.uint64(29)
        h *= np.uint64(_P3)
        h ^= h >> np.uint64(32)
    return h


# ---------------------------------------------------------------------------
# split-block bloom filter (SBBF)
# ---------------------------------------------------------------------------

_SALT = np.array([0x47B6137B, 0x44974D91, 0x8824AD5B, 0xA2B7289D,
                  0x705495C7, 0x2DF1424B, 0x9EFC4947, 0x5C6BFB31],
                 np.uint32)
_MIN_BYTES = 32  # one 256-bit block
# BloomFilterHeader field ids; algorithm/hash/compression are thrift
# unions whose single set field (fid 1) names the variant
_BFH_NUM_BYTES, _BFH_ALGO, _BFH_HASH, _BFH_COMP = 1, 2, 3, 4


class SplitBlockBloomFilter:
    """Parquet SBBF: ``num_bytes`` (any multiple of 32 >= 32 — this
    writer always sizes a power of two, but a READER must accept every
    spec-legal block count) of 256-bit blocks, 8 salted words each.
    Insert/check follow the spec exactly: block = mulhi32(upper32(h),
    num_blocks); within the block, word i gets bit
    ``(lower32(h) * SALT[i]) >> 27``."""

    def __init__(self, num_bytes: int) -> None:
        if num_bytes < _MIN_BYTES or num_bytes % 32:
            raise ValueError(
                f"SBBF size must be a multiple of 32 >= {_MIN_BYTES} "
                f"bytes (got {num_bytes})")
        self.num_bytes = num_bytes
        self._words = np.zeros(num_bytes // 4, np.uint32)

    @classmethod
    def for_ndv(cls, ndv: int, fpp: float = 0.01,
                max_bytes: int = 128 * 1024) -> "SplitBlockBloomFilter":
        """Size for ``ndv`` distinct values at false-positive rate ``fpp``
        (parquet-mr's formula: bits = -8*ndv / ln(1 - fpp^(1/8))), rounded
        up to a power of two and clamped to [32, max_bytes]."""
        if not 0.0 < fpp < 1.0:
            raise ValueError("fpp must be in (0, 1)")
        bits = -8.0 * max(ndv, 1) / math.log(1.0 - fpp ** 0.125)
        need = max(_MIN_BYTES, 1 << max(0, math.ceil(bits / 8) - 1)
                   .bit_length())
        cap = max(_MIN_BYTES, 1 << (int(max_bytes).bit_length() - 1))
        return cls(min(need, cap))

    @classmethod
    def from_bitset(cls, bitset: bytes) -> "SplitBlockBloomFilter":
        f = cls(len(bitset))
        f._words = np.frombuffer(bitset, dtype="<u4").copy()
        return f

    def _block_word_base(self, h: int) -> int:
        z = self.num_bytes // 32
        return (((h >> 32) * z) >> 32) * 8

    def insert_hash(self, h: int) -> None:
        base = self._block_word_base(h)
        x = np.uint32(h & 0xFFFFFFFF)
        with np.errstate(over="ignore"):
            bits = np.uint32(1) << ((x * _SALT) >> np.uint32(27))
        self._words[base: base + 8] |= bits

    def check_hash(self, h: int) -> bool:
        base = self._block_word_base(h)
        x = np.uint32(h & 0xFFFFFFFF)
        with np.errstate(over="ignore"):
            bits = np.uint32(1) << ((x * _SALT) >> np.uint32(27))
        return bool(np.all(self._words[base: base + 8] & bits == bits))

    def insert_hashes(self, hashes: np.ndarray) -> None:
        """Bulk insert (uint64 hash array) — one vectorized pass per salt
        word, the shape the fixed-width distinct-set population uses."""
        z = np.uint64(self.num_bytes // 32)
        with np.errstate(over="ignore"):
            base = (((hashes >> np.uint64(32)) * z) >> np.uint64(32)) * \
                np.uint64(8)
            x = hashes.astype(np.uint32)
            for i in range(8):
                bits = np.uint32(1) << ((x * _SALT[i]) >> np.uint32(27))
                np.bitwise_or.at(self._words, base + np.uint64(i), bits)

    def add_values(self, values, physical_type: int) -> None:
        """Hash + insert a set of values by their plain encoding: numeric
        ndarrays ride the vectorized hash, byte values the scalar one."""
        if isinstance(values, np.ndarray) and values.dtype.itemsize in (4, 8)\
                and values.dtype.kind in "iuf":
            self.insert_hashes(xxh64_fixed(values))
            return
        for v in values:
            self.insert_hash(xxh64(bytes(v)))

    def check_value(self, value, physical_type: int) -> bool:
        return self.check_hash(xxh64(plain_value_bytes(value,
                                                       physical_type)))

    def serialize(self) -> bytes:
        """BloomFilterHeader (thrift compact) + bitset, the on-file layout
        ColumnMetaData.bloom_filter_offset points at."""
        w = CompactWriter()
        w.struct_begin()
        w.field_i32(_BFH_NUM_BYTES, self.num_bytes)
        for fid in (_BFH_ALGO, _BFH_HASH, _BFH_COMP):
            w.field_struct_begin(fid)   # union wrapper ...
            w.field_struct_begin(1)     # ... variant 1 = BLOCK/XXHASH/UNCOMP
            w.struct_end()
            w.struct_end()
        w.struct_end()
        return w.getvalue() + self._words.astype("<u4").tobytes()


def plain_value_bytes(value, physical_type: int) -> bytes:
    """One value's plain encoding — the bytes the bloom hash covers."""
    fmt = _FIXED_FMT.get(physical_type)
    if fmt is not None:
        return struct.pack(fmt, value)
    return bytes(value)


def parse_bloom_header(data: bytes, offset: int,
                       limit: int | None = None) -> tuple[int, int]:
    """(num_bytes, bitset_offset) of a serialized bloom filter at
    ``offset``.  Raises ThriftDecodeError when the header is garbage or
    the unions don't carry a known variant."""
    r = CompactReader(data, offset, limit=limit)
    hdr = r.read_struct()
    nb = hdr.get(_BFH_NUM_BYTES)
    if not isinstance(nb, int) or isinstance(nb, bool) or nb < _MIN_BYTES \
            or nb % 32:
        raise ThriftDecodeError(
            f"bloom header numBytes {nb!r} invalid (need a multiple of 32 "
            f">= {_MIN_BYTES})")
    for fid, what in ((_BFH_ALGO, "algorithm"), (_BFH_HASH, "hash"),
                      (_BFH_COMP, "compression")):
        union = hdr.get(fid)
        if not isinstance(union, dict) or 1 not in union:
            raise ThriftDecodeError(
                f"bloom header {what} union missing variant 1")
    return nb, r.pos


def bloom_check(data: bytes, bloom_offset: int, value,
                physical_type: int) -> bool:
    """Probe a serialized bloom filter in ``data`` without touching any
    data page: False = the value is DEFINITELY absent from the chunk."""
    nb, bitset_off = parse_bloom_header(data, bloom_offset)
    if bitset_off + nb > len(data):
        raise ThriftDecodeError("bloom bitset overruns the file")
    f = SplitBlockBloomFilter.from_bitset(data[bitset_off: bitset_off + nb])
    return f.check_value(value, physical_type)
