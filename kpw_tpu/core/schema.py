"""Parquet schema model (physical types, repetition, nesting, rep/def math).

Replaces what the reference gets for free from parquet-mr's ``MessageType`` +
``ProtoWriteSupport`` (reference ParquetFile.java:97-99): a tree of fields,
flattened to the footer's ``SchemaElement`` list, with per-leaf max
repetition/definition levels computed per the Dremel model.
"""

from __future__ import annotations

from dataclasses import dataclass, field


# Physical types (parquet.thrift Type)
class PhysicalType:
    BOOLEAN = 0
    INT32 = 1
    INT64 = 2
    INT96 = 3
    FLOAT = 4
    DOUBLE = 5
    BYTE_ARRAY = 6
    FIXED_LEN_BYTE_ARRAY = 7


class Repetition:
    REQUIRED = 0
    OPTIONAL = 1
    REPEATED = 2


# parquet.thrift ConvertedType
class ConvertedType:
    UTF8 = 0
    MAP = 1
    MAP_KEY_VALUE = 2
    LIST = 3
    ENUM = 4
    DECIMAL = 5
    DATE = 6
    TIME_MILLIS = 7
    TIME_MICROS = 8
    TIMESTAMP_MILLIS = 9
    TIMESTAMP_MICROS = 10
    UINT_8 = 11
    UINT_16 = 12
    UINT_32 = 13
    UINT_64 = 14
    INT_8 = 15
    INT_16 = 16
    INT_32 = 17
    INT_64 = 18
    JSON = 19
    BSON = 20
    INTERVAL = 21


class Encoding:
    PLAIN = 0
    PLAIN_DICTIONARY = 2
    RLE = 3
    BIT_PACKED = 4
    DELTA_BINARY_PACKED = 5
    DELTA_LENGTH_BYTE_ARRAY = 6
    DELTA_BYTE_ARRAY = 7
    RLE_DICTIONARY = 8
    BYTE_STREAM_SPLIT = 9


class Codec:
    UNCOMPRESSED = 0
    SNAPPY = 1
    GZIP = 2
    BROTLI = 4
    LZ4 = 5
    ZSTD = 6
    LZ4_RAW = 7


class PageType:
    DATA_PAGE = 0
    INDEX_PAGE = 1
    DICTIONARY_PAGE = 2
    DATA_PAGE_V2 = 3


@dataclass
class Field:
    """One node of the schema tree.  Groups have children; leaves a type."""

    name: str
    repetition: int = Repetition.REQUIRED
    physical_type: int | None = None  # None => group
    converted_type: int | None = None
    type_length: int | None = None  # for FIXED_LEN_BYTE_ARRAY
    field_id: int | None = None
    children: list["Field"] = field(default_factory=list)

    @property
    def is_leaf(self) -> bool:
        return self.physical_type is not None


@dataclass
class ColumnDescriptor:
    """A leaf column with its Dremel levels and dotted path."""

    path: tuple[str, ...]
    leaf: Field
    max_def: int
    max_rep: int

    @property
    def name(self) -> str:
        return ".".join(self.path)


class Schema:
    """A rooted parquet schema; computes leaf columns and flattens for footers."""

    def __init__(self, fields: list[Field], name: str = "schema") -> None:
        self.root = Field(name=name, physical_type=None, children=fields)
        self.columns: list[ColumnDescriptor] = []
        self._walk(self.root, (), 0, 0)

    def _walk(self, node: Field, path: tuple[str, ...], max_def: int, max_rep: int) -> None:
        for child in node.children:
            d, r = max_def, max_rep
            if child.repetition == Repetition.OPTIONAL:
                d += 1
            elif child.repetition == Repetition.REPEATED:
                d += 1
                r += 1
            cpath = path + (child.name,)
            if child.is_leaf:
                self.columns.append(ColumnDescriptor(cpath, child, d, r))
            else:
                self._walk(child, cpath, d, r)

    def flatten(self) -> list[Field]:
        """Footer order: root first, then preorder."""
        out: list[Field] = []

        def rec(node: Field) -> None:
            out.append(node)
            for c in node.children:
                rec(c)

        rec(self.root)
        return out

    def column(self, dotted: str) -> ColumnDescriptor:
        for c in self.columns:
            if c.name == dotted:
                return c
        raise KeyError(dotted)


# canonical physical-type -> numpy dtype mapping (shared by all bridges)
import numpy as _np  # noqa: E402

NUMPY_DTYPES = {
    PhysicalType.INT32: _np.int32,
    PhysicalType.INT64: _np.int64,
    PhysicalType.FLOAT: _np.float32,
    PhysicalType.DOUBLE: _np.float64,
    PhysicalType.BOOLEAN: _np.bool_,
}


# -- convenience constructors ------------------------------------------------

_PHYS_BY_NAME = {
    "bool": PhysicalType.BOOLEAN,
    "boolean": PhysicalType.BOOLEAN,
    "int32": PhysicalType.INT32,
    "int64": PhysicalType.INT64,
    "float": PhysicalType.FLOAT,
    "float32": PhysicalType.FLOAT,
    "double": PhysicalType.DOUBLE,
    "float64": PhysicalType.DOUBLE,
    "bytes": PhysicalType.BYTE_ARRAY,
    "string": PhysicalType.BYTE_ARRAY,
}


def leaf(name: str, type_name: str, repetition: int = Repetition.REQUIRED,
         field_id: int | None = None) -> Field:
    """Build a leaf field from a short type name ('int64', 'string', ...)."""
    converted = ConvertedType.UTF8 if type_name == "string" else None
    return Field(
        name=name,
        repetition=repetition,
        physical_type=_PHYS_BY_NAME[type_name],
        converted_type=converted,
        field_id=field_id,
    )


def group(name: str, children: list[Field], repetition: int = Repetition.REQUIRED,
          converted_type: int | None = None) -> Field:
    return Field(name=name, repetition=repetition, children=children,
                 converted_type=converted_type)


def list_of(name: str, element: Field, repetition: int = Repetition.OPTIONAL) -> Field:
    """Standard 3-level LIST layout: name (LIST) -> repeated 'list' -> 'element'."""
    element.name = "element"
    return Field(
        name=name,
        repetition=repetition,
        converted_type=ConvertedType.LIST,
        children=[Field(name="list", repetition=Repetition.REPEATED, children=[element])],
    )
