"""Column-chunk assembly: values + rep/def levels -> dictionary/data pages.

This is the boundary the north star swaps for a pluggable backend: the
reference funnels every record through parquet-mr's ColumnWriter/PageWriter
(ParquetFile.java:59-62); here a whole column *batch* is encoded at once so
the encoder can be numpy (this module) or vmapped TPU kernels
(kpw_tpu.ops.backend.TpuChunkEncoder) producing identical bytes.
"""

from __future__ import annotations

import os
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from . import encodings as enc
from .bytecol import ByteColumn
from .compression import compress
from .index import PageStats, SplitBlockBloomFilter, xxh64
from .metadata import (
    DATA_PAGE_PREFIX,
    DICT_PAGE_PREFIX,
    ColumnChunk,
    ColumnMetaData,
    DataPageHeader,
    DictionaryPageHeader,
    Statistics,
    data_page_suffix,
    dict_page_suffix,
    fast_data_page_header,
    write_page_header,
)
from .schema import Codec, ColumnDescriptor, Encoding, PageType, PhysicalType
from .select_encoding import EncodingChooser
from ..utils.tracing import stage


@dataclass
class ColumnChunkData:
    """One column's data for a batch of rows (Dremel-shredded).

    ``values`` holds only the *present* leaf values (no nulls): an ndarray for
    fixed-width types or a list of ``bytes`` for BYTE_ARRAY.  ``def_levels`` /
    ``rep_levels`` are per-slot level arrays (None when max level is 0).
    ``num_rows`` is the number of top-level records covered.
    """

    column: ColumnDescriptor
    values: object
    def_levels: np.ndarray | None = None
    rep_levels: np.ndarray | None = None
    num_rows: int = 0

    @property
    def num_slots(self) -> int:
        if self.def_levels is not None:
            return len(self.def_levels)
        return len(self.values)

    _est_bytes: int | None = field(default=None, repr=False, compare=False)

    def estimated_bytes(self) -> int:
        # Memoized: the byte-list scan is O(n) and every consumer (batch
        # sizing, page geometry, the TPU planner) asks repeatedly.  Chunk
        # data is immutable once handed to the writer.
        if self._est_bytes is None:
            v = self.values
            if isinstance(v, np.ndarray):
                data = v.nbytes
            elif isinstance(v, ByteColumn):
                data = v.payload_bytes() + 4 * len(v)
            else:
                data = sum(len(x) + 4 for x in v)
            levels = 0
            if self.def_levels is not None:
                levels += len(self.def_levels)
            if self.rep_levels is not None:
                levels += len(self.rep_levels)
            self._est_bytes = data + levels // 4
        return self._est_bytes

def _min_max_bytes(values, physical_type: int):
    lo, hi, _, _ = _min_max_typed(values, physical_type)
    return lo, hi


def _min_max_typed(values, physical_type: int):
    """(min_bytes, max_bytes, min_key, max_key): the plain-encoded stats
    bytes plus python-comparable keys — the page index needs both (the
    bytes go in the ColumnIndex, the keys decide boundary order)."""
    if len(values) == 0:
        return None, None, None, None
    if physical_type in (PhysicalType.BYTE_ARRAY, PhysicalType.FIXED_LEN_BYTE_ARRAY):
        lo, hi = bytes(min(values)), bytes(max(values))
        return lo, hi, lo, hi
    arr = np.asarray(values)
    if arr.dtype.kind == "f":
        mask = ~np.isnan(arr)
        if not mask.any():
            return None, None, None, None
        arr = arr[mask]
    dtype = enc._PLAIN_DTYPES.get(physical_type)
    if physical_type == PhysicalType.BOOLEAN:
        lo, hi = bool(arr.min()), bool(arr.max())
        return bytes([lo]), bytes([hi]), lo, hi
    lo_v, hi_v = arr.min(), arr.max()
    lo = np.asarray(lo_v, dtype).tobytes()
    hi = np.asarray(hi_v, dtype).tobytes()
    return lo, hi, lo_v.item(), hi_v.item()


class EncodedChunk:
    """Serialized pages for one column chunk + footer metadata ingredients.

    ``parts`` is a writev-style gather list of page buffers (bytes /
    memoryview) in file order, dict page first if any: the writer hands
    the parts straight to the sink so the chunk's pages are never
    concatenated into one intermediate blob (the copy measured as the
    largest host-assembly slice at the 64-column uncompressed shape).
    ``blob`` joins lazily for callers that still want one buffer."""

    __slots__ = ("parts", "length", "meta", "dictionary_page_len", "_blob",
                 "pages", "bloom")

    def __init__(self, parts, meta: ColumnMetaData,
                 dictionary_page_len: int, length: int | None = None,
                 pages: list | None = None, bloom=None) -> None:
        if isinstance(parts, (bytes, bytearray, memoryview)):
            parts = [parts]  # compat: single pre-joined blob
        self.parts = parts
        self.length = (sum(len(p) for p in parts)
                       if length is None else length)
        self.meta = meta
        self.dictionary_page_len = dictionary_page_len  # 0 if none
        self._blob: bytes | None = None
        # query-ready-files carriers (core/index.py): per-data-page stats
        # for the ColumnIndex/OffsetIndex, and the populated bloom filter
        # (None when the respective feature is off for this chunk)
        self.pages = pages
        self.bloom = bloom

    @property
    def blob(self) -> bytes:
        """All pages back to back as one buffer (joined on first access)."""
        if self._blob is None:
            if len(self.parts) == 1 and isinstance(self.parts[0], bytes):
                self._blob = self.parts[0]
            else:
                self._blob = b"".join(self.parts)
        return self._blob


_POOL = None
_POOL_LOCK = threading.Lock()

# (num_values, encoding, crc_on) -> the constant data-page header suffix:
# page geometries repeat across chunks/row groups, so the nogil lowering
# reuses a handful of suffix fragments instead of composing one per page
# (same idea as ops/backend.py's _BP_PREFIXES; benign data race — worst
# case two threads build the same bytes once each)
_SUFFIX_CACHE: dict = {}


def _cached_data_suffix(num_values: int, encoding: int, crc_on: bool) -> bytes:
    key = (num_values, encoding, crc_on)
    s = _SUFFIX_CACHE.get(key)
    if s is None:
        if len(_SUFFIX_CACHE) > 4096:  # geometries are few; cap anyway
            _SUFFIX_CACHE.clear()
        s = _SUFFIX_CACHE[key] = data_page_suffix(num_values, encoding,
                                                  crc_on)
    return s


def shared_assembly_pool():
    """One process-wide host-assembly pool (column-parallel page building,
    native encode calls, column-chunk serialization): encoders are
    constructed per rotated file by the streaming writer, so a per-encoder
    pool would leak threads on every rotation.  Sized to the core count;
    callers gate on their own ``encoder_threads`` before using it."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=max(2, os.cpu_count() or 1),
                thread_name_prefix="kpw-encode")
        return _POOL


class PreparedRowGroup:
    """Opaque handle between :meth:`CpuChunkEncoder.launch_many` and
    :meth:`CpuChunkEncoder.assemble_many` — carries whatever the launch
    phase dispatched (device handles, resolved page plans) so the two
    halves can run on different pipeline threads for different row groups
    without colliding on encoder instance state."""

    __slots__ = ("pres", "state")

    def __init__(self, pres: list, state=None) -> None:
        self.pres = pres  # per-chunk prepare() results, encode()'s ``pre``
        self.state = state  # backend-private (e.g. the TPU planner's plans)


@dataclass
class EncoderOptions:
    codec: int = Codec.UNCOMPRESSED
    # None = codec default (zstd 3, gzip 6); parquet-mr exposes the same
    # knob through its codec configuration (SURVEY.md §5 config surface)
    compression_level: int | None = None
    enable_dictionary: bool = True
    data_page_size: int = 1024 * 1024
    dictionary_page_size_limit: int = 1024 * 1024
    max_dictionary_ratio: float = 0.67  # fall back to plain beyond this
    write_statistics: bool = True
    # Fallback value encoding when the dictionary is rejected/disabled:
    # False -> PLAIN (parquet-mr v1 behavior); True -> DELTA_BINARY_PACKED
    # for int columns and DELTA_LENGTH_BYTE_ARRAY for byte arrays
    # (BASELINE.md config 3: high-cardinality/string-heavy workloads).
    # LEGACY SPELLING: since ISSUE 16 this is a forced-override rule inside
    # the encoding chooser (core/select_encoding.py) — prefer
    # ``adaptive_encodings`` / the ``encodings`` override map.
    delta_fallback: bool = False
    # Stats-driven per-column encoding chooser (core/select_encoding.py):
    # row group 1's observed stats pick among PLAIN / dictionary+RLE /
    # DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY / BYTE_STREAM_SPLIT,
    # pinned per file for reader coherence.  Off = byte-identical
    # pre-chooser output (PLAIN / delta_fallback rules).
    adaptive_encodings: bool = False
    # Explicit per-column overrides (column name or dotted path -> Encoding
    # int or spec name); takes precedence over every adaptive rule and
    # disables the dictionary attempt for that column.
    encodings: dict | None = None
    # Column-parallel encode threads in the native backend (0 = one per
    # core).  The BASELINE target is per *host*, and the native primitives
    # release the GIL, so columns encode in parallel; 1 disables.
    encoder_threads: int = 0
    # Write the optional crc field in every page header: standard CRC-32
    # (gzip polynomial, PARQUET-1539) over the on-wire page body, after
    # compression.  parquet-mr 1.10 doesn't write it; readers that verify
    # (pyarrow page_checksum_verification) detect torn/corrupt pages.
    page_checksums: bool = False
    # Query-ready files (core/index.py): collect per-page min/max/null
    # stats during page assembly and emit PARQUET-922 ColumnIndex/
    # OffsetIndex sections at close (parquet-mr 1.11 writes them by
    # default too).  Off = byte-identical pre-index output.
    write_page_index: bool = True
    # Split-block bloom filters, opt-in (they cost file bytes): None =
    # disabled; () = auto — string columns plus any column whose chunk
    # dictionary-encoded (the build's exact distinct set makes population
    # a k-hash pass); a tuple of column names pins the set explicitly.
    bloom_columns: tuple | None = None
    bloom_fpp: float = 0.01
    bloom_max_bytes: int = 128 * 1024
    # Nogil batch page assembly (native/src/assemble.cc): the native/TPU
    # backends lower each chunk's resolved page plan to a flat parts/op
    # table and assemble (gather + RLE + compress + CRC + page stats) in
    # ONE GIL-released native call per column, so the shared assembly
    # pool shards columns across real cores.  False restores the pure
    # Python page loop byte-identically (the numpy oracle always uses it).
    native_assembly: bool = True


class CpuChunkEncoder:
    """Numpy reference encoder for one column chunk (whole batch at once).

    The four ``_*_body``/``_dictionary_build`` methods are the primitive-op
    boundary: the TPU backend (kpw_tpu.ops.backend.TpuChunkEncoder) subclasses
    this and swaps them for device kernels producing byte-identical streams.
    """

    def __init__(self, options: EncoderOptions) -> None:
        self.options = options
        # the ONE encoding-decision point (core/select_encoding.py):
        # override map > legacy delta_fallback > per-file adaptive pin
        self.chooser = EncodingChooser(options)
        # nogil-assembly accounting (chunks/pages that went through the
        # native assemble_pages call) — read by the writer's stats/meters;
        # the lock only guards the two increments (assembly pool threads)
        self.native_asm_chunks = 0
        self.native_asm_pages = 0
        self._asm_count_lock = threading.Lock()

    def begin_file(self) -> None:
        """Per-file reset hook, called by ``ParquetFileWriter.__init__``:
        the chooser's adaptive decisions are pinned per FILE (reader
        coherence), and a custom Builder backend may hand the same encoder
        object to every rotated file (runtime/parquet_file.py)."""
        self.chooser.begin_file()

    # -- primitive ops (overridden by the TPU backend) ---------------------
    def _dictionary_build(self, values, pt: int):
        """Return (dict_values, indices).  ``indices`` may be any object the
        matching ``_indices_body`` understands (ndarray here; a device handle
        in the TPU backend)."""
        return enc.dictionary_build(values, pt)

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        """Data-page value body for slots [va, vb) of a dictionary column."""
        return enc.dictionary_indices_encode(indices[va:vb], dict_size)

    def _plain_body(self, values, pt: int) -> bytes:
        return enc.plain_encode(values, pt)

    def _fallback_encoding(self, pt: int, col=None) -> int:
        """Value encoding for non-dictionary chunks — delegated WHOLLY to
        the chooser (core/select_encoding.py), the one decision point.
        With ``col`` the pinned/overridden per-column decision applies;
        without it only the column-independent rules (legacy
        ``delta_fallback``, PLAIN) can answer."""
        if col is not None:
            d = self.chooser.peek(col)
            if d is not None:
                return d.value_encoding
        return self.chooser.static_value_encoding(pt)

    def _values_body(self, values, pt: int, encoding: int) -> bytes:
        if encoding == Encoding.DELTA_BINARY_PACKED:
            bit_size = 32 if pt == PhysicalType.INT32 else 64
            return enc.delta_binary_packed_encode(np.asarray(values), bit_size)
        if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
            return enc.delta_length_byte_array_encode(values)
        if encoding == Encoding.BYTE_STREAM_SPLIT:
            return enc.byte_stream_split_encode(values, pt)
        return self._plain_body(values, pt)

    def _levels_body(self, levels: np.ndarray, max_level: int) -> bytes:
        return enc.rle_levels_v1(levels, max_level)

    def _stats_min_max(self, values, pt: int):
        """Column statistics min/max — overridable so backends can avoid
        iterating packed byte columns in Python."""
        return _min_max_bytes(values, pt)

    def _values_page_body(self, chunk: "ColumnChunkData", va: int, vb: int,
                          pt: int, encoding: int) -> bytes:
        """Non-dictionary value body for present-value range [va, vb) — the
        per-page boundary a backend can override with pre-planned bodies
        (the TPU delta planner)."""
        return self._values_body(chunk.values[va:vb], pt, encoding)

    def _values_page_parts(self, chunk: "ColumnChunkData", va: int, vb: int,
                           pt: int, encoding: int) -> list:
        """Value body as a list of buffers (bytes/memoryview).  Default wraps
        the single-body boundary; backends override to avoid materializing
        big concatenations (e.g. DELTA_LENGTH_BYTE_ARRAY = tiny delta header
        + multi-MB payload) when the codec can stream parts."""
        return [self._values_page_body(chunk, va, vb, pt, encoding)]

    def _compress_parts(self, parts: list, body_len: int):
        """Compress a page given as buffer parts.  Returns (buffer, length);
        buffer is None for UNCOMPRESSED (caller appends the parts verbatim).
        The returned buffer may be scratch reused by the NEXT page — consume
        immediately."""
        opts = self.options
        if opts.codec == Codec.UNCOMPRESSED:
            return None, body_len
        data = parts[0] if len(parts) == 1 else b"".join(parts)
        comp = compress(bytes(data) if not isinstance(data, bytes) else data,
                        opts.codec, opts.compression_level)
        return comp, len(comp)

    def _levels_page_blob(self, chunk: "ColumnChunkData", a: int, b: int) -> bytes:
        """rep + def level streams for slots [a, b) — the per-page boundary
        the TPU backend overrides with planned device-encoded bodies."""
        col = chunk.column
        blob = b""
        if col.max_rep > 0:
            blob += self._levels_body(chunk.rep_levels[a:b], col.max_rep)
        if col.max_def > 0:
            blob += self._levels_body(chunk.def_levels[a:b], col.max_def)
        return blob

    def _native_assembler(self):
        """The nogil page-assembly extension module, or None to use the
        Python page loops.  The numpy oracle stays pure Python — the
        native/TPU backends override (gated on ``options.native_assembly``,
        the loaded extension, and a codec the native path covers)."""
        return None

    def _planned_levels_blob(self, chunk: "ColumnChunkData", a: int,
                             b: int) -> bytes | None:
        """A pre-resolved rep+def level blob for slots [a, b), or None when
        the native assembly lowering should RLE-encode the level streams
        itself (the TPU backend overrides with its planner's blobs)."""
        return None

    def _planned_level_ops(self, chunk: "ColumnChunkData", a: int,
                           b: int) -> list | None:
        """Op-level form of :meth:`_planned_levels_blob` for assemblers
        that carry the RLE-from-runs op (``OP_KINDS >= 4``): None, or a
        list of descriptors in stream order —

        * ``("raw", part)`` — bytes/buffer emitted verbatim (already
          carrying its v1 length prefix), and
        * ``("runs", run_vals u32, run_lens i32, width)`` — the device
          level planner's compact run table, replayed to the exact
          mixed RLE/bit-pack stream INSIDE the one nogil native call
          (kOpRleRuns, kModeLen32 prefix) instead of through the
          Python ``rle_hybrid_from_runs`` loop.

        The TPU backend overrides; the default has no planner."""
        return None

    def _page_stats_min_max(self, chunk: "ColumnChunkData", va: int, vb: int,
                            pt: int):
        """Per-page (min_bytes, max_bytes, min_key, max_key) over the
        present-value range [va, vb) — the page-index stats boundary a
        backend can override (the native encoder routes ByteColumn pages
        through the C++ lexicographic scan)."""
        return _min_max_typed(chunk.values[va:vb], pt)

    def _page_crc(self, parts: list) -> int | None:
        """Checksum of the on-wire page body (post-compression), streamed
        across parts so the uncompressed multi-part path stays concat-free.
        The PageHeader crc field uses standard CRC-32 (gzip polynomial
        0x04C11DB7, PARQUET-1539) — NOT CRC32C, which parquet reserves for
        Hadoop-style block checksums.  None when checksums are disabled
        (the optional field is omitted)."""
        if not self.options.page_checksums:
            return None
        crc = 0
        for p in parts:
            crc = zlib.crc32(p, crc)
        # thrift i32 is signed: reinterpret the uint32 CRC (Arrow casts the
        # same way; an out-of-range positive varint would read back wrong)
        return crc - (1 << 32) if crc >= (1 << 31) else crc

    def _try_dictionary(self, chunk: ColumnChunkData):
        """Build (dict_values, indices), or return None when the build can
        prove ahead of time that the dictionary would be rejected (backends
        may abort early; the resulting file bytes are identical either way
        because rejection falls back to the same non-dictionary encoding)."""
        return self._dictionary_build(chunk.values, chunk.column.leaf.physical_type)

    def prepare(self, chunk: ColumnChunkData):
        """Launch-phase hook for pipelined backends: precompute whatever can
        be dispatched asynchronously for ``chunk``; the result is handed back
        to :meth:`encode` as ``pre``.  The CPU encoder has nothing to launch."""
        return None

    def _finish_prepare(self, pre):
        """Materialize a :meth:`prepare` handle into (dict_values, indices),
        or None to fall through to the synchronous ``_dictionary_build``."""
        return pre

    # -- split row-group encode (launch || assemble) -----------------------
    # The writer's overlapped pipeline drives these two halves from
    # different threads: row group N+1's launch_many (device dispatch in
    # the TPU backend) runs while row group N is still in assemble_many
    # (pure host page building).  encode_many composes them inline, so the
    # sync path and every backend stay byte-identical by construction.

    # Whether launch_many performs real asynchronous work worth its own
    # pipeline stage.  False here (and for the native backend): prepare()
    # is a no-op, so a split stage would only DEEPEN the pipe — one more
    # detached-but-unencoded row group estimated at the unlearned size
    # ratio, which measurably skews the first file's size-based rotation.
    # The TPU backend overrides to True: its launch dispatches the
    # planner's device programs, the thing the assembly stage overlaps.
    split_launch_overlaps = False

    def launch_many(self, chunks: list[ColumnChunkData]) -> PreparedRowGroup:
        """Phase 1: dispatch whatever can run asynchronously for a whole
        row group (device programs in the TPU backend; nothing here).
        Returns the handle :meth:`assemble_many` consumes."""
        return PreparedRowGroup([self.prepare(c) for c in chunks])

    def _parallel_assembly_ok(self) -> bool:
        """Whether assemble_many may shard columns across the shared pool.
        The pure-numpy oracle stays sequential (its primitives hold the
        GIL; threading adds overhead, not parallelism) — the native/TPU
        backends override to True when their GIL-releasing primitives are
        loaded."""
        return False

    def _assembly_workers(self, n_chunks: int) -> int:
        workers = self.options.encoder_threads or (os.cpu_count() or 1)
        return min(workers, n_chunks)

    def assemble_many(self, chunks: list[ColumnChunkData],
                      prepared: PreparedRowGroup,
                      base_offset: int) -> list["EncodedChunk"]:
        """Phase 2: pure host assembly of every column's pages.  Shards
        columns across the shared pool when the backend's primitives
        release the GIL (``encoder_threads`` sizes it; 1 pins serial):
        each chunk encodes at offset 0 (page bytes never embed offsets),
        then footer offsets shift by the running base — byte-identical to
        the sequential path."""
        workers = self._assembly_workers(len(chunks))
        if workers > 1 and self._parallel_assembly_ok():
            # Batched tasks (a few per worker, not one per column): every
            # pool handoff is a GIL round trip whose reacquire can stall a
            # full switch interval behind the other thread — at 64 columns
            # the per-column submit/result churn measurably convoyed the
            # 2-thread arm.  Sharded MANUALLY (one submitted callable
            # encodes a slice of columns serially, order preserved):
            # Executor.map's chunksize parameter is ignored by
            # ThreadPoolExecutor, so passing it would batch nothing.
            # 4 shards per worker keeps load balance without the
            # per-column round trips.
            pairs = list(zip(chunks, prepared.pres))
            size = max(1, -(-len(pairs) // (4 * workers)))
            shards = [pairs[i:i + size] for i in range(0, len(pairs), size)]

            def encode_shard(shard: list) -> list:
                return [self.encode(c, 0, pre=p) for c, p in shard]

            out = [e for enc_shard in
                   shared_assembly_pool().map(encode_shard, shards)
                   for e in enc_shard]
            return self._shift_offsets(out, base_offset)
        out = []
        offset = base_offset
        for chunk, pre in zip(chunks, prepared.pres):
            e = self.encode(chunk, offset, pre=pre)
            offset += e.length
            out.append(e)
        return out

    def encode_many(self, chunks: list[ColumnChunkData], base_offset: int) -> list["EncodedChunk"]:
        """Encode several chunks laid out back to back.  Launches all device
        work first (async dispatch), then assembles in order so host assembly
        of column i overlaps device compute of columns i+1.."""
        return self.assemble_many(chunks, self.launch_many(chunks),
                                  base_offset)

    @staticmethod
    def _shift_offsets(encoded: list["EncodedChunk"],
                       base_offset: int) -> list["EncodedChunk"]:
        """Footer-offset fixup for chunks encoded at offset 0 in parallel:
        the ONE definition of which meta fields carry file offsets, shared
        by every backend — a new offset field added here reaches all."""
        offset = base_offset
        for e in encoded:
            m = e.meta
            if m.dictionary_page_offset is not None:
                m.dictionary_page_offset += offset
            m.data_page_offset += offset
            offset += e.length
        return encoded

    # -- query-ready metadata (core/index.py) ------------------------------
    def _bloom_on(self, col, pt: int, dict_accepted: bool) -> bool:
        """Whether this chunk gets a bloom filter.  Explicit
        ``bloom_columns`` pins the set; the auto mode ``()`` covers string
        columns plus any column whose chunk actually dictionary-encoded
        (``dict_accepted`` — the ratio/size gates passed, so cardinality
        is low enough that a filter can prune and population is a k-hash
        pass over the exact set).  Keying on acceptance, not on "a build
        ran", keeps emission backend-identical: the CPU build never
        ratio-aborts early while native/mesh do, but all backends agree
        on what is *accepted*."""
        cols = self.options.bloom_columns
        if cols is None or pt == PhysicalType.BOOLEAN:
            return False
        if cols:
            return col.name in cols or ".".join(col.path) in cols
        return pt in (PhysicalType.BYTE_ARRAY,
                      PhysicalType.FIXED_LEN_BYTE_ARRAY) or dict_accepted

    def _bloom_wants_distinct(self, chunk: ColumnChunkData) -> bool:
        """True when bloom filters are configured for this column, so a
        backend's dictionary-build ratio/byte early-abort should hand back
        the full distinct set anyway — the filter needs it, and a second
        distinct pass would cost more than the completed build (the
        native/mesh ``_try_dictionary`` overrides consult this).
        ``dict_accepted=False``: whether the build will be accepted is
        not knowable here, so only the unconditional selection terms
        apply — auto-mode fixed-width blooms ride acceptance, which never
        needs an abort waiver (an accepted build completed by definition)."""
        return self._bloom_on(chunk.column, chunk.column.leaf.physical_type,
                              False)

    def _build_bloom(self, chunk: ColumnChunkData, pt: int, dict_values):
        """Populate one chunk's SBBF: from the dictionary's exact distinct
        set when a build ran (dictionary-encoded OR rejected — the set is
        exact either way, and on the device backends it is the mesh-global
        merged dictionary), else a host distinct pass over the present
        values."""
        opts = self.options
        if dict_values is not None:
            distinct = dict_values
        elif isinstance(chunk.values, np.ndarray):
            distinct = np.unique(chunk.values)
        else:
            distinct = {bytes(v) for v in chunk.values}
        f = SplitBlockBloomFilter.for_ndv(len(distinct), opts.bloom_fpp,
                                          opts.bloom_max_bytes)
        f.add_values(distinct, pt)
        return f

    # -- helpers -----------------------------------------------------------
    def _dictionary_viable(self, chunk: ColumnChunkData) -> bool:
        if not self.options.enable_dictionary:
            return False
        pt = chunk.column.leaf.physical_type
        if pt == PhysicalType.BOOLEAN:
            return False
        n = len(chunk.values)
        return n > 0

    def _page_slot_ranges(self, chunk: ColumnChunkData, est_total_bytes: int) -> list[tuple[int, int]]:
        """Split the chunk's slots into data pages of ~data_page_size bytes.
        Page boundaries must fall on record starts (rep level 0) so readers can
        count rows per page."""
        num_slots = chunk.num_slots
        if num_slots == 0:
            return [(0, 0)]
        slots_per_page = max(
            1, int(num_slots * self.options.data_page_size / max(est_total_bytes, 1))
        )
        if slots_per_page >= num_slots:
            return [(0, num_slots)]
        record_starts = None
        if chunk.rep_levels is not None:
            record_starts = np.nonzero(np.asarray(chunk.rep_levels) == 0)[0]
        ranges = []
        a = 0
        while a < num_slots:
            b = min(a + slots_per_page, num_slots)
            if record_starts is not None and b < num_slots:
                i = np.searchsorted(record_starts, b)
                b = int(record_starts[i]) if i < len(record_starts) else num_slots
            ranges.append((a, b))
            a = b
        return ranges

    def _slot_ranges(self, chunk: ColumnChunkData) -> list[tuple[int, int]]:
        """Page slot ranges for ``chunk`` — the single entry point so a
        backend can memoize the O(num_slots) record-start scan across the
        planner/encode passes that all need the same geometry."""
        return self._page_slot_ranges(chunk, chunk.estimated_bytes())

    def _chunk_statistics(self, chunk: ColumnChunkData, pt: int,
                          use_dict: bool, dict_values,
                          page_stats: list | None) -> Statistics | None:
        """Footer Statistics for one chunk — ONE definition shared by the
        Python page loops and the native assembly path, so the two cannot
        drift.  Reduces over the per-page min/max when the page-index pass
        already walked every value (O(pages)); dictionary chunks reduce
        over the distinct set (O(k)); otherwise one full value scan."""
        if not self.options.write_statistics:
            return None
        col = chunk.column
        if page_stats:
            # the per-page min/max just collected covers every present
            # value with the same plain encoding, so the chunk stats
            # reduce over pages in O(pages) — not a second O(n) value
            # scan (or O(k) dictionary scan, which is also a numpy GIL
            # release/reacquire per chunk the 2-thread assembly pool
            # pays for in handoff stalls)
            mins = [(ps.min_key, ps.min_bytes) for ps in page_stats
                    if ps.min_key is not None]
            maxs = [(ps.max_key, ps.max_bytes) for ps in page_stats
                    if ps.max_key is not None]
            lo = min(mins, key=lambda t: t[0])[1] if mins else None
            hi = max(maxs, key=lambda t: t[0])[1] if maxs else None
        else:
            # The dictionary is exactly the set of present values, so
            # its min/max equals the column's — O(k) instead of O(n).
            stat_src = dict_values if use_dict else chunk.values
            lo, hi = self._stats_min_max(stat_src, pt)
        null_count = None
        if chunk.def_levels is not None:
            null_count = int((chunk.def_levels < col.max_def).sum())
        elif col.max_def == 0:
            null_count = 0
        if lo is not None or null_count is not None:
            return Statistics(null_count=null_count, min_value=lo,
                              max_value=hi)
        return None

    # numpy dtype -> native/src/assemble.cc StatsDtype code (0 = no native
    # page stats; the lowering falls back to the per-page numpy oracle)
    _STATS_DTYPES = {
        np.dtype(np.int32): 1, np.dtype(np.int64): 2,
        np.dtype(np.uint32): 3, np.dtype(np.uint64): 4,
        np.dtype(np.float32): 5, np.dtype(np.float64): 6,
        np.dtype(np.bool_): 7,
    }

    def _encode_native_chunk(self, chunk: ColumnChunkData, base_offset: int,
                             *, use_dict, dict_values, indices, dict_plain,
                             value_encoding, encodings, def_levels,
                             value_offsets, record_starts, page_stats_on,
                             bloom) -> EncodedChunk | None:
        """Lower this chunk's fully resolved page plan to the flat page/op
        tables of native/src/assemble.cc and assemble every page (gather +
        RLE + compress + CRC + fixed-width page stats) in ONE GIL-released
        native call.  Byte-identical to the Python page loops by
        construction: bodies either come from the same planner/primitive
        boundaries (RAW ops) or are RLE-encoded by the same object code the
        ctypes path runs (RLE ops), and the header fragments compose
        exactly :func:`write_page_header`'s v1 bytes (pinned in
        tests/test_assemble.py)."""
        asm = self._native_assembler()
        opts = self.options
        col = chunk.column
        pt = col.leaf.physical_type
        crc_on = opts.page_checksums
        flags = 1 if crc_on else 0
        values = chunk.values

        buffers: list = []
        ops: list = []      # kOpStride=5 slots per op
        pages: list = []    # kPageStride=7 slots per page

        def add_buf(obj) -> int:
            buffers.append(obj)
            return len(buffers) - 1

        def add_raw(part) -> None:
            if isinstance(part, (bytes, bytearray)):
                n = len(part)
            elif isinstance(part, np.ndarray):
                if not part.flags.c_contiguous:
                    part = np.ascontiguousarray(part)
                n = part.nbytes
            else:
                n = memoryview(part).nbytes
            ops.extend((0, add_buf(part), 0, n, 0))

        # level streams as u32 once per chunk (the RLE ops slice them)
        max_rep, max_def = col.max_rep, col.max_def
        rep_buf = def_buf = -1
        rep_aux = def_aux = 0
        if max_rep > 0:
            rep_buf = add_buf(np.ascontiguousarray(
                np.asarray(chunk.rep_levels), np.uint32))
            rep_aux = enc.bit_width(max_rep) | (2 << 8)  # kModeLen32
        if max_def > 0:
            def_buf = add_buf(np.ascontiguousarray(
                np.asarray(def_levels), np.uint32))
            def_aux = enc.bit_width(max_def) | (2 << 8)

        if use_dict:
            nd = len(dict_values)
            dict_prefix = add_buf(DICT_PAGE_PREFIX)
            dict_suffix = add_buf(dict_page_suffix(
                # lint: encoding-choice ok — dict page header field, not a
                # value-encoding choice (acceptance was decided upstream)
                nd, Encoding.PLAIN_DICTIONARY, crc_on))
            op_start = len(ops) // 5
            add_raw(dict_plain)
            pages.extend((op_start, len(ops) // 5, dict_prefix, dict_suffix,
                          flags, 0, 0))
            idx_w = enc.bit_width(max(nd - 1, 0))
            idx_buf = -1
            if isinstance(indices, np.ndarray):
                idx = indices
                if idx.dtype != np.uint32 or not idx.flags.c_contiguous:
                    idx = np.ascontiguousarray(idx, np.uint32)
                idx_buf = add_buf(idx)
            idx_aux = idx_w | (1 << 8)  # kModeWidthByte
        else:
            nd = 0
            idx_buf = -1

        # op-kind generation of the loaded assembler: >= 4 adds the
        # nested-pipeline ops (RLE-from-runs for planner level streams,
        # bytes-plain straight from the packed ByteColumn representation);
        # a stale cached .so keeps the old lowering
        asm_ops = getattr(asm, "OP_KINDS", 2)

        # zero-copy PLAIN: the page body IS the contiguous value slice
        contig_vals = None
        if isinstance(values, np.ndarray):
            contig_vals = (values if values.flags.c_contiguous
                           else np.ascontiguousarray(values))
        plain_raw = (not use_dict and value_encoding == Encoding.PLAIN
                     and contig_vals is not None
                     and values.dtype == enc._PLAIN_DTYPES.get(pt))
        # BYTE_STREAM_SPLIT straight from the contiguous value buffer: the
        # byte-plane transpose runs INSIDE the one nogil native call
        # (kOpBss, OP_KINDS >= 5), so BSS pages cost no host
        # materialization — same zero-copy shape as plain_raw
        bss_raw = (not use_dict
                   and value_encoding == Encoding.BYTE_STREAM_SPLIT
                   and asm_ops >= 5 and contig_vals is not None
                   and values.dtype == enc._PLAIN_DTYPES.get(pt))
        val_buf = add_buf(contig_vals) if plain_raw or bss_raw else -1
        isz = values.dtype.itemsize if plain_raw or bss_raw else 0

        # packed BYTE_ARRAY PLAIN: the page body assembles from the
        # ByteColumn's (data, offsets) buffers inside the native call
        # (kOpBytesPlain — 4-byte LE length + raw bytes per value,
        # byte-identical to byte_array_plain_encode), so non-dictionary
        # string pages cost no host materialization at all
        bytes_plain = (not use_dict and value_encoding == Encoding.PLAIN
                       and asm_ops >= 4 and isinstance(values, ByteColumn)
                       and pt == PhysicalType.BYTE_ARRAY)
        if bytes_plain:
            ba_data_buf = add_buf(values.data)
            ba_offs_buf = add_buf(np.ascontiguousarray(values.offsets,
                                                       np.int64))

        sdt = 0
        if page_stats_on and contig_vals is not None:
            sdt = self._STATS_DTYPES.get(contig_vals.dtype, 0)

        data_prefix = add_buf(DATA_PAGE_PREFIX)
        suffixes: dict = {}  # num_values -> registered suffix buffer index
        data_rows: list = []  # per data page: (a, b, va, vb)
        for a, b in self._slot_ranges(chunk):
            if def_levels is not None:
                va, vb = int(value_offsets[a]), int(value_offsets[b])
            else:
                va, vb = a, b
            op_start = len(ops) // 5
            if max_rep > 0 or max_def > 0:
                lvl_ops = (self._planned_level_ops(chunk, a, b)
                           if asm_ops >= 4 else None)
                if lvl_ops is not None:
                    for d in lvl_ops:
                        if d[0] == "raw":
                            add_raw(d[1])
                        else:  # ("runs", vals u32, lens i32, width)
                            _, rv, rl, width = d
                            rv_buf = add_buf(np.ascontiguousarray(
                                rv, np.uint32))
                            rl_buf = add_buf(np.ascontiguousarray(
                                rl, np.int32))
                            ops.extend((2, rv_buf, 0, len(rv),
                                        width | (2 << 8) | (rl_buf << 16)))
                else:
                    planned = self._planned_levels_blob(chunk, a, b)
                    if planned is not None:
                        add_raw(planned)
                    else:
                        if max_rep > 0:
                            ops.extend((1, rep_buf, a, b, rep_aux))
                        if max_def > 0:
                            ops.extend((1, def_buf, a, b, def_aux))
            if use_dict:
                if idx_buf >= 0:
                    ops.extend((1, idx_buf, va, vb, idx_aux))
                else:
                    # planner bodies (_PageBodies) / device indices: the
                    # backend resolves them; bytes or a parts list
                    body = self._indices_body(indices, va, vb, nd)
                    if type(body) is list:
                        for part in body:
                            add_raw(part)
                    else:
                        add_raw(body)
            elif plain_raw:
                ops.extend((0, val_buf, va * isz, vb * isz, 0))
            elif bss_raw:
                # element-indexed (aux = value width): the native op
                # transposes values [va, vb) into their byte planes
                ops.extend((4, val_buf, va, vb, isz))
            elif bytes_plain:
                ops.extend((3, ba_data_buf, va, vb, ba_offs_buf << 16))
            else:
                for part in self._values_page_parts(chunk, va, vb, pt,
                                                    value_encoding):
                    add_raw(part)
            suffix = suffixes.get(b - a)
            if suffix is None:
                suffix = suffixes[b - a] = add_buf(_cached_data_suffix(
                    b - a, value_encoding, crc_on))
            pages.extend((op_start, len(ops) // 5, data_prefix, suffix,
                          flags, va, vb))
            data_rows.append((a, b, va, vb))

        n_pages = len(pages) // 7
        out_meta = np.empty((n_pages, 3), np.int64)
        if sdt:
            out_stats = np.empty((n_pages, 2), contig_vals.dtype)
            out_mask = np.empty(n_pages, np.uint8)
        else:
            out_stats = out_mask = None
        level = opts.compression_level
        if level is None:
            level = 3  # zstd default (core/compression.py); others ignore
        with stage("assemble.native", column=col.name):
            blob = asm.assemble_pages(
                tuple(buffers), np.array(pages, np.int64),
                np.array(ops, np.int64), int(opts.codec), int(level),
                contig_vals if sdt else None, sdt, out_meta, out_stats,
                out_mask)

        header_total = int(out_meta[:, 2].sum())
        total_uncompressed = header_total + int(out_meta[:, 0].sum())
        total_compressed = header_total + int(out_meta[:, 1].sum())
        first_data = 1 if use_dict else 0
        dict_page_len = 0
        dictionary_page_offset = None
        if use_dict:
            dict_page_len = int(out_meta[0, 1] + out_meta[0, 2])
            dictionary_page_offset = base_offset

        page_stats = None
        if page_stats_on:
            page_stats = []
            page_off = dict_page_len
            plain_dtype = enc._PLAIN_DTYPES.get(pt)
            for i, (a, b, va, vb) in enumerate(data_rows):
                row = first_data + i
                size = int(out_meta[row, 1] + out_meta[row, 2])
                if sdt:
                    m = int(out_mask[row])
                    if m == 1:
                        lo_v, hi_v = out_stats[row, 0], out_stats[row, 1]
                        if pt == PhysicalType.BOOLEAN:
                            lo_k, hi_k = bool(lo_v), bool(hi_v)
                            lo_b, hi_b = bytes([lo_k]), bytes([hi_k])
                        else:
                            lo_b = np.asarray(lo_v, plain_dtype).tobytes()
                            hi_b = np.asarray(hi_v, plain_dtype).tobytes()
                            lo_k, hi_k = lo_v.item(), hi_v.item()
                    elif m == 0:  # empty page / all-NaN
                        lo_b = hi_b = lo_k = hi_k = None
                    else:
                        # ±0.0 tie on min or max: numpy's SIMD lane order
                        # decides the winning sign — re-run the oracle so
                        # the ColumnIndex bytes cannot drift from it
                        lo_b, hi_b, lo_k, hi_k = self._page_stats_min_max(
                            chunk, va, vb, pt)
                else:
                    lo_b, hi_b, lo_k, hi_k = self._page_stats_min_max(
                        chunk, va, vb, pt)
                page_stats.append(PageStats(
                    first_row_index=(a if record_starts is None
                                     else int(np.searchsorted(
                                         record_starts, a))),
                    offset=page_off, compressed_size=size, num_values=b - a,
                    null_count=((b - a) - (vb - va)
                                if def_levels is not None else 0),
                    min_bytes=lo_b, max_bytes=hi_b,
                    min_key=lo_k, max_key=hi_k))
                page_off += size

        stats = self._chunk_statistics(chunk, pt, use_dict, dict_values,
                                       page_stats)
        meta = ColumnMetaData(
            type=pt,
            encodings=sorted(encodings),
            path_in_schema=list(col.path),
            codec=opts.codec,
            num_values=chunk.num_slots,
            total_uncompressed_size=total_uncompressed,
            total_compressed_size=total_compressed,
            data_page_offset=base_offset + dict_page_len,
            dictionary_page_offset=dictionary_page_offset,
            statistics=stats,
        )
        with self._asm_count_lock:
            self.native_asm_chunks += 1
            self.native_asm_pages += n_pages
        return EncodedChunk([blob], meta, dict_page_len, length=len(blob),
                            pages=page_stats, bloom=bloom)

    def encode(self, chunk: ColumnChunkData, base_offset: int, pre=None) -> EncodedChunk:
        """Encode a chunk into pages.  ``base_offset`` is the absolute file
        offset where the blob will be written (for footer offsets).  ``pre``
        is the result of :meth:`prepare` when driven via :meth:`encode_many`."""
        col = chunk.column
        pt = col.leaf.physical_type
        opts = self.options

        use_dict = False
        dict_values = None
        indices = None
        n_uniq = None
        if self._dictionary_viable(chunk) and \
                self.chooser.dictionary_wanted(col):
            built = self._finish_prepare(pre) if pre is not None else None
            if built is None:
                built = self._try_dictionary(chunk)
            if built is not None:
                dict_values, indices = built
                n_uniq = len(dict_values)
                n = len(indices)
                if n_uniq <= max(1, int(n * opts.max_dictionary_ratio)):
                    dict_plain = enc.plain_encode(dict_values, pt)
                    if len(dict_plain) <= opts.dictionary_page_size_limit:
                        use_dict = True

        # the one decision point: pinned per file after row group 1 (the
        # dictionary build just handed cardinality over for free)
        decision = self.chooser.choose(chunk, pt, dict_accepted=use_dict,
                                       dict_size=n_uniq)
        encodings = set()
        if use_dict:
            # lint: encoding-choice ok — dictionary is an acceptance
            # mechanism (the chooser gates whether to ATTEMPT the build;
            # PLAIN_DICTIONARY is what acceptance spells on the wire)
            value_encoding = Encoding.PLAIN_DICTIONARY
            # lint: encoding-choice ok — footer encodings list spelling
            # of the accepted dictionary (levels are RLE by spec)
            encodings.update([Encoding.PLAIN_DICTIONARY, Encoding.RLE])
        else:
            value_encoding = decision.value_encoding
            encodings.add(value_encoding)
        if col.max_def > 0 or col.max_rep > 0:
            # lint: encoding-choice ok — footer encodings list; levels
            # are always RLE by spec, never chosen
            encodings.add(Encoding.RLE)

        # Map slots -> present-value offsets for page slicing.
        def_levels = chunk.def_levels
        value_offsets = None
        if def_levels is not None:
            present = np.asarray(def_levels) == col.max_def
            value_offsets = np.concatenate([[0], np.cumsum(present)])
        # Query-ready metadata (core/index.py): per-page stats for the
        # ColumnIndex/OffsetIndex, collected as pages are laid out (page
        # offsets relative to the chunk's first byte — made absolute at
        # footer time), and the chunk's bloom filter.  The bloom populates
        # from the dictionary build's exact distinct set whenever one ran
        # (accepted OR ratio-rejected; on the device backends this is the
        # mesh-global dictionary), so it costs k hashes, not n.
        page_stats: list | None = [] if opts.write_page_index else None
        record_starts = None
        if page_stats is not None and chunk.rep_levels is not None:
            record_starts = np.nonzero(np.asarray(chunk.rep_levels) == 0)[0]
        bloom = None
        if self._bloom_on(col, pt, use_dict):
            with stage("encode.bloom", column=col.name):
                bloom = self._build_bloom(chunk, pt, dict_values)
        if self._native_assembler() is not None:
            out = self._encode_native_chunk(
                chunk, base_offset,
                use_dict=use_dict, dict_values=dict_values, indices=indices,
                dict_plain=dict_plain if use_dict else None,
                value_encoding=value_encoding, encodings=encodings,
                def_levels=def_levels, value_offsets=value_offsets,
                record_starts=record_starts,
                page_stats_on=page_stats is not None, bloom=bloom)
            if out is not None:
                return out

        # -- Python page loops (the oracle, and the native fallback) -------
        # Pages accumulate as a PARTS LIST handed to the writer verbatim
        # (EncodedChunk.parts): no bytearray doubling, no bytes() bounce,
        # and since the writer gathers parts straight into the sink, no
        # join either — the page buffers are copied exactly once, by the
        # sink write itself.
        blob_parts: list = []
        blob_len = 0
        dict_page_len = 0
        total_uncompressed = 0
        total_compressed = 0
        dictionary_page_offset = None
        data_page_offset = None

        if use_dict:
            comp_buf, comp_len = self._compress_parts([dict_plain],
                                                      len(dict_plain))
            header = write_page_header(
                PageType.DICTIONARY_PAGE,
                len(dict_plain),
                comp_len,
                # lint: encoding-choice ok — dict page header field
                dict_header=DictionaryPageHeader(len(dict_values), Encoding.PLAIN_DICTIONARY),
                crc=self._page_crc([dict_plain] if comp_buf is None
                                   else [comp_buf]),
            )
            dictionary_page_offset = base_offset
            blob_parts.append(header)
            # comp_buf may be a REUSED compressor scratch (native zstd/
            # snappy paths): it must be materialized before the next page
            # overwrites it — the join at the end reads parts lazily
            blob_parts.append(dict_plain if comp_buf is None
                              else bytes(comp_buf))
            blob_len += len(header) + comp_len
            dict_page_len = len(header) + comp_len
            total_uncompressed += len(header) + len(dict_plain)
            total_compressed += len(header) + comp_len

        if (opts.codec == Codec.UNCOMPRESSED and not opts.page_checksums
                and col.max_def == 0 and col.max_rep == 0):
            # Tight loop for the hot shape (flat required column,
            # uncompressed, no CRC — the cfg2 headline): no level blob, no
            # compress/crc dispatch, header straight through the direct
            # composer.  Byte-identical to the generic loop below by
            # construction (same body bytes, same fast header).
            nd = len(dict_values) if use_dict else 0
            for a, b in self._slot_ranges(chunk):
                if use_dict:
                    body = self._indices_body(indices, a, b, nd)
                    # planner bodies may arrive as a parts LIST
                    # (zero-copy prefix + packed view)
                    parts = body if type(body) is list else [body]
                else:
                    parts = self._values_page_parts(chunk, a, b, pt,
                                                    value_encoding)
                body_len = sum(map(len, parts))
                header = fast_data_page_header(body_len, body_len, b - a,
                                               value_encoding)
                if data_page_offset is None:
                    data_page_offset = base_offset + blob_len
                page_off = blob_len
                blob_parts.append(header)
                blob_parts.extend(parts)
                hl = len(header)
                blob_len += hl + body_len
                total_uncompressed += hl + body_len
                total_compressed += hl + body_len
                if page_stats is not None:
                    # flat required column: slot == row, no nulls
                    lo_b, hi_b, lo_k, hi_k = self._page_stats_min_max(
                        chunk, a, b, pt)
                    page_stats.append(PageStats(
                        first_row_index=a, offset=page_off,
                        compressed_size=hl + body_len, num_values=b - a,
                        null_count=0, min_bytes=lo_b, max_bytes=hi_b,
                        min_key=lo_k, max_key=hi_k))
        else:
            for a, b in self._slot_ranges(chunk):
                if def_levels is not None:
                    va, vb = int(value_offsets[a]), int(value_offsets[b])
                else:
                    va, vb = a, b
                levels_blob = self._levels_page_blob(chunk, a, b)
                if use_dict:
                    body = self._indices_body(indices, va, vb,
                                              len(dict_values))
                    parts = body if type(body) is list else [body]
                else:
                    parts = self._values_page_parts(chunk, va, vb, pt,
                                                    value_encoding)
                if levels_blob:
                    parts.insert(0, levels_blob)
                body_len = sum(len(p) for p in parts)
                comp_buf, comp_len = self._compress_parts(parts, body_len)
                header = write_page_header(
                    PageType.DATA_PAGE,
                    body_len,
                    comp_len,
                    data_header=DataPageHeader(
                        num_values=b - a,
                        encoding=value_encoding,
                        # lint: encoding-choice ok — level encodings are
                        # always RLE by spec, never chosen
                        definition_level_encoding=Encoding.RLE,
                        # lint: encoding-choice ok — same: levels are RLE
                        repetition_level_encoding=Encoding.RLE,
                    ),
                    crc=self._page_crc(parts if comp_buf is None
                                       else [comp_buf]),
                )
                if data_page_offset is None:
                    data_page_offset = base_offset + blob_len
                page_off = blob_len
                blob_parts.append(header)
                if comp_buf is None:
                    blob_parts.extend(parts)  # uncompressed: verbatim
                else:
                    blob_parts.append(bytes(comp_buf))  # scratch: see above
                blob_len += len(header) + comp_len
                total_uncompressed += len(header) + body_len
                total_compressed += len(header) + comp_len
                if page_stats is not None:
                    lo_b, hi_b, lo_k, hi_k = self._page_stats_min_max(
                        chunk, va, vb, pt)
                    page_stats.append(PageStats(
                        first_row_index=(a if record_starts is None
                                         else int(np.searchsorted(
                                             record_starts, a))),
                        offset=page_off,
                        compressed_size=len(header) + comp_len,
                        num_values=b - a,
                        null_count=((b - a) - (vb - va)
                                    if def_levels is not None else 0),
                        min_bytes=lo_b, max_bytes=hi_b,
                        min_key=lo_k, max_key=hi_k))

        stats = self._chunk_statistics(chunk, pt, use_dict, dict_values,
                                       page_stats)

        meta = ColumnMetaData(
            type=pt,
            encodings=sorted(encodings),
            path_in_schema=list(col.path),
            codec=opts.codec,
            num_values=chunk.num_slots,
            total_uncompressed_size=total_uncompressed,
            total_compressed_size=total_compressed,
            data_page_offset=data_page_offset,
            dictionary_page_offset=dictionary_page_offset,
            statistics=stats,
        )
        # No join: the parts list IS the output (writev-style gather all
        # the way to the sink) — the last whole-output-volume memcpy on
        # the assembly hot path, gone.
        return EncodedChunk(blob_parts, meta, dict_page_len, length=blob_len,
                            pages=page_stats, bloom=bloom)
