"""Page compression codecs.

Mirrors the codec surface the reference exposes via parquet-mr
(``CompressionCodecName`` set at KafkaProtoParquetWriter.java:484, default
UNCOMPRESSED; the only native code in the reference system is the codec JNI —
SURVEY.md §2.2).  Preference order per codec:

1. the framework's own C++ library (``kpw_tpu.native``) — Snappy implemented
   from scratch, ZSTD via libzstd;
2. system libraries via ctypes / stdlib fallbacks.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import zlib

from .schema import Codec

_snappy_ct = None


def _load_snappy_ctypes():
    global _snappy_ct
    if _snappy_ct is not None:
        return _snappy_ct
    for name in ("libsnappy.so.1", "libsnappy.so", ctypes.util.find_library("snappy")):
        if not name:
            continue
        try:
            lib = ctypes.CDLL(name)
            lib.snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.snappy_compress.restype = ctypes.c_int
            lib.snappy_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
            ]
            lib.snappy_uncompress.restype = ctypes.c_int
            lib.snappy_uncompress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
            ]
            lib.snappy_uncompressed_length.restype = ctypes.c_int
            lib.snappy_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
            ]
            _snappy_ct = lib
            return lib
        except OSError:
            continue
    _snappy_ct = False
    return False


def _native():
    try:
        from .. import native

        return native.lib()
    except Exception:
        return None


def snappy_compress(data: bytes) -> bytes:
    lib = _native()
    if lib is not None:
        return lib.snappy_compress(data)
    ct = _load_snappy_ctypes()
    if ct:
        max_len = ct.snappy_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(max_len)
        out_len = ctypes.c_size_t(max_len)
        rc = ct.snappy_compress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"snappy_compress failed rc={rc}")
        return out.raw[: out_len.value]
    raise RuntimeError("no snappy implementation available")


def snappy_decompress(data: bytes) -> bytes:
    lib = _native()
    if lib is not None:
        return lib.snappy_decompress(data)
    ct = _load_snappy_ctypes()
    if ct:
        out_len = ctypes.c_size_t(0)
        rc = ct.snappy_uncompressed_length(data, len(data), ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError("bad snappy stream")
        out = ctypes.create_string_buffer(out_len.value)
        rc = ct.snappy_uncompress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError("snappy_uncompress failed")
        return out.raw[: out_len.value]
    raise RuntimeError("no snappy implementation available")


def zstd_compress(data: bytes, level: int = 3) -> bytes:
    lib = _native()
    if lib is not None:
        out = lib.zstd_compress(data, level)
        if out is not None:
            return out
    import zstandard

    return zstandard.ZstdCompressor(level=level).compress(data)


def zstd_decompress(data: bytes) -> bytes:
    lib = _native()
    if lib is not None:
        out = lib.zstd_decompress(data)
        if out is not None:
            return out
    import zstandard

    return zstandard.ZstdDecompressor().decompress(data)


def compress(data: bytes, codec: int, level: int | None = None) -> bytes:
    """``level`` applies to level-capable codecs (zstd default 3, gzip
    default 6 — parquet-mr's codec configuration surface, exposed via
    Builder.compression_level); snappy has no level knob."""
    if codec == Codec.UNCOMPRESSED:
        return data
    if codec == Codec.SNAPPY:
        return snappy_compress(data)
    if codec == Codec.GZIP:
        co = zlib.compressobj(6 if level is None else level,
                              zlib.DEFLATED, 16 + 15)
        return co.compress(data) + co.flush()
    if codec == Codec.ZSTD:
        return zstd_compress(data, 3 if level is None else level)
    raise ValueError(f"unsupported codec {codec}")


def decompress(data: bytes, codec: int, uncompressed_size: int | None = None) -> bytes:
    if codec == Codec.UNCOMPRESSED:
        return data
    if codec == Codec.SNAPPY:
        return snappy_decompress(data)
    if codec == Codec.GZIP:
        return zlib.decompress(data, 16 + 15)
    if codec == Codec.ZSTD:
        return zstd_decompress(data)
    raise ValueError(f"unsupported codec {codec}")


_CODEC_NAMES = {
    "uncompressed": Codec.UNCOMPRESSED,
    "none": Codec.UNCOMPRESSED,
    "snappy": Codec.SNAPPY,
    "gzip": Codec.GZIP,
    "zstd": Codec.ZSTD,
}


def codec_from_name(name) -> int:
    if isinstance(name, int):
        return name
    return _CODEC_NAMES[name.lower()]
