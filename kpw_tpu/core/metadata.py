"""Thrift-compact serializers for parquet footer/page-header structs.

Field ids follow parquet-format's parquet.thrift.  The reference never sees
these bytes (parquet-mr owns them); we write them directly so the whole file
format is under this framework's control.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .schema import Encoding, PageType, PhysicalType, Repetition  # noqa: F401  (re-export convenience)
from .thrift import CT_BINARY, CT_I32, CT_I64, CT_STRUCT, CompactWriter

CREATED_BY = "kpw_tpu version 0.1.0 (build tpu-native)"


@dataclass
class Statistics:
    null_count: int | None = None
    distinct_count: int | None = None
    min_value: bytes | None = None
    max_value: bytes | None = None

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        if self.null_count is not None:
            w.field_i64(3, self.null_count)
        if self.distinct_count is not None:
            w.field_i64(4, self.distinct_count)
        if self.max_value is not None:
            w.field_binary(5, self.max_value)
        if self.min_value is not None:
            w.field_binary(6, self.min_value)
        w.struct_end()


@dataclass
class DataPageHeader:
    num_values: int
    encoding: int
    definition_level_encoding: int
    repetition_level_encoding: int
    statistics: Statistics | None = None

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i32(1, self.num_values)
        w.field_i32(2, self.encoding)
        w.field_i32(3, self.definition_level_encoding)
        w.field_i32(4, self.repetition_level_encoding)
        if self.statistics is not None:
            w._field_header(5, CT_STRUCT)
            self.statistics.write(w)
        w.struct_end()


@dataclass
class DataPageHeaderV2:
    num_values: int
    num_nulls: int
    num_rows: int
    encoding: int
    definition_levels_byte_length: int
    repetition_levels_byte_length: int
    is_compressed: bool = True

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i32(1, self.num_values)
        w.field_i32(2, self.num_nulls)
        w.field_i32(3, self.num_rows)
        w.field_i32(4, self.encoding)
        w.field_i32(5, self.definition_levels_byte_length)
        w.field_i32(6, self.repetition_levels_byte_length)
        if not self.is_compressed:
            w.field_bool(7, False)
        w.struct_end()


@dataclass
class DictionaryPageHeader:
    num_values: int
    encoding: int

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i32(1, self.num_values)
        w.field_i32(2, self.encoding)
        w.struct_end()


def _zzv(out: bytearray, n: int) -> None:
    """zigzag varint straight into ``out`` (the compact protocol's i32/i64
    value encoding; python ints, so one formula covers both widths)."""
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def fast_data_page_header(uncompressed_size: int, compressed_size: int,
                          num_values: int, encoding: int) -> bytes:
    """The v1 DATA_PAGE header's exact compact-thrift bytes, composed
    directly — byte-identical to :func:`write_page_header` for the no-CRC
    RLE-levels shape (asserted over randomized values in
    tests/test_parquet_core.py) but without the per-field writer dispatch,
    which profiled at ~7% of the whole 64-column uncompressed encode."""
    o = bytearray(b"\x15\x00\x15")  # field1 i32 type=0(zz=0); field2 hdr
    _zzv(o, uncompressed_size)
    o.append(0x15)  # field 3 i32
    _zzv(o, compressed_size)
    o.append(0x2C)  # field 5 struct (delta 2: CRC field 4 absent)
    o.append(0x15)  # .field 1 i32 num_values
    _zzv(o, num_values)
    o.append(0x15)  # .field 2 i32 encoding
    _zzv(o, encoding)
    # .fields 3/4: definition/repetition level encoding, always RLE (3)
    o += b"\x15\x06\x15\x06\x00\x00"  # + inner stop + outer stop
    return bytes(o)


# Header FRAGMENTS for the nogil assembly path (native/src/assemble.cc):
# the C++ side emits ``prefix + zzvarint(uncompressed) + 0x15 +
# zzvarint(compressed) [+ 0x15 + zzvarint(crc)] + suffix`` per page, so
# everything except the two size varints (and the optional CRC, computed
# after compression) is composed here.  Byte-identical to
# :func:`write_page_header` for the v1 shapes (pinned in
# tests/test_assemble.py over randomized values).
DATA_PAGE_PREFIX = b"\x15\x00\x15"  # field1 i32 type=0(zz=0); field2 hdr
DICT_PAGE_PREFIX = b"\x15\x04\x15"  # field1 i32 type=2(zz=4); field2 hdr


def data_page_suffix(num_values: int, encoding: int,
                     crc_on: bool = False) -> bytes:
    """Everything after the compressed-size/CRC varints of a v1 DATA_PAGE
    header: the DataPageHeader struct (field 5 — delta 1 after the CRC
    field 4, delta 2 otherwise) with RLE level encodings."""
    o = bytearray((0x1C if crc_on else 0x2C, 0x15))
    _zzv(o, num_values)
    o.append(0x15)  # .field 2 i32 encoding
    _zzv(o, encoding)
    o += b"\x15\x06\x15\x06\x00\x00"  # RLE/RLE + inner stop + outer stop
    return bytes(o)


def dict_page_suffix(num_values: int, encoding: int,
                     crc_on: bool = False) -> bytes:
    """DICTIONARY_PAGE counterpart of :func:`data_page_suffix` (field 7 —
    delta 3 after the CRC field 4, delta 4 otherwise)."""
    o = bytearray((0x3C if crc_on else 0x4C, 0x15))
    _zzv(o, num_values)
    o.append(0x15)  # .field 2 i32 encoding
    _zzv(o, encoding)
    o += b"\x00\x00"  # inner stop + outer stop
    return bytes(o)


def fast_dict_page_header(uncompressed_size: int, compressed_size: int,
                          num_values: int, encoding: int) -> bytes:
    """DICTIONARY_PAGE counterpart of :func:`fast_data_page_header`."""
    o = bytearray(b"\x15\x04\x15")  # field1 i32 type=2 (zz=4); field2 hdr
    _zzv(o, uncompressed_size)
    o.append(0x15)  # field 3 i32
    _zzv(o, compressed_size)
    o.append(0x4C)  # field 7 struct (delta 4)
    o.append(0x15)  # .field 1 i32 num_values
    _zzv(o, num_values)
    o.append(0x15)  # .field 2 i32 encoding
    _zzv(o, encoding)
    o += b"\x00\x00"  # inner stop + outer stop
    return bytes(o)


def write_page_header(
    page_type: int,
    uncompressed_size: int,
    compressed_size: int,
    data_header: DataPageHeader | None = None,
    dict_header: DictionaryPageHeader | None = None,
    v2_header: DataPageHeaderV2 | None = None,
    crc: int | None = None,
) -> bytes:
    if crc is None and v2_header is None:
        # hot shapes ride the direct composers (identical bytes)
        if (data_header is not None and dict_header is None
                and page_type == PageType.DATA_PAGE
                and data_header.statistics is None
                and data_header.definition_level_encoding == Encoding.RLE
                and data_header.repetition_level_encoding == Encoding.RLE):
            return fast_data_page_header(
                uncompressed_size, compressed_size,
                data_header.num_values, data_header.encoding)
        if (dict_header is not None and data_header is None
                and page_type == PageType.DICTIONARY_PAGE):
            return fast_dict_page_header(
                uncompressed_size, compressed_size,
                dict_header.num_values, dict_header.encoding)
    w = CompactWriter()
    w.struct_begin()
    w.field_i32(1, page_type)
    w.field_i32(2, uncompressed_size)
    w.field_i32(3, compressed_size)
    if crc is not None:
        w.field_i32(4, crc)
    if data_header is not None:
        w._field_header(5, CT_STRUCT)
        data_header.write(w)
    if dict_header is not None:
        w._field_header(7, CT_STRUCT)
        dict_header.write(w)
    if v2_header is not None:
        w._field_header(8, CT_STRUCT)
        v2_header.write(w)
    w.struct_end()
    return w.getvalue()


@dataclass
class ColumnMetaData:
    type: int
    encodings: list[int]
    path_in_schema: list[str]
    codec: int
    num_values: int
    total_uncompressed_size: int
    total_compressed_size: int
    data_page_offset: int
    dictionary_page_offset: int | None = None
    statistics: Statistics | None = None
    # split-block bloom filter section (parquet.thrift fields 14/15),
    # assigned at close() when the index sections land in the file —
    # the query-ready-files layer (core/index.py)
    bloom_filter_offset: int | None = None
    bloom_filter_length: int | None = None

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i32(1, self.type)
        w.field_list_begin(2, CT_I32, len(self.encodings))
        for e in self.encodings:
            w.list_i32(e)
        w.field_list_begin(3, CT_BINARY, len(self.path_in_schema))
        for p in self.path_in_schema:
            w.list_binary(p.encode("utf-8"))
        w.field_i32(4, self.codec)
        w.field_i64(5, self.num_values)
        w.field_i64(6, self.total_uncompressed_size)
        w.field_i64(7, self.total_compressed_size)
        w.field_i64(9, self.data_page_offset)
        if self.dictionary_page_offset is not None:
            w.field_i64(11, self.dictionary_page_offset)
        if self.statistics is not None:
            w._field_header(12, CT_STRUCT)
            self.statistics.write(w)
        if self.bloom_filter_offset is not None:
            w.field_i64(14, self.bloom_filter_offset)
        if self.bloom_filter_length is not None:
            w.field_i32(15, self.bloom_filter_length)
        w.struct_end()


@dataclass
class SortingColumn:
    """RowGroup ``sorting_columns`` entry (parquet.thrift SortingColumn):
    a declaration that the row group's rows are sorted by the leaf at
    ``column_idx`` — what readers need before they can binary-search or
    merge files, and what sort-on-compact (io/compact.py) publishes."""

    column_idx: int
    descending: bool = False
    nulls_first: bool = False

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i32(1, self.column_idx)
        w.field_bool(2, self.descending)
        w.field_bool(3, self.nulls_first)
        w.struct_end()


@dataclass
class ColumnChunk:
    file_offset: int
    meta_data: ColumnMetaData
    # PARQUET-922 page-index section pointers (parquet.thrift fields 4-7),
    # assigned at close() once the serialized ColumnIndex/OffsetIndex land
    offset_index_offset: int | None = None
    offset_index_length: int | None = None
    column_index_offset: int | None = None
    column_index_length: int | None = None
    # builder-side carriers, never serialized: the encoder's per-page
    # stats (core.index.PageStats) and populated bloom filter ride the
    # ColumnChunk from commit to close, where the sections are written
    page_stats: list | None = field(default=None, repr=False, compare=False)
    bloom: object = field(default=None, repr=False, compare=False)

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_i64(2, self.file_offset)
        w._field_header(3, CT_STRUCT)
        self.meta_data.write(w)
        if self.offset_index_offset is not None:
            w.field_i64(4, self.offset_index_offset)
            w.field_i32(5, self.offset_index_length)
        if self.column_index_offset is not None:
            w.field_i64(6, self.column_index_offset)
            w.field_i32(7, self.column_index_length)
        w.struct_end()


def _vu(out: bytearray, n: int) -> None:
    """unsigned varint straight into ``out``."""
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def fast_column_chunk(cc: "ColumnChunk") -> bytes:
    """One ColumnChunk's exact compact-thrift bytes, composed directly —
    byte-identical to :meth:`ColumnChunk.write` across every optional
    combination (asserted over randomized values in
    tests/test_parquet_core.py).  The footer writes one of these per
    column per row group through the generic per-field writer, the last
    remaining Python serialization block on the 64-column encode."""
    m = cc.meta_data
    o = bytearray()
    o.append(0x26)  # field 2 i64 file_offset
    _zzv(o, cc.file_offset)
    o.append(0x1C)  # field 3 struct meta_data
    o.append(0x15)  # .1 i32 type
    _zzv(o, m.type)
    o.append(0x19)  # .2 list<i32> encodings
    ne = len(m.encodings)
    if ne < 15:
        o.append((ne << 4) | 5)
    else:
        o.append(0xF5)
        _vu(o, ne)
    for e in m.encodings:
        _zzv(o, e)
    o.append(0x19)  # .3 list<binary> path_in_schema
    npath = len(m.path_in_schema)
    if npath < 15:
        o.append((npath << 4) | 8)
    else:
        o.append(0xF8)
        _vu(o, npath)
    for p in m.path_in_schema:
        b = p.encode("utf-8")
        _vu(o, len(b))
        o += b
    o.append(0x15)  # .4 i32 codec
    _zzv(o, m.codec)
    o.append(0x16)  # .5 i64 num_values
    _zzv(o, m.num_values)
    o.append(0x16)  # .6 i64 total_uncompressed_size
    _zzv(o, m.total_uncompressed_size)
    o.append(0x16)  # .7 i64 total_compressed_size
    _zzv(o, m.total_compressed_size)
    o.append(0x26)  # .9 i64 data_page_offset (delta 2: field 8 unused)
    _zzv(o, m.data_page_offset)
    last = 9
    if m.dictionary_page_offset is not None:
        o.append(0x26)  # .11 i64 (delta 2: field 10 unused)
        _zzv(o, m.dictionary_page_offset)
        last = 11
    if m.statistics is not None:
        o.append(((12 - last) << 4) | 12)  # .12 struct statistics
        last = 12
        s = m.statistics
        slast = 0
        if s.null_count is not None:
            o.append(((3 - slast) << 4) | 6)
            _zzv(o, s.null_count)
            slast = 3
        if s.distinct_count is not None:
            o.append(((4 - slast) << 4) | 6)
            _zzv(o, s.distinct_count)
            slast = 4
        if s.max_value is not None:
            o.append(((5 - slast) << 4) | 8)
            _vu(o, len(s.max_value))
            o += s.max_value
            slast = 5
        if s.min_value is not None:
            o.append(((6 - slast) << 4) | 8)
            _vu(o, len(s.min_value))
            o += s.min_value
        o.append(0)  # statistics stop
    if m.bloom_filter_offset is not None:
        o.append(((14 - last) << 4) | 6)  # .14 i64 bloom_filter_offset
        _zzv(o, m.bloom_filter_offset)
        last = 14
        if m.bloom_filter_length is not None:
            o.append(0x15)  # .15 i32 bloom_filter_length (delta 1)
            _zzv(o, m.bloom_filter_length)
    o.append(0)  # ColumnMetaData stop
    clast = 3  # ColumnChunk's own field cursor (2, 3 written above)
    if cc.offset_index_offset is not None:
        o.append(((4 - clast) << 4) | 6)  # .4 i64 offset_index_offset
        _zzv(o, cc.offset_index_offset)
        o.append(0x15)  # .5 i32 offset_index_length
        _zzv(o, cc.offset_index_length)
        clast = 5
    if cc.column_index_offset is not None:
        o.append(((6 - clast) << 4) | 6)  # .6 i64 column_index_offset
        _zzv(o, cc.column_index_offset)
        o.append(0x15)  # .7 i32 column_index_length
        _zzv(o, cc.column_index_length)
    o.append(0)  # ColumnChunk stop
    return bytes(o)


@dataclass
class RowGroup:
    columns: list[ColumnChunk]
    total_byte_size: int
    num_rows: int
    sorting_columns: list[SortingColumn] | None = None
    file_offset: int | None = None
    total_compressed_size: int | None = None
    ordinal: int | None = None
    # serialized ColumnChunk fragments, precomputed at commit time by the
    # pipelined writer so close() only splices bytes (None = serialize in
    # write(), the non-pipelined path)
    _cc_bytes: list | None = field(default=None, repr=False, compare=False)

    def precompute_column_bytes(self, pool=None) -> None:
        """Serialize every column chunk's footer fragment NOW — called by
        the writer right after the row group's offsets are final, so the
        per-column thrift composition rides the overlapped assembly window
        instead of the close() critical path.  ``pool`` (optional
        concurrent.futures executor) shards the composition per column.
        Must not be called before the metas' file offsets are absolute."""
        if pool is not None and len(self.columns) > 1:
            self._cc_bytes = list(pool.map(fast_column_chunk, self.columns))
        else:
            self._cc_bytes = [fast_column_chunk(c) for c in self.columns]

    def write(self, w: CompactWriter) -> None:
        w.struct_begin()
        w.field_list_begin(1, CT_STRUCT, len(self.columns))
        # complete nested structs: their field-delta state is confined,
        # so the direct composer's bytes splice in verbatim
        for b in (self._cc_bytes if self._cc_bytes is not None
                  else map(fast_column_chunk, self.columns)):
            w.append_raw(b)
        w.field_i64(2, self.total_byte_size)
        w.field_i64(3, self.num_rows)
        if self.sorting_columns:
            w.field_list_begin(4, CT_STRUCT, len(self.sorting_columns))
            for sc in self.sorting_columns:
                sc.write(w)
        if self.file_offset is not None:
            w.field_i64(5, self.file_offset)
        if self.total_compressed_size is not None:
            w.field_i64(6, self.total_compressed_size)
        if self.ordinal is not None:
            w.field_i16(7, self.ordinal)
        w.struct_end()


def _write_schema_element(w: CompactWriter, f) -> None:
    """f: kpw_tpu.core.schema.Field"""
    w.struct_begin()
    if f.is_leaf:
        w.field_i32(1, f.physical_type)
        if f.type_length is not None:
            w.field_i32(2, f.type_length)
    # root has no repetition in common practice unless set
    if f.repetition is not None:
        w.field_i32(3, f.repetition)
    w.field_string(4, f.name)
    if not f.is_leaf and f.children:
        w.field_i32(5, len(f.children))
    if f.converted_type is not None:
        w.field_i32(6, f.converted_type)
    if f.field_id is not None:
        w.field_i32(9, f.field_id)
    w.struct_end()


@dataclass
class FileMetaData:
    schema_fields: list  # flattened Fields, root first
    num_rows: int
    row_groups: list[RowGroup]
    key_value_metadata: list[tuple[str, str]] = field(default_factory=list)
    created_by: str = CREATED_BY
    version: int = 1

    def serialize(self) -> bytes:
        w = CompactWriter()
        w.struct_begin()
        w.field_i32(1, self.version)
        w.field_list_begin(2, CT_STRUCT, len(self.schema_fields))
        for f in self.schema_fields:
            _write_schema_element(w, f)
        w.field_i64(3, self.num_rows)
        w.field_list_begin(4, CT_STRUCT, len(self.row_groups))
        for rg in self.row_groups:
            rg.write(w)
        if self.key_value_metadata:
            w.field_list_begin(5, CT_STRUCT, len(self.key_value_metadata))
            for k, v in self.key_value_metadata:
                w.struct_begin()
                w.field_string(1, k)
                if v is not None:
                    w.field_string(2, v)
                w.struct_end()
        w.field_string(6, self.created_by)
        # column_orders: TypeDefinedOrder for every leaf — readers only trust
        # min_value/max_value statistics when this is present
        num_leaves = sum(1 for f in self.schema_fields if f.is_leaf)
        w.field_list_begin(7, CT_STRUCT, num_leaves)
        for _ in range(num_leaves):
            w.struct_begin()
            w.field_struct_begin(1)  # TypeDefinedOrder (empty struct)
            w.struct_end()
            w.struct_end()
        w.struct_end()
        return w.getvalue()
