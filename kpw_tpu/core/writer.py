"""Parquet file writer: row-group assembly + footer.

Owns the whole physical file layout ("PAR1" magic, page blobs, thrift footer)
— the role parquet-mr's ``ParquetFileWriter`` plays underneath the reference's
``ParquetFile`` wrapper (ParquetFile.java:36-68).  Batch-oriented: callers
append :class:`ColumnBatch`es; a row group is flushed when its accumulated
size crosses ``row_group_size`` (the reference's ``blockSize``,
KafkaProtoParquetWriter.java:473).
"""

from __future__ import annotations

import io
import json
import os
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .bytecol import ByteColumn
from .index import serialize_column_index, serialize_offset_index
from .metadata import (ColumnChunk, FileMetaData, RowGroup, SortingColumn)
from .pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions
from .schema import PhysicalType, Schema
from ..utils.tracing import stage

MAGIC = b"PAR1"


class PipelineError(RuntimeError):
    """A pipeline stage failed after its row group was detached from the
    pending buffer: the data cannot be recovered by retrying, so the writer
    is poisoned — every subsequent operation re-raises.  Deliberately NOT an
    OSError: the runtime's infinite-IO-retry must not spin on it; the worker
    dies un-acked and the records are redelivered (at-least-once)."""


class StatQueue(queue.Queue):
    """Bounded stage queue with backpressure instrumentation: live depth,
    high watermark, and cumulative blocked-on-put / blocked-on-get stall
    seconds.  Put stall = the producer stage waiting on a full queue (the
    downstream stage is the bottleneck); get stall = the consumer stage
    starved (the upstream stage is).  The non-blocking fast path costs one
    extra try per operation and only the SLOW path (already sleeping on
    the queue's condition) takes the stats lock around a timer read — the
    un-contended hot path's overhead is a counter bump."""

    def __init__(self, maxsize: int = 0) -> None:
        super().__init__(maxsize)
        self._stat_lock = threading.Lock()
        self.high_watermark = 0
        self.put_stall_s = 0.0
        self.get_stall_s = 0.0
        self.puts = 0
        self.gets = 0

    def put(self, item, block: bool = True, timeout=None) -> None:
        try:
            super().put(item, block=False)
        except queue.Full:
            if not block:
                raise
            t0 = time.perf_counter()
            try:
                super().put(item, block=True, timeout=timeout)
            finally:
                # a timed-out Full still stalled the producer: count it
                with self._stat_lock:
                    self.put_stall_s += time.perf_counter() - t0
        depth = self.qsize()
        with self._stat_lock:
            self.puts += 1
            if depth > self.high_watermark:
                self.high_watermark = depth

    def get(self, block: bool = True, timeout=None):
        try:
            item = super().get(block=False)
        except queue.Empty:
            if not block:
                raise
            t0 = time.perf_counter()
            try:
                item = super().get(block=True, timeout=timeout)
            finally:
                with self._stat_lock:
                    self.get_stall_s += time.perf_counter() - t0
        with self._stat_lock:
            self.gets += 1
        return item

    def stats(self) -> dict:
        with self._stat_lock:
            return {
                "depth": self.qsize(),
                "high_watermark": self.high_watermark,
                "put_stall_s": round(self.put_stall_s, 6),
                "get_stall_s": round(self.get_stall_s, 6),
                "puts": self.puts,
                "gets": self.gets,
            }


@dataclass
class WriterProperties:
    """Mirrors the reference's ParquetProperties (ParquetFile.java:105-122):
    blockSize, pageSize, codec, enableDictionary — plus encoder backend."""

    row_group_size: int = 128 * 1024 * 1024
    data_page_size: int = 1024 * 1024
    codec: int = 0
    compression_level: int | None = None
    enable_dictionary: bool = True
    write_statistics: bool = True
    # LEGACY SPELLING (see core/select_encoding.py): a forced per-type
    # override rule inside the encoding chooser — kept for back-compat;
    # prefer adaptive_encodings / the encodings override map below
    delta_fallback: bool = False
    # adaptive per-column encodings: decide from row group 1's observed
    # stats, pinned per file (reader coherence); encodings maps column
    # name/dotted path -> Encoding and takes precedence over everything
    adaptive_encodings: bool = False
    encodings: dict | None = None
    encoder_threads: int = 0
    page_checksums: bool = False
    key_value_metadata: dict = field(default_factory=dict)
    # query-ready files (core/index.py): PARQUET-922 page indexes on by
    # default (parquet-mr 1.11 parity), bloom filters opt-in (None = off,
    # () = auto: string/dictionary columns, tuple = explicit columns),
    # sorting declarations as (column_name, descending, nulls_first)
    write_page_index: bool = True
    bloom_columns: tuple | None = None
    bloom_fpp: float = 0.01
    bloom_max_bytes: int = 128 * 1024
    sorting_columns: tuple = ()
    # nogil batch page assembly (native/src/assemble.cc): on by default
    # where a backend supports it; False restores the pure-Python page
    # loops byte-identically (tests/test_assemble.py pins the identity)
    native_assembly: bool = True

    def encoder_options(self) -> EncoderOptions:
        return EncoderOptions(
            codec=self.codec,
            compression_level=self.compression_level,
            enable_dictionary=self.enable_dictionary,
            data_page_size=self.data_page_size,
            write_statistics=self.write_statistics,
            delta_fallback=self.delta_fallback,
            adaptive_encodings=self.adaptive_encodings,
            encodings=self.encodings,
            encoder_threads=self.encoder_threads,
            page_checksums=self.page_checksums,
            write_page_index=self.write_page_index,
            bloom_columns=self.bloom_columns,
            bloom_fpp=self.bloom_fpp,
            bloom_max_bytes=self.bloom_max_bytes,
            native_assembly=self.native_assembly,
        )


class ColumnBatch:
    """A batch of rows in columnar form: list of ColumnChunkData, one per
    schema leaf, all covering the same rows."""

    # serialized-payload bytes this batch was shredded from (set by the wire
    # shredder; None for batches built from parsed records/arrays) — lets
    # the worker meter written bytes without re-walking the records
    wire_bytes: int | None = None

    def __init__(self, chunks: list[ColumnChunkData], num_rows: int) -> None:
        self.chunks = chunks
        self.num_rows = num_rows

    def estimated_bytes(self) -> int:
        return sum(c.estimated_bytes() for c in self.chunks)


class ParquetFileWriter:
    """Writes a parquet file to a binary file object.

    The encoder is pluggable (EncoderBackend boundary): anything with an
    ``encode(ColumnChunkData, base_offset) -> EncodedChunk`` method.
    """

    def __init__(self, sink, schema: Schema, properties: WriterProperties | None = None,
                 encoder=None, pipeline: bool = False,
                 retry_policy=None, heartbeat=None) -> None:
        self.sink = sink
        self.schema = schema
        self.properties = properties or WriterProperties()
        self.encoder = encoder or CpuChunkEncoder(self.properties.encoder_options())
        # adaptive encoding decisions are pinned PER FILE (reader
        # coherence): a shared encoder (custom Builder backend across
        # rotated files) must re-decide from this file's first row group
        if hasattr(self.encoder, "begin_file"):
            self.encoder.begin_file()
        # IO-retry classification for the pipelined IO thread (duck-typed
        # runtime.retry.RetryPolicy: is_fatal + next_sleep).  None keeps the
        # historical fixed-100ms retry-every-OSError loop.
        self._retry_policy = retry_policy
        # IO-progress publisher (duck-typed runtime.watchdog.Heartbeat:
        # io_started/io_finished/beat).  The pipelined IO thread stalls
        # *off* the worker thread, so the hung-IO watchdog can only see it
        # through this seam; None (the default) publishes nothing.
        self._heartbeat = heartbeat
        self._pos = 0
        # query-ready-files state (core/index.py): resolved sorting
        # declarations, whether footer fragments must be recomposed at
        # close (index/bloom sections add ColumnChunk fields the
        # commit-time precompute cannot know yet), the section anchor a
        # retried close() overwrites instead of appending twice, and the
        # counters index_info() reports
        self._sorting = self._resolve_sorting(self.properties.sorting_columns)
        self._defer_cc_bytes = (self.properties.write_page_index
                                or self.properties.bloom_columns is not None)
        self._index_section_start: int | None = None
        self._index_counts = {"pages_indexed": 0, "column_indexes": 0,
                              "index_bytes": 0, "bloom_filters": 0,
                              "bloom_bytes": 0}
        self._row_groups: list[RowGroup] = []
        self._pending: list[ColumnChunkData] | None = None
        self._pending_rows = 0
        self._pending_bytes = 0
        self._size_ratio = 1.0  # EWMA of on-disk bytes / raw-estimate bytes
        self._num_rows = 0
        self._closed = False
        # Overlapped pipeline (SURVEY.md §2.4): caller accumulates batch
        # N+3 while the dispatch thread launches row group N+2's encode
        # (device programs + readbacks in the TPU backend), the assembly
        # thread page-assembles/serializes row group N+1 on the host, and
        # the IO thread writes row group N.  Bounded queues (depth 1 each)
        # cap in-flight memory at ~4 row groups and backpressure the
        # producer naturally.  The assembly stage only exists when the
        # encoder supports the launch||assemble split AND a second core is
        # available to overlap onto (auto-inlined into the dispatch thread
        # otherwise — the classic 3-stage shape).
        self._pipeline = pipeline
        self._enc_q: queue.Queue | None = None
        self._asm_q: queue.Queue | None = None
        self._io_q: queue.Queue | None = None
        self._enc_thread: threading.Thread | None = None
        self._asm_thread: threading.Thread | None = None
        self._io_thread: threading.Thread | None = None
        # detached but not yet ENCODED (raw estimate, ratio-scaled by
        # estimated_size); once a stage finishes encoding, the row group
        # moves to _encoded_inflight at its EXACT byte size — the deeper
        # 4-stage pipe holds more in-flight groups, and scaling known
        # sizes by the EWMA ratio would skew size-based rotation
        self._inflight_bytes = 0
        self._encoded_inflight = 0  # encoded but not yet durable (exact)
        self._inflight_lock = threading.Lock()  # += / -= across stage threads
        self._pipe_error: BaseException | None = None
        self._abandoned = threading.Event()
        self._used_assembly_stage = False
        # per-stage busy seconds of the pipeline threads (zeros on the
        # sync path): each key is written by exactly one stage thread and
        # read approximately — the overlap evidence the bench breakdown
        # and the runtime metrics surface without a global tracer
        self.stage_busy_s = {"dispatch": 0.0, "assemble": 0.0, "io": 0.0}
        self._write(MAGIC)

    def _resolve_sorting(self, spec) -> list[SortingColumn]:
        """(name, descending, nulls_first) declarations -> SortingColumn
        entries with leaf ordinals; an unknown column name fails here, at
        construction, not in a published footer."""
        if not spec:
            return []
        cols = self.schema.columns
        out = []
        for name, descending, nulls_first in spec:
            idx = next((i for i, c in enumerate(cols)
                        if c.name == name or ".".join(c.path) == name), None)
            if idx is None:
                raise ValueError(
                    f"sort_order column {name!r} is not a schema leaf "
                    f"(have {[c.name for c in cols]})")
            out.append(SortingColumn(idx, bool(descending),
                                     bool(nulls_first)))
        return out

    def _split_assembly_capable(self) -> bool:
        """True when the encoder can split a row group into launch_many
        (device dispatch) + assemble_many (host page building) halves that
        are safe to run on different threads for different row groups, AND
        its launch actually overlaps real asynchronous work
        (``split_launch_overlaps`` — a no-op launch would only deepen the
        pipe and skew first-file rotation estimates, see pages.py).
        Conservative by construction: an encoder that overrode encode_many
        itself (a custom backend, a test double) keeps its override on the
        single encode stage — the split path would silently bypass it."""
        cls = type(self.encoder)
        return (getattr(cls, "split_launch_overlaps", False)
                and getattr(cls, "encode_many", None)
                is CpuChunkEncoder.encode_many
                and hasattr(cls, "launch_many")
                and hasattr(cls, "assemble_many"))

    @staticmethod
    def _available_cores() -> int:
        """Cores this process may actually use (affinity mask respects
        cgroup/taskset limits; same rule as the Builder's pipeline auto)."""
        try:
            return len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            return os.cpu_count() or 1

    # -- low level ---------------------------------------------------------
    def _write(self, data: bytes) -> None:
        self._write_parts([data])

    def _write_parts(self, parts: list) -> int:
        """Positioned write of one or more buffers without concatenation: on
        retry after a partially-failed earlier write, seek back to the
        logical position so garbage bytes are overwritten and footer/page
        offsets stay true (at-least-once: a transient IO failure must never
        silently drop or shift data).  _pos only advances after every part
        is written.  Returns the bytes written."""
        if hasattr(self.sink, "seek"):
            try:
                self.sink.seek(self._pos)
            except (OSError, io.UnsupportedOperation):
                pass
        # NOTE (measured): do NOT pre-size the sink with a seek-ahead
        # end-marker — BytesIO's growth is already amortized-efficient,
        # and the marker write measured ~1.5x SLOWER than plain appends
        # at the 20 MB row-group shape; the profile cost attributed to
        # sink writes is cache-cold source traffic, not reallocation.
        if len(parts) > 8 and hasattr(self.sink, "writelines"):
            # writev-style gather: the parts list is now per PAGE BUFFER
            # (EncodedChunk.parts), thousands of entries per row group —
            # writelines loops in C, one Python call for the lot.  Raises
            # partway => _pos unmoved, the retry seeks back (same contract
            # as the per-part loop).
            written = sum(map(len, parts))
            self.sink.writelines(parts)
        else:
            written = 0
            for p in parts:
                self.sink.write(p)
                written += len(p)
        self._pos += written
        return written

    # -- public ------------------------------------------------------------
    @property
    def bytes_written(self) -> int:
        return self._pos

    @property
    def has_assembly_stage(self) -> bool:
        """Whether the overlapped host-assembly stage ran on its own
        thread (False until the first pipelined flush, on the sync path,
        and when auto-inlined — split-incapable encoder or single core).
        Sticky across close() so post-run stats stay readable."""
        return self._asm_thread is not None or self._used_assembly_stage

    def pipeline_stats(self) -> dict:
        """Pull-based pipeline observability snapshot: per-stage busy
        seconds plus each stage queue's depth / high-watermark / stall
        accounting (the queue is named for the stage that CONSUMES it:
        ``dispatch`` feeds the encode-dispatch thread, ``assembly`` the
        host-assembly thread when split, ``io`` the IO thread).  Queues
        survive :meth:`close`/:meth:`abandon`, so post-run stats stay
        readable; empty ``queues`` means the sync (non-pipelined) path."""
        out: dict = {
            "split_assembly": self.has_assembly_stage,
            "stage_busy_s": {k: round(v, 6)
                             for k, v in self.stage_busy_s.items()},
            "queues": {},
        }
        for name, q in (("dispatch", self._enc_q),
                        ("assembly", self._asm_q),
                        ("io", self._io_q)):
            if q is not None:
                out["queues"][name] = q.stats()
        return out

    @property
    def size_ratio(self) -> float:
        """Measured on-disk/raw-estimate byte ratio of encoded row groups
        (1.0 until the first row group finishes encoding — the pipelined
        paths fold the exact encoded size in as soon as it is known,
        before the IO commit)."""
        return self._size_ratio

    def estimated_size(self) -> int:
        """In-flight size estimate: bytes on disk + buffered batch estimate
        + row groups queued in the pipeline.  The reference's rotation check
        reads in-flight ParquetWriter getDataSize() (ParquetFile.java:77-79);
        this is the equivalent.  Buffered/in-flight raw bytes are scaled by
        the measured encoded/raw ratio of already-committed row groups so
        size-based rotation tracks what will actually land on disk
        (dictionary/RLE/compression can shrink — or stats can grow — the
        raw columnar estimate substantially).  Row groups already through
        the encode stage count at their exact encoded size."""
        return self._pos + self._encoded_inflight + int(
            self._size_ratio * (self._pending_bytes + self._inflight_bytes))

    def append_batch(self, batch: ColumnBatch) -> None:
        """Pure-memory append: buffers the batch, never touches the sink
        (cannot raise transient IO).  Pair with :meth:`maybe_flush_row_group`
        — the seam the streaming worker retries independently."""
        if self._closed:
            raise ValueError("writer closed")
        if self._pending is None:
            self._pending = [[c] for c in batch.chunks]
        else:
            if len(batch.chunks) != len(self._pending):
                raise ValueError("batch schema mismatch")
            for bucket, chunk in zip(self._pending, batch.chunks):
                bucket.append(chunk)
        self._pending_rows += batch.num_rows
        self._pending_bytes += batch.estimated_bytes()

    def maybe_flush_row_group(self) -> None:
        """Flush iff the pending bytes crossed row_group_size (idempotent,
        retry-safe).  In pipeline mode the flush is handed to the encode/IO
        threads and this returns as soon as the detach is queued."""
        if self._pending_bytes >= self.properties.row_group_size:
            if self._pipeline:
                self._launch_flush()
            else:
                self.flush_row_group()

    # -- pipelined flush ---------------------------------------------------
    def _check_pipe_error(self) -> None:
        """Poisoned-writer check: once a stage failed with detached data the
        error is permanent (never cleared) — retrying cannot recover the
        dropped row group, and acking its offsets would break at-least-once."""
        if self._pipe_error is not None:
            raise PipelineError(
                "row-group pipeline failed; file must be abandoned"
            ) from self._pipe_error

    def _ensure_pipe(self) -> None:
        if self._enc_thread is not None:
            return
        self._enc_q = StatQueue(maxsize=1)
        self._io_q = StatQueue(maxsize=1)
        # the assembly stage earns its thread only when the encoder can
        # split AND there is a second core to overlap onto; otherwise it
        # auto-inlines into the dispatch thread (3-stage shape, identical
        # behavior to the pre-split pipeline)
        if self._split_assembly_capable() and self._available_cores() > 1:
            self._asm_q = StatQueue(maxsize=1)
            self._asm_thread = threading.Thread(
                target=self._assembly_loop, name="kpw-rg-assemble",
                daemon=True)
            self._used_assembly_stage = True
            self._asm_thread.start()
        self._enc_thread = threading.Thread(
            target=self._encode_loop, name="kpw-rg-encode", daemon=True)
        self._io_thread = threading.Thread(
            target=self._io_loop, name="kpw-rg-io", daemon=True)
        self._enc_thread.start()
        self._io_thread.start()

    def _launch_flush(self) -> None:
        """Detach the pending row group and queue it for encode+IO.  Blocks
        (bounded queue) when two row groups are already in flight."""
        self._check_pipe_error()
        if not self._pending or self._pending_rows == 0:
            return
        self._ensure_pipe()
        parts, rows = self._pending, self._pending_rows
        est = self._pending_bytes
        self._pending = None
        self._pending_rows = 0
        self._pending_bytes = 0
        with self._inflight_lock:
            self._inflight_bytes += est
        self._enc_q.put((parts, rows, est))

    def _encode_chunks(self, chunks: list[ColumnChunkData]):
        """Encode merged chunks at base offset 0 (absolute offsets are
        assigned at commit time) — shared by the sync and pipelined paths."""
        with stage("rowgroup.encode",
                   rows=chunks[0].num_rows if chunks else 0):
            if hasattr(self.encoder, "encode_many"):
                return self.encoder.encode_many(chunks, 0)
            encoded, off = [], 0
            for chunk in chunks:
                e = self.encoder.encode(chunk, off)
                off += e.length
                encoded.append(e)
            return encoded

    def _relay_sentinel(self, q: queue.Queue) -> None:
        """Tell the next stage's thread to exit; never blocks forever (the
        downstream thread may already be gone after an abandon)."""
        while True:
            try:
                q.put(None, timeout=0.2)
                return
            except queue.Full:
                if self._abandoned.is_set():
                    return  # downstream drains or exits on its own timeout

    def _next_stage_q(self) -> queue.Queue:
        """The queue the dispatch stage feeds: the assembly stage when it
        exists, else straight to IO."""
        return self._asm_q if self._asm_q is not None else self._io_q

    def _encode_loop(self) -> None:
        """Stage B (dispatch): merge one row group at a time and either
        launch its encode through the split API — so the device leg of row
        group N+1 runs while the assembly thread still owns row group N's
        host leg — or, without an assembly stage, encode it whole.  Either
        way the encode is at base offset 0 (absolute offsets are assigned
        by the IO stage — the native encoder does the same shift for its
        column-parallel path)."""
        while True:
            try:
                item = self._enc_q.get(timeout=0.2)
            except queue.Empty:
                if self._abandoned.is_set():
                    self._relay_sentinel(self._next_stage_q())
                    return
                continue
            if item is None:
                self._relay_sentinel(self._next_stage_q())
                return
            if self._abandoned.is_set() or self._pipe_error is not None:
                continue  # drain without work (abandoned or poisoned)
            parts, rows, est = item
            try:
                t0 = time.perf_counter()
                chunks = [self._merge_chunks(p) for p in parts]
                if self._asm_q is not None:
                    with stage("rowgroup.launch", rows=rows):
                        prepared = self.encoder.launch_many(chunks)
                    self.stage_busy_s["dispatch"] += time.perf_counter() - t0
                    self._asm_q.put((chunks, prepared, rows, est))
                else:
                    encoded = self._encode_chunks(chunks)
                    enc_len = self._mark_encoded(encoded, est)
                    self.stage_busy_s["dispatch"] += time.perf_counter() - t0
                    self._io_q.put((encoded, rows, enc_len))
            except BaseException as e:  # noqa: BLE001 - poisons the writer
                self._pipe_error = e
                with self._inflight_lock:
                    self._inflight_bytes -= est

    def _assembly_loop(self) -> None:
        """Stage B': column-parallel host assembly (page building, blob
        serialization, stats) of one row group at a time, overlapped with
        the NEXT row group's dispatch in stage B.  Owns its own queue and
        the same poison protocol as the other stages: an assembly failure
        after detach is unrecoverable (the rows left the pending buffer),
        so it poisons the writer instead of dying silently."""
        while True:
            try:
                item = self._asm_q.get(timeout=0.2)
            except queue.Empty:
                if self._abandoned.is_set():
                    self._relay_sentinel(self._io_q)
                    return
                continue
            if item is None:
                self._relay_sentinel(self._io_q)
                return
            if self._abandoned.is_set() or self._pipe_error is not None:
                continue  # drain without work (abandoned or poisoned)
            chunks, prepared, rows, est = item
            try:
                t0 = time.perf_counter()
                with stage("rowgroup.assemble", rows=rows):
                    encoded = self.encoder.assemble_many(chunks, prepared, 0)
                enc_len = self._mark_encoded(encoded, est)
                self.stage_busy_s["assemble"] += time.perf_counter() - t0
                self._io_q.put((encoded, rows, enc_len))
            except BaseException as e:  # noqa: BLE001 - poisons the writer
                self._pipe_error = e
                with self._inflight_lock:
                    self._inflight_bytes -= est

    def _io_loop(self) -> None:
        """Stage C: sequential positioned writes + footer bookkeeping.
        Transient IO failures retry forever (reference tryUntilSucceeds,
        KPW.java:410-428) unless the file is abandoned; anything else
        poisons the writer rather than killing this thread silently."""
        while True:
            try:
                item = self._io_q.get(timeout=0.2)
            except queue.Empty:
                if self._abandoned.is_set():
                    return
                continue
            if item is None:
                return
            if self._abandoned.is_set():
                continue
            encoded, rows, enc_len = item
            sleep = None
            attempt = 0
            started = time.monotonic()
            # heartbeat around the whole commit-with-retry: a write that
            # never returns parks this thread here, and the pending
            # "rowgroup.io_write" op is what the hung-IO watchdog ages.  Each
            # retry attempt that RETURNS re-stamps it (beat) — a live
            # backoff loop is the retry policy's business, not a hang.
            hb = self._heartbeat
            hb_token = (hb.io_started("rowgroup.io_write")
                        if hb is not None else None)
            while not self._abandoned.is_set() and self._pipe_error is None:
                try:
                    attempt += 1
                    t0 = time.perf_counter()
                    # raw_estimate=0: _mark_encoded already folded this
                    # row group's exact encoded size into the ratio EWMA
                    # (one stage earlier than a commit-time update — the
                    # deeper pipeline must not delay ratio learning)
                    self._commit_encoded(encoded, rows)
                    self.stage_busy_s["io"] += time.perf_counter() - t0
                    break
                except OSError as e:
                    if hb is not None:
                        hb.beat()
                    pol = self._retry_policy
                    if pol is None:
                        sleep = 0.1  # historical fixed retry-everything
                    elif pol.is_fatal(e):
                        # non-transient errno (ENOSPC/EROFS/...): retrying
                        # in place cannot heal it — poison the writer so
                        # the owning worker dies un-acked and the records
                        # are redelivered instead of spinning forever
                        self._pipe_error = e
                        break
                    else:
                        sleep = pol.next_sleep(sleep)
                        # honor the policy's attempt/deadline budget: a
                        # bounded policy must cap this seam too, not spin
                        if ((pol.max_attempts is not None
                             and attempt >= pol.max_attempts)
                                or (pol.deadline is not None
                                    and time.monotonic() + sleep - started
                                    > pol.deadline)):
                            self._pipe_error = e
                            break
                    if self._abandoned.wait(sleep):
                        break
                except BaseException as e:  # noqa: BLE001 - poison, don't die
                    self._pipe_error = e
            if hb is not None:
                hb.io_finished(hb_token)
            with self._inflight_lock:
                self._encoded_inflight -= enc_len

    def _mark_encoded(self, encoded_chunks, raw_estimate: int) -> int:
        """Account one row group the moment its encode finishes: fold the
        EXACT encoded size into the encoded/raw ratio EWMA (the pipelined
        commit happens a queue hop — or two, with the assembly stage —
        later, and size-based rotation must not keep estimating with a
        stale ratio) and move the group from the ratio-scaled raw-estimate
        pool to the exact encoded-inflight pool.  Returns the encoded
        size, which replaces the raw estimate on the IO queue."""
        actual = sum(e.length for e in encoded_chunks)
        if raw_estimate > 0 and actual > 0:
            self._size_ratio += 0.5 * (actual / raw_estimate
                                       - self._size_ratio)
        with self._inflight_lock:
            self._inflight_bytes -= raw_estimate
            self._encoded_inflight += actual
        return actual

    def _commit_encoded(self, encoded_chunks, num_rows: int,
                        raw_estimate: int = 0) -> None:
        """Write encoded-at-offset-0 chunks at the current position and
        record the row group.  Raises before any state change on IO failure
        (the positioned _write seeks back on retry).  ``raw_estimate`` is the
        pre-encode pending-bytes estimate for this row group; it feeds the
        encoded/raw size-ratio EWMA behind :meth:`estimated_size`."""
        rg_start = self._pos
        parts: list = []
        columns: list[ColumnChunk] = []
        total_byte_size = 0
        total_compressed = 0
        for e in encoded_chunks:
            m = e.meta
            parts.extend(e.parts)
            total_byte_size += m.total_uncompressed_size
            total_compressed += m.total_compressed_size
        with stage("rowgroup.io_write", rowgroup=len(self._row_groups),
                   rows=num_rows):
            # one seek, then a writev-style gather of every chunk's page
            # buffers: the page bytes go from the encoder's parts straight
            # into the sink — no per-chunk blob join, no whole-row-group
            # b"".join bounce (tens of MB at default block size);
            # raises => nothing mutated yet (_pos only advances at the end)
            actual = self._write_parts(parts)
        if raw_estimate > 0 and actual > 0:
            self._size_ratio += 0.5 * (actual / raw_estimate
                                       - self._size_ratio)
        for e in encoded_chunks:
            # metas carry running offsets based at 0 (encode_many's base);
            # shift the whole row group to its absolute file position
            m = e.meta
            if m.dictionary_page_offset is not None:
                m.dictionary_page_offset += rg_start
            m.data_page_offset += rg_start
            columns.append(ColumnChunk(file_offset=m.data_page_offset,
                                       meta_data=m,
                                       page_stats=getattr(e, "pages", None),
                                       bloom=getattr(e, "bloom", None)))
        rg = RowGroup(
            columns=columns,
            total_byte_size=total_byte_size,
            num_rows=num_rows,
            sorting_columns=list(self._sorting) or None,
            file_offset=rg_start,
            total_compressed_size=total_compressed,
            ordinal=len(self._row_groups),
        )
        # offsets are absolute now: serialize the footer fragments here —
        # on the pipelined path this runs in the IO thread, overlapped
        # with later row groups' encode, so close() only splices bytes.
        # With index/bloom sections enabled the fragments gain fields only
        # known at close (section offsets), so serialization defers there.
        if not self._defer_cc_bytes:
            rg.precompute_column_bytes()
        self._row_groups.append(rg)
        self._num_rows += num_rows

    def _drain_pipe(self) -> None:
        """Flush the tail through the pipeline and join every stage thread
        (the sentinel relays stage to stage, in order)."""
        if self._enc_thread is None:
            return
        self._enc_q.put(None)
        self._enc_thread.join()
        if self._asm_thread is not None:
            self._asm_thread.join()
        self._io_thread.join()
        self._enc_thread = self._asm_thread = self._io_thread = None
        self._check_pipe_error()

    def abandon(self) -> None:
        """Stop pipeline threads without finishing the file (the reference
        abandons the open tmp on close — KPW.java:381-398)."""
        self._abandoned.set()
        if self._enc_thread is not None:
            for q, t in ((self._enc_q, self._enc_thread),
                         (self._asm_q, self._asm_thread),
                         (self._io_q, self._io_thread)):
                if t is None:
                    continue
                try:
                    q.put_nowait(None)
                except queue.Full:
                    pass
                t.join(timeout=10)
            self._enc_thread = self._asm_thread = self._io_thread = None
        self._closed = True

    def write_batch(self, batch: ColumnBatch) -> None:
        """Append a batch; flushes a row group when the threshold crosses.

        Ownership contract: the batch is owned by the writer as soon as this
        is called — the append itself cannot fail.  If the internal flush
        raises (transient IO), the data is safely buffered; retry by calling
        :meth:`flush_row_group` (or just :meth:`close`), do NOT re-submit the
        batch."""
        self.append_batch(batch)
        self.maybe_flush_row_group()

    @staticmethod
    def _merge_chunks(parts: list[ColumnChunkData]) -> ColumnChunkData:
        if len(parts) == 1:
            return parts[0]
        first = parts[0]
        if isinstance(first.values, np.ndarray):
            values = np.concatenate([p.values for p in parts])
        elif all(isinstance(p.values, ByteColumn) for p in parts):
            datas = [p.values.payload() for p in parts]
            offsets = [np.zeros(1, np.int64)]
            base = 0
            for p in parts:
                o = p.values.offsets
                offsets.append(o[1:] - o[0] + base)
                base += p.values.payload_bytes()
            values = ByteColumn(b"".join(datas), np.concatenate(offsets))
        else:
            values = [v for p in parts for v in p.values]

        def cat(attr):
            arrs = [getattr(p, attr) for p in parts]
            if arrs[0] is None:
                return None
            return np.concatenate(arrs)

        return ColumnChunkData(
            column=first.column,
            values=values,
            def_levels=cat("def_levels"),
            rep_levels=cat("rep_levels"),
            num_rows=sum(p.num_rows for p in parts),
        )

    def flush_row_group(self) -> None:
        """Transactional: encode everything, then write, and only then mutate
        writer state — so a transient IO failure leaves ``_pending`` intact
        and a retried flush re-encodes and overwrites (no dropped rows, no
        desynced offsets).  Same encode-at-0 + commit path the pipeline
        threads use (one bookkeeping implementation, byte-identical)."""
        if not self._pending or self._pending_rows == 0:
            return
        chunks = [self._merge_chunks(parts) for parts in self._pending]
        num_rows = self._pending_rows
        encoded_chunks = self._encode_chunks(chunks)
        # raises => retry safe (state mutates only after a successful write)
        self._commit_encoded(encoded_chunks, num_rows,
                             raw_estimate=self._pending_bytes)
        self._pending = None
        self._pending_rows = 0
        self._pending_bytes = 0

    def _write_index_sections(self) -> None:
        """Query-ready footer sections (core/index.py), laid out between
        the last row group and the footer: every chunk's bloom filter
        (header + bitset), then all ColumnIndexes, then all OffsetIndexes
        (the PARQUET-922 recommended grouping) — each section's offset and
        length recorded into the footer fields that point at it.  Retry-
        safe like the footer itself: the first call anchors the section
        start, and a retried close() seeks back and overwrites rather than
        appending a second copy."""
        if self._index_section_start is None:
            self._index_section_start = self._pos
        else:
            self._pos = self._index_section_start
        counts = self._index_counts = {
            "pages_indexed": 0, "column_indexes": 0, "index_bytes": 0,
            "bloom_filters": 0, "bloom_bytes": 0}
        with stage("encode.page_index", row_groups=len(self._row_groups)):
            for rg in self._row_groups:
                for cc in rg.columns:
                    if cc.bloom is None:
                        continue
                    blob = cc.bloom.serialize()
                    cc.meta_data.bloom_filter_offset = self._pos
                    cc.meta_data.bloom_filter_length = len(blob)
                    self._write(blob)
                    counts["bloom_filters"] += 1
                    counts["bloom_bytes"] += len(blob)
            for rg in self._row_groups:
                for cc in rg.columns:
                    if not cc.page_stats:
                        continue
                    blob = serialize_column_index(cc.page_stats)
                    cc.column_index_offset = self._pos
                    cc.column_index_length = len(blob)
                    self._write(blob)
                    counts["column_indexes"] += 1
                    counts["index_bytes"] += len(blob)
            for rg in self._row_groups:
                for cc in rg.columns:
                    if not cc.page_stats:
                        continue
                    m = cc.meta_data
                    chunk_start = (m.dictionary_page_offset
                                   if m.dictionary_page_offset is not None
                                   else m.data_page_offset)
                    blob = serialize_offset_index(cc.page_stats, chunk_start)
                    cc.offset_index_offset = self._pos
                    cc.offset_index_length = len(blob)
                    self._write(blob)
                    counts["pages_indexed"] += len(cc.page_stats)
                    counts["index_bytes"] += len(blob)

    def index_info(self) -> dict:
        """Counters of the query-ready sections this file carries (zeros
        until close, and with the features off): pages indexed, column
        indexes, index/bloom bytes, bloom filter count, plus the declared
        sorting columns."""
        return {**self._index_counts,
                "sorting_columns": [(s.column_idx, s.descending,
                                     s.nulls_first) for s in self._sorting]}

    def encoding_info(self) -> dict:
        """Per-column value-encoding decisions of this file's encoder
        (core/select_encoding.py): dotted column path -> the chosen
        encoding, whether dictionary was kept, the trigger reason, and
        the row-group-1 stats that drove it.  Empty for custom backends
        without the chooser, and until the first row group encodes."""
        chooser = getattr(self.encoder, "chooser", None)
        if chooser is None:
            return {}
        return chooser.report()

    def assembly_info(self) -> dict:
        """Nogil-assembly accounting of this file's encoder: column chunks
        and pages whose assembly ran as one GIL-released native call
        (native/src/assemble.cc) instead of the Python page loops.  Zeros
        for backends without the extension (and with the knob off)."""
        e = self.encoder
        return {"native_chunks": getattr(e, "native_asm_chunks", 0),
                "native_pages": getattr(e, "native_asm_pages", 0)}

    def close(self) -> None:
        if self._closed:
            return
        if self._pipeline and self._enc_thread is not None:
            try:
                self._launch_flush()  # tail row group rides the pipe, in order
                self._drain_pipe()
            except Exception:
                # poisoned: stop the threads, then surface.  Deliberately NOT
                # BaseException — a KeyboardInterrupt mid-drain leaves state
                # intact so a retried close() can still finish the file.
                self.abandon()
                raise
        self.flush_row_group()  # no-op unless something is still pending
        if self._defer_cc_bytes and self._row_groups:
            self._write_index_sections()
        kv = list(self.properties.key_value_metadata.items())
        # surface the chooser's per-column choice + trigger stats in the
        # footer (readers see the encoding itself in each ColumnMetaData's
        # encodings list; this records WHY, for audit/debug tooling)
        if self.properties.adaptive_encodings or self.properties.encodings:
            einfo = self.encoding_info()
            if einfo:
                kv.append(("kpw.encoding_decisions",
                           json.dumps(einfo, sort_keys=True)))
        meta = FileMetaData(
            schema_fields=self.schema.flatten(),
            num_rows=self._num_rows,
            row_groups=self._row_groups,
            key_value_metadata=kv,
        )
        footer = meta.serialize()
        # one positioned write so a retried close() can't append twice
        self._write(footer + len(footer).to_bytes(4, "little") + MAGIC)
        self._closed = True


def columns_from_arrays(schema: Schema, arrays: dict[str, object]) -> ColumnBatch:
    """Build a flat-schema ColumnBatch from {column_name: ndarray | list[bytes]}.
    Optional columns may pass a (values, validity_mask) tuple."""
    chunks = []
    num_rows = None
    for col in schema.columns:
        data = arrays[col.name]
        def_levels = None
        if isinstance(data, tuple):
            values, valid = data
            valid = np.asarray(valid, bool)
            def_levels = valid.astype(np.int32) * col.max_def
            if isinstance(values, np.ndarray):
                values = values[valid]
            else:
                values = [v for v, ok in zip(values, valid) if ok]
            n = len(valid)
        else:
            values = data
            n = len(values)
            if col.max_def > 0:
                def_levels = np.full(n, col.max_def, np.int32)
        if isinstance(values, list) and col.leaf.physical_type in (
                PhysicalType.BYTE_ARRAY, PhysicalType.FIXED_LEN_BYTE_ARRAY):
            values = ByteColumn.from_list(values)
        if num_rows is None:
            num_rows = n
        elif num_rows != n:
            raise ValueError("ragged column lengths")
        chunks.append(ColumnChunkData(col, values, def_levels, None, n))
    return ColumnBatch(chunks, num_rows or 0)
