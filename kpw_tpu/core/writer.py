"""Parquet file writer: row-group assembly + footer.

Owns the whole physical file layout ("PAR1" magic, page blobs, thrift footer)
— the role parquet-mr's ``ParquetFileWriter`` plays underneath the reference's
``ParquetFile`` wrapper (ParquetFile.java:36-68).  Batch-oriented: callers
append :class:`ColumnBatch`es; a row group is flushed when its accumulated
size crosses ``row_group_size`` (the reference's ``blockSize``,
KafkaProtoParquetWriter.java:473).
"""

from __future__ import annotations

import io
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .bytecol import ByteColumn
from .metadata import ColumnChunk, FileMetaData, RowGroup
from .pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions
from .schema import PhysicalType, Schema
from ..utils.tracing import stage

MAGIC = b"PAR1"


class PipelineError(RuntimeError):
    """A pipeline stage failed after its row group was detached from the
    pending buffer: the data cannot be recovered by retrying, so the writer
    is poisoned — every subsequent operation re-raises.  Deliberately NOT an
    OSError: the runtime's infinite-IO-retry must not spin on it; the worker
    dies un-acked and the records are redelivered (at-least-once)."""


@dataclass
class WriterProperties:
    """Mirrors the reference's ParquetProperties (ParquetFile.java:105-122):
    blockSize, pageSize, codec, enableDictionary — plus encoder backend."""

    row_group_size: int = 128 * 1024 * 1024
    data_page_size: int = 1024 * 1024
    codec: int = 0
    compression_level: int | None = None
    enable_dictionary: bool = True
    write_statistics: bool = True
    delta_fallback: bool = False
    encoder_threads: int = 0
    page_checksums: bool = False
    key_value_metadata: dict = field(default_factory=dict)

    def encoder_options(self) -> EncoderOptions:
        return EncoderOptions(
            codec=self.codec,
            compression_level=self.compression_level,
            enable_dictionary=self.enable_dictionary,
            data_page_size=self.data_page_size,
            write_statistics=self.write_statistics,
            delta_fallback=self.delta_fallback,
            encoder_threads=self.encoder_threads,
            page_checksums=self.page_checksums,
        )


class ColumnBatch:
    """A batch of rows in columnar form: list of ColumnChunkData, one per
    schema leaf, all covering the same rows."""

    # serialized-payload bytes this batch was shredded from (set by the wire
    # shredder; None for batches built from parsed records/arrays) — lets
    # the worker meter written bytes without re-walking the records
    wire_bytes: int | None = None

    def __init__(self, chunks: list[ColumnChunkData], num_rows: int) -> None:
        self.chunks = chunks
        self.num_rows = num_rows

    def estimated_bytes(self) -> int:
        return sum(c.estimated_bytes() for c in self.chunks)


class ParquetFileWriter:
    """Writes a parquet file to a binary file object.

    The encoder is pluggable (EncoderBackend boundary): anything with an
    ``encode(ColumnChunkData, base_offset) -> EncodedChunk`` method.
    """

    def __init__(self, sink, schema: Schema, properties: WriterProperties | None = None,
                 encoder=None, pipeline: bool = False) -> None:
        self.sink = sink
        self.schema = schema
        self.properties = properties or WriterProperties()
        self.encoder = encoder or CpuChunkEncoder(self.properties.encoder_options())
        self._pos = 0
        self._row_groups: list[RowGroup] = []
        self._pending: list[ColumnChunkData] | None = None
        self._pending_rows = 0
        self._pending_bytes = 0
        self._size_ratio = 1.0  # EWMA of on-disk bytes / raw-estimate bytes
        self._num_rows = 0
        self._closed = False
        # 3-stage pipeline (SURVEY.md §2.4): caller accumulates batch N+2
        # while the encode thread encodes row group N+1 and the IO thread
        # writes row group N.  Bounded queues (depth 1 each) cap in-flight
        # memory at ~3 row groups and backpressure the producer naturally.
        self._pipeline = pipeline
        self._enc_q: queue.Queue | None = None
        self._io_q: queue.Queue | None = None
        self._enc_thread: threading.Thread | None = None
        self._io_thread: threading.Thread | None = None
        self._inflight_bytes = 0  # detached but not yet durable (estimate)
        self._inflight_lock = threading.Lock()  # += / -= from two threads
        self._pipe_error: BaseException | None = None
        self._abandoned = threading.Event()
        self._write(MAGIC)

    # -- low level ---------------------------------------------------------
    def _write(self, data: bytes) -> None:
        self._write_parts([data])

    def _write_parts(self, parts: list) -> int:
        """Positioned write of one or more buffers without concatenation: on
        retry after a partially-failed earlier write, seek back to the
        logical position so garbage bytes are overwritten and footer/page
        offsets stay true (at-least-once: a transient IO failure must never
        silently drop or shift data).  _pos only advances after every part
        is written.  Returns the bytes written."""
        if hasattr(self.sink, "seek"):
            try:
                self.sink.seek(self._pos)
            except (OSError, io.UnsupportedOperation):
                pass
        written = 0
        # NOTE (measured): do NOT pre-size the sink with a seek-ahead
        # end-marker — BytesIO's growth is already amortized-efficient,
        # and the marker write measured ~1.5x SLOWER than plain appends
        # at the 20 MB row-group shape; the profile cost attributed to
        # sink writes is cache-cold source traffic, not reallocation.
        for p in parts:
            self.sink.write(p)
            written += len(p)
        self._pos += written
        return written

    # -- public ------------------------------------------------------------
    @property
    def bytes_written(self) -> int:
        return self._pos

    @property
    def size_ratio(self) -> float:
        """Measured on-disk/raw-estimate byte ratio of committed row groups
        (1.0 until the first commit)."""
        return self._size_ratio

    def estimated_size(self) -> int:
        """In-flight size estimate: bytes on disk + buffered batch estimate
        + row groups queued in the pipeline.  The reference's rotation check
        reads in-flight ParquetWriter getDataSize() (ParquetFile.java:77-79);
        this is the equivalent.  Buffered/in-flight raw bytes are scaled by
        the measured encoded/raw ratio of already-committed row groups so
        size-based rotation tracks what will actually land on disk
        (dictionary/RLE/compression can shrink — or stats can grow — the
        raw columnar estimate substantially)."""
        return self._pos + int(
            self._size_ratio * (self._pending_bytes + self._inflight_bytes))

    def append_batch(self, batch: ColumnBatch) -> None:
        """Pure-memory append: buffers the batch, never touches the sink
        (cannot raise transient IO).  Pair with :meth:`maybe_flush_row_group`
        — the seam the streaming worker retries independently."""
        if self._closed:
            raise ValueError("writer closed")
        if self._pending is None:
            self._pending = [[c] for c in batch.chunks]
        else:
            if len(batch.chunks) != len(self._pending):
                raise ValueError("batch schema mismatch")
            for bucket, chunk in zip(self._pending, batch.chunks):
                bucket.append(chunk)
        self._pending_rows += batch.num_rows
        self._pending_bytes += batch.estimated_bytes()

    def maybe_flush_row_group(self) -> None:
        """Flush iff the pending bytes crossed row_group_size (idempotent,
        retry-safe).  In pipeline mode the flush is handed to the encode/IO
        threads and this returns as soon as the detach is queued."""
        if self._pending_bytes >= self.properties.row_group_size:
            if self._pipeline:
                self._launch_flush()
            else:
                self.flush_row_group()

    # -- pipelined flush ---------------------------------------------------
    def _check_pipe_error(self) -> None:
        """Poisoned-writer check: once a stage failed with detached data the
        error is permanent (never cleared) — retrying cannot recover the
        dropped row group, and acking its offsets would break at-least-once."""
        if self._pipe_error is not None:
            raise PipelineError(
                "row-group pipeline failed; file must be abandoned"
            ) from self._pipe_error

    def _ensure_pipe(self) -> None:
        if self._enc_thread is not None:
            return
        self._enc_q = queue.Queue(maxsize=1)
        self._io_q = queue.Queue(maxsize=1)
        self._enc_thread = threading.Thread(
            target=self._encode_loop, name="kpw-rg-encode", daemon=True)
        self._io_thread = threading.Thread(
            target=self._io_loop, name="kpw-rg-io", daemon=True)
        self._enc_thread.start()
        self._io_thread.start()

    def _launch_flush(self) -> None:
        """Detach the pending row group and queue it for encode+IO.  Blocks
        (bounded queue) when two row groups are already in flight."""
        self._check_pipe_error()
        if not self._pending or self._pending_rows == 0:
            return
        self._ensure_pipe()
        parts, rows = self._pending, self._pending_rows
        est = self._pending_bytes
        self._pending = None
        self._pending_rows = 0
        self._pending_bytes = 0
        with self._inflight_lock:
            self._inflight_bytes += est
        self._enc_q.put((parts, rows, est))

    def _encode_chunks(self, chunks: list[ColumnChunkData]):
        """Encode merged chunks at base offset 0 (absolute offsets are
        assigned at commit time) — shared by the sync and pipelined paths."""
        with stage("rowgroup.encode"):
            if hasattr(self.encoder, "encode_many"):
                return self.encoder.encode_many(chunks, 0)
            encoded, off = [], 0
            for chunk in chunks:
                e = self.encoder.encode(chunk, off)
                off += len(e.blob)
                encoded.append(e)
            return encoded

    def _relay_io_sentinel(self) -> None:
        """Tell the IO thread to exit; never blocks forever (the IO thread
        may already be gone after an abandon)."""
        while True:
            try:
                self._io_q.put(None, timeout=0.2)
                return
            except queue.Full:
                if self._abandoned.is_set():
                    return  # IO thread drains or exits on its own timeout

    def _encode_loop(self) -> None:
        """Stage B: merge + encode one row group at a time, at base offset 0
        (absolute offsets are assigned by the IO stage — the native encoder
        does the same shift for its column-parallel path)."""
        while True:
            try:
                item = self._enc_q.get(timeout=0.2)
            except queue.Empty:
                if self._abandoned.is_set():
                    self._relay_io_sentinel()
                    return
                continue
            if item is None:
                self._relay_io_sentinel()
                return
            if self._abandoned.is_set() or self._pipe_error is not None:
                continue  # drain without work (abandoned or poisoned)
            parts, rows, est = item
            try:
                encoded = self._encode_chunks(
                    [self._merge_chunks(p) for p in parts])
                self._io_q.put((encoded, rows, est))
            except BaseException as e:  # noqa: BLE001 - poisons the writer
                self._pipe_error = e
                with self._inflight_lock:
                    self._inflight_bytes -= est

    def _io_loop(self) -> None:
        """Stage C: sequential positioned writes + footer bookkeeping.
        Transient IO failures retry forever (reference tryUntilSucceeds,
        KPW.java:410-428) unless the file is abandoned; anything else
        poisons the writer rather than killing this thread silently."""
        while True:
            try:
                item = self._io_q.get(timeout=0.2)
            except queue.Empty:
                if self._abandoned.is_set():
                    return
                continue
            if item is None:
                return
            if self._abandoned.is_set():
                continue
            encoded, rows, est = item
            while not self._abandoned.is_set() and self._pipe_error is None:
                try:
                    self._commit_encoded(encoded, rows, raw_estimate=est)
                    break
                except OSError:
                    time.sleep(0.1)
                except BaseException as e:  # noqa: BLE001 - poison, don't die
                    self._pipe_error = e
            with self._inflight_lock:
                self._inflight_bytes -= est

    def _commit_encoded(self, encoded_chunks, num_rows: int,
                        raw_estimate: int = 0) -> None:
        """Write encoded-at-offset-0 chunks at the current position and
        record the row group.  Raises before any state change on IO failure
        (the positioned _write seeks back on retry).  ``raw_estimate`` is the
        pre-encode pending-bytes estimate for this row group; it feeds the
        encoded/raw size-ratio EWMA behind :meth:`estimated_size`."""
        rg_start = self._pos
        blobs = []
        columns: list[ColumnChunk] = []
        total_byte_size = 0
        total_compressed = 0
        for e in encoded_chunks:
            m = e.meta
            blobs.append(e.blob)
            total_byte_size += m.total_uncompressed_size
            total_compressed += m.total_compressed_size
        with stage("rowgroup.io_write"):
            # one seek, then per-chunk writes: no b"".join bounce copy of
            # the whole row group (tens of MB at default block size);
            # raises => nothing mutated yet (_pos only advances at the end)
            actual = self._write_parts(blobs)
        if raw_estimate > 0 and actual > 0:
            self._size_ratio += 0.5 * (actual / raw_estimate
                                       - self._size_ratio)
        for e in encoded_chunks:
            # metas carry running offsets based at 0 (encode_many's base);
            # shift the whole row group to its absolute file position
            m = e.meta
            if m.dictionary_page_offset is not None:
                m.dictionary_page_offset += rg_start
            m.data_page_offset += rg_start
            columns.append(ColumnChunk(file_offset=m.data_page_offset,
                                       meta_data=m))
        self._row_groups.append(RowGroup(
            columns=columns,
            total_byte_size=total_byte_size,
            num_rows=num_rows,
            file_offset=rg_start,
            total_compressed_size=total_compressed,
            ordinal=len(self._row_groups),
        ))
        self._num_rows += num_rows

    def _drain_pipe(self) -> None:
        """Flush the tail through the pipeline and join both threads."""
        if self._enc_thread is None:
            return
        self._enc_q.put(None)
        self._enc_thread.join()
        self._io_thread.join()
        self._enc_thread = self._io_thread = None
        self._check_pipe_error()

    def abandon(self) -> None:
        """Stop pipeline threads without finishing the file (the reference
        abandons the open tmp on close — KPW.java:381-398)."""
        self._abandoned.set()
        if self._enc_thread is not None:
            try:
                self._enc_q.put_nowait(None)
            except queue.Full:
                pass
            self._enc_thread.join(timeout=10)
            if self._io_thread is not None:
                try:
                    self._io_q.put_nowait(None)
                except queue.Full:
                    pass
                self._io_thread.join(timeout=10)
            self._enc_thread = self._io_thread = None
        self._closed = True

    def write_batch(self, batch: ColumnBatch) -> None:
        """Append a batch; flushes a row group when the threshold crosses.

        Ownership contract: the batch is owned by the writer as soon as this
        is called — the append itself cannot fail.  If the internal flush
        raises (transient IO), the data is safely buffered; retry by calling
        :meth:`flush_row_group` (or just :meth:`close`), do NOT re-submit the
        batch."""
        self.append_batch(batch)
        self.maybe_flush_row_group()

    @staticmethod
    def _merge_chunks(parts: list[ColumnChunkData]) -> ColumnChunkData:
        if len(parts) == 1:
            return parts[0]
        first = parts[0]
        if isinstance(first.values, np.ndarray):
            values = np.concatenate([p.values for p in parts])
        elif all(isinstance(p.values, ByteColumn) for p in parts):
            datas = [p.values.payload() for p in parts]
            offsets = [np.zeros(1, np.int64)]
            base = 0
            for p in parts:
                o = p.values.offsets
                offsets.append(o[1:] - o[0] + base)
                base += p.values.payload_bytes()
            values = ByteColumn(b"".join(datas), np.concatenate(offsets))
        else:
            values = [v for p in parts for v in p.values]

        def cat(attr):
            arrs = [getattr(p, attr) for p in parts]
            if arrs[0] is None:
                return None
            return np.concatenate(arrs)

        return ColumnChunkData(
            column=first.column,
            values=values,
            def_levels=cat("def_levels"),
            rep_levels=cat("rep_levels"),
            num_rows=sum(p.num_rows for p in parts),
        )

    def flush_row_group(self) -> None:
        """Transactional: encode everything, then write, and only then mutate
        writer state — so a transient IO failure leaves ``_pending`` intact
        and a retried flush re-encodes and overwrites (no dropped rows, no
        desynced offsets).  Same encode-at-0 + commit path the pipeline
        threads use (one bookkeeping implementation, byte-identical)."""
        if not self._pending or self._pending_rows == 0:
            return
        chunks = [self._merge_chunks(parts) for parts in self._pending]
        num_rows = self._pending_rows
        encoded_chunks = self._encode_chunks(chunks)
        # raises => retry safe (state mutates only after a successful write)
        self._commit_encoded(encoded_chunks, num_rows,
                             raw_estimate=self._pending_bytes)
        self._pending = None
        self._pending_rows = 0
        self._pending_bytes = 0

    def close(self) -> None:
        if self._closed:
            return
        if self._pipeline and self._enc_thread is not None:
            try:
                self._launch_flush()  # tail row group rides the pipe, in order
                self._drain_pipe()
            except Exception:
                # poisoned: stop the threads, then surface.  Deliberately NOT
                # BaseException — a KeyboardInterrupt mid-drain leaves state
                # intact so a retried close() can still finish the file.
                self.abandon()
                raise
        self.flush_row_group()  # no-op unless something is still pending
        meta = FileMetaData(
            schema_fields=self.schema.flatten(),
            num_rows=self._num_rows,
            row_groups=self._row_groups,
            key_value_metadata=list(self.properties.key_value_metadata.items()),
        )
        footer = meta.serialize()
        # one positioned write so a retried close() can't append twice
        self._write(footer + len(footer).to_bytes(4, "little") + MAGIC)
        self._closed = True


def columns_from_arrays(schema: Schema, arrays: dict[str, object]) -> ColumnBatch:
    """Build a flat-schema ColumnBatch from {column_name: ndarray | list[bytes]}.
    Optional columns may pass a (values, validity_mask) tuple."""
    chunks = []
    num_rows = None
    for col in schema.columns:
        data = arrays[col.name]
        def_levels = None
        if isinstance(data, tuple):
            values, valid = data
            valid = np.asarray(valid, bool)
            def_levels = valid.astype(np.int32) * col.max_def
            if isinstance(values, np.ndarray):
                values = values[valid]
            else:
                values = [v for v, ok in zip(values, valid) if ok]
            n = len(valid)
        else:
            values = data
            n = len(values)
            if col.max_def > 0:
                def_levels = np.full(n, col.max_def, np.int32)
        if isinstance(values, list) and col.leaf.physical_type in (
                PhysicalType.BYTE_ARRAY, PhysicalType.FIXED_LEN_BYTE_ARRAY):
            values = ByteColumn.from_list(values)
        if num_rows is None:
            num_rows = n
        elif num_rows != n:
            raise ValueError("ragged column lengths")
        chunks.append(ColumnChunkData(col, values, def_levels, None, n))
    return ColumnBatch(chunks, num_rows or 0)
