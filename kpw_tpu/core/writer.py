"""Parquet file writer: row-group assembly + footer.

Owns the whole physical file layout ("PAR1" magic, page blobs, thrift footer)
— the role parquet-mr's ``ParquetFileWriter`` plays underneath the reference's
``ParquetFile`` wrapper (ParquetFile.java:36-68).  Batch-oriented: callers
append :class:`ColumnBatch`es; a row group is flushed when its accumulated
size crosses ``row_group_size`` (the reference's ``blockSize``,
KafkaProtoParquetWriter.java:473).
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field

import numpy as np

from .bytecol import ByteColumn
from .metadata import ColumnChunk, FileMetaData, RowGroup
from .pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions
from .schema import PhysicalType, Schema
from ..utils.tracing import stage

MAGIC = b"PAR1"


@dataclass
class WriterProperties:
    """Mirrors the reference's ParquetProperties (ParquetFile.java:105-122):
    blockSize, pageSize, codec, enableDictionary — plus encoder backend."""

    row_group_size: int = 128 * 1024 * 1024
    data_page_size: int = 1024 * 1024
    codec: int = 0
    compression_level: int | None = None
    enable_dictionary: bool = True
    write_statistics: bool = True
    delta_fallback: bool = False
    encoder_threads: int = 0
    key_value_metadata: dict = field(default_factory=dict)

    def encoder_options(self) -> EncoderOptions:
        return EncoderOptions(
            codec=self.codec,
            compression_level=self.compression_level,
            enable_dictionary=self.enable_dictionary,
            data_page_size=self.data_page_size,
            write_statistics=self.write_statistics,
            delta_fallback=self.delta_fallback,
            encoder_threads=self.encoder_threads,
        )


class ColumnBatch:
    """A batch of rows in columnar form: list of ColumnChunkData, one per
    schema leaf, all covering the same rows."""

    def __init__(self, chunks: list[ColumnChunkData], num_rows: int) -> None:
        self.chunks = chunks
        self.num_rows = num_rows

    def estimated_bytes(self) -> int:
        return sum(c.estimated_bytes() for c in self.chunks)


class ParquetFileWriter:
    """Writes a parquet file to a binary file object.

    The encoder is pluggable (EncoderBackend boundary): anything with an
    ``encode(ColumnChunkData, base_offset) -> EncodedChunk`` method.
    """

    def __init__(self, sink, schema: Schema, properties: WriterProperties | None = None,
                 encoder=None) -> None:
        self.sink = sink
        self.schema = schema
        self.properties = properties or WriterProperties()
        self.encoder = encoder or CpuChunkEncoder(self.properties.encoder_options())
        self._pos = 0
        self._row_groups: list[RowGroup] = []
        self._pending: list[ColumnChunkData] | None = None
        self._pending_rows = 0
        self._pending_bytes = 0
        self._num_rows = 0
        self._closed = False
        self._write(MAGIC)

    # -- low level ---------------------------------------------------------
    def _write(self, data: bytes) -> None:
        """Positioned write: on retry after a partially-failed earlier write,
        seek back to the logical position so garbage bytes are overwritten and
        footer/page offsets stay true (at-least-once: a transient IO failure
        must never silently drop or shift data)."""
        if self._pos and hasattr(self.sink, "seek"):
            try:
                self.sink.seek(self._pos)
            except (OSError, io.UnsupportedOperation):
                pass
        self.sink.write(data)
        self._pos += len(data)

    # -- public ------------------------------------------------------------
    @property
    def bytes_written(self) -> int:
        return self._pos

    def estimated_size(self) -> int:
        """In-flight size estimate: bytes on disk + buffered batch estimate.
        The reference's rotation check reads in-flight ParquetWriter
        getDataSize() (ParquetFile.java:77-79); this is the equivalent."""
        return self._pos + self._pending_bytes

    def append_batch(self, batch: ColumnBatch) -> None:
        """Pure-memory append: buffers the batch, never touches the sink
        (cannot raise transient IO).  Pair with :meth:`maybe_flush_row_group`
        — the seam the streaming worker retries independently."""
        if self._closed:
            raise ValueError("writer closed")
        if self._pending is None:
            self._pending = [[c] for c in batch.chunks]
        else:
            if len(batch.chunks) != len(self._pending):
                raise ValueError("batch schema mismatch")
            for bucket, chunk in zip(self._pending, batch.chunks):
                bucket.append(chunk)
        self._pending_rows += batch.num_rows
        self._pending_bytes += batch.estimated_bytes()

    def maybe_flush_row_group(self) -> None:
        """Flush iff the pending bytes crossed row_group_size (idempotent,
        retry-safe)."""
        if self._pending_bytes >= self.properties.row_group_size:
            self.flush_row_group()

    def write_batch(self, batch: ColumnBatch) -> None:
        """Append a batch; flushes a row group when the threshold crosses.

        Ownership contract: the batch is owned by the writer as soon as this
        is called — the append itself cannot fail.  If the internal flush
        raises (transient IO), the data is safely buffered; retry by calling
        :meth:`flush_row_group` (or just :meth:`close`), do NOT re-submit the
        batch."""
        self.append_batch(batch)
        self.maybe_flush_row_group()

    @staticmethod
    def _merge_chunks(parts: list[ColumnChunkData]) -> ColumnChunkData:
        if len(parts) == 1:
            return parts[0]
        first = parts[0]
        if isinstance(first.values, np.ndarray):
            values = np.concatenate([p.values for p in parts])
        elif all(isinstance(p.values, ByteColumn) for p in parts):
            datas = [p.values.payload() for p in parts]
            offsets = [np.zeros(1, np.int64)]
            base = 0
            for p in parts:
                o = p.values.offsets
                offsets.append(o[1:] - o[0] + base)
                base += p.values.payload_bytes()
            values = ByteColumn(b"".join(datas), np.concatenate(offsets))
        else:
            values = [v for p in parts for v in p.values]

        def cat(attr):
            arrs = [getattr(p, attr) for p in parts]
            if arrs[0] is None:
                return None
            return np.concatenate(arrs)

        return ColumnChunkData(
            column=first.column,
            values=values,
            def_levels=cat("def_levels"),
            rep_levels=cat("rep_levels"),
            num_rows=sum(p.num_rows for p in parts),
        )

    def flush_row_group(self) -> None:
        """Transactional: encode everything, then write, and only then mutate
        writer state — so a transient IO failure leaves ``_pending`` intact
        and a retried flush re-encodes and overwrites (no dropped rows, no
        desynced offsets)."""
        if not self._pending or self._pending_rows == 0:
            return
        chunks = [self._merge_chunks(parts) for parts in self._pending]
        num_rows = self._pending_rows

        rg_start = self._pos
        columns: list[ColumnChunk] = []
        blobs: list[bytes] = []
        total_byte_size = 0
        total_compressed = 0
        with stage("rowgroup.encode"):
            if hasattr(self.encoder, "encode_many"):
                encoded_chunks = self.encoder.encode_many(chunks, rg_start)
            else:
                encoded_chunks, offset = [], rg_start
                for chunk in chunks:
                    e = self.encoder.encode(chunk, offset)
                    offset += len(e.blob)
                    encoded_chunks.append(e)
        for encoded in encoded_chunks:
            blobs.append(encoded.blob)
            columns.append(ColumnChunk(
                file_offset=encoded.meta.data_page_offset,
                meta_data=encoded.meta,
            ))
            total_byte_size += encoded.meta.total_uncompressed_size
            total_compressed += encoded.meta.total_compressed_size
        with stage("rowgroup.io_write"):
            self._write(b"".join(blobs))  # raises => state untouched, retry safe
        self._pending = None
        self._pending_rows = 0
        self._pending_bytes = 0
        self._row_groups.append(RowGroup(
            columns=columns,
            total_byte_size=total_byte_size,
            num_rows=num_rows,
            file_offset=rg_start,
            total_compressed_size=total_compressed,
            ordinal=len(self._row_groups),
        ))
        self._num_rows += num_rows

    def close(self) -> None:
        if self._closed:
            return
        self.flush_row_group()
        meta = FileMetaData(
            schema_fields=self.schema.flatten(),
            num_rows=self._num_rows,
            row_groups=self._row_groups,
            key_value_metadata=list(self.properties.key_value_metadata.items()),
        )
        footer = meta.serialize()
        # one positioned write so a retried close() can't append twice
        self._write(footer + len(footer).to_bytes(4, "little") + MAGIC)
        self._closed = True


def columns_from_arrays(schema: Schema, arrays: dict[str, object]) -> ColumnBatch:
    """Build a flat-schema ColumnBatch from {column_name: ndarray | list[bytes]}.
    Optional columns may pass a (values, validity_mask) tuple."""
    chunks = []
    num_rows = None
    for col in schema.columns:
        data = arrays[col.name]
        def_levels = None
        if isinstance(data, tuple):
            values, valid = data
            valid = np.asarray(valid, bool)
            def_levels = valid.astype(np.int32) * col.max_def
            if isinstance(values, np.ndarray):
                values = values[valid]
            else:
                values = [v for v, ok in zip(values, valid) if ok]
            n = len(valid)
        else:
            values = data
            n = len(values)
            if col.max_def > 0:
                def_levels = np.full(n, col.max_def, np.int32)
        if isinstance(values, list) and col.leaf.physical_type in (
                PhysicalType.BYTE_ARRAY, PhysicalType.FIXED_LEN_BYTE_ARRAY):
            values = ByteColumn.from_list(values)
        if num_rows is None:
            num_rows = n
        elif num_rows != n:
            raise ValueError("ragged column lengths")
        chunks.append(ColumnChunkData(col, values, def_levels, None, n))
    return ColumnBatch(chunks, num_rows or 0)
