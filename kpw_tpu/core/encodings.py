"""CPU reference encoders for parquet pages (numpy-vectorized).

This is build-plan step 1 (SURVEY.md §7): the encodings parquet-mr applies
under the reference's single ``writer.write(record)`` funnel
(ParquetFile.java:59-62) — PLAIN, RLE/bit-pack hybrid, dictionary,
DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY — reimplemented from the format
spec.  These are both the default CPU backend and the correctness oracle for
the TPU kernels in ``kpw_tpu.ops``.
"""

from __future__ import annotations

import struct

import numpy as np

from .schema import PhysicalType
from .thrift import varint_bytes, zigzag


def bit_width(max_value: int) -> int:
    return int(max_value).bit_length()


# ---------------------------------------------------------------------------
# bit-packing (parquet RLE/bit-pack hybrid ordering: value bit j lands at
# overall bit position i*width + j; bytes are LSB-first)
# ---------------------------------------------------------------------------

def bitpack(values: np.ndarray, width: int) -> bytes:
    """Pack ``values`` (< 2**width) into parquet LSB-first bit layout."""
    if width == 0 or len(values) == 0:
        return b""
    v = np.ascontiguousarray(values, dtype=np.uint64)
    bits = ((v[:, None] >> np.arange(width, dtype=np.uint64)) & 1).astype(np.uint8)
    flat = bits.reshape(-1)
    pad = (-flat.size) % 8
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.uint8)])
    weights = (1 << np.arange(8, dtype=np.uint16)).astype(np.uint16)
    out = (flat.reshape(-1, 8) * weights).sum(axis=1).astype(np.uint8)
    return out.tobytes()


def bitunpack(data: bytes, width: int, count: int) -> np.ndarray:
    """Inverse of :func:`bitpack` (tests / readback)."""
    if width == 0:
        return np.zeros(count, np.uint64)
    raw = np.frombuffer(data, np.uint8)
    bits = ((raw[:, None] >> np.arange(8, dtype=np.uint8)) & 1).reshape(-1)
    need = count * width
    bits = bits[:need].reshape(count, width).astype(np.uint64)
    return (bits << np.arange(width, dtype=np.uint64)).sum(axis=1)


# ---------------------------------------------------------------------------
# RLE / bit-pack hybrid
# ---------------------------------------------------------------------------

def _runs(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Return (run_values, run_lengths)."""
    n = len(values)
    if n == 0:
        return values, np.zeros(0, np.int64)
    change = np.nonzero(np.diff(values))[0] + 1
    starts = np.concatenate([[0], change])
    lengths = np.diff(np.concatenate([starts, [n]]))
    return values[starts], lengths


def _rle_run(value: int, count: int, width: int) -> bytes:
    nbytes = (width + 7) // 8
    return varint_bytes(count << 1) + int(value).to_bytes(nbytes, "little")


def _bitpack_run(values: np.ndarray, width: int) -> bytes:
    """values are padded here to a multiple of 8; count = #groups."""
    pad = (-len(values)) % 8
    if pad:
        values = np.concatenate([values, np.zeros(pad, values.dtype)])
    groups = len(values) // 8
    return varint_bytes((groups << 1) | 1) + bitpack(values, width)


def rle_hybrid_encode(values: np.ndarray, width: int) -> bytes:
    """Parquet RLE/bit-pack hybrid: long runs -> RLE, the rest -> 8-value
    bit-packed groups (mid-stream bit-pack runs must cover exact multiples of
    8 values; only the final group may be padded)."""
    n = len(values)
    if n == 0:
        return b""
    if width == 0:
        # all values are zero-width (single possible value): one RLE run
        return varint_bytes(n << 1)
    values = np.ascontiguousarray(values, dtype=np.uint64)
    run_vals, run_lens = _runs(values)
    # Fast path: few long runs => pure bit-packing (valid hybrid stream).
    long_mask = run_lens >= 8
    if not long_mask.any() or run_lens[long_mask].sum() < max(8, n // 10):
        return _bitpack_run(values, width)
    return rle_hybrid_from_runs(run_vals, run_lens, width)


def rle_hybrid_from_runs(run_vals: np.ndarray, run_lens: np.ndarray,
                         width: int) -> bytes:
    """The mixed RLE/bit-pack assembly of :func:`rle_hybrid_encode`, driven
    from precomputed runs — O(runs) host work, so a device run-scan (TPU
    level encoding, ops.levels) can hand off only the compact run list.
    Byte-identical to the slow path of ``rle_hybrid_encode`` by construction
    (that function delegates here)."""
    out = bytearray()
    buf: list[np.ndarray] = []
    buf_len = 0

    def flush_buf() -> None:
        nonlocal buf, buf_len
        if buf_len:
            out.extend(_bitpack_run(np.concatenate(buf), width))
            buf = []
            buf_len = 0

    for rv, rl in zip(run_vals.tolist(), run_lens.tolist()):
        if buf_len % 8:
            take = min((-buf_len) % 8, rl)
            buf.append(np.full(take, rv, np.uint64))
            buf_len += take
            rl -= take
        if rl >= 8:
            flush_buf()
            out.extend(_rle_run(rv, rl, width))
            rl = 0
        if rl:
            buf.append(np.full(rl, rv, np.uint64))
            buf_len += rl
    flush_buf()
    return bytes(out)


def rle_hybrid_decode(data: bytes, width: int, count: int) -> np.ndarray:
    """Decoder for tests."""
    out = np.zeros(count, np.uint64)
    pos = 0
    idx = 0
    nbytes = (width + 7) // 8
    while idx < count:
        header = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:  # bit-packed run
            groups = header >> 1
            nvals = groups * 8
            nb = (nvals * width + 7) // 8
            vals = bitunpack(data[pos : pos + nb], width, nvals)
            pos += nb
            take = min(nvals, count - idx)
            out[idx : idx + take] = vals[:take]
            idx += take
        else:  # RLE run
            run_len = header >> 1
            value = int.from_bytes(data[pos : pos + nbytes], "little")
            pos += nbytes
            take = min(run_len, count - idx)
            out[idx : idx + take] = value
            idx += take
    return out


def rle_levels_v1(levels: np.ndarray, max_level: int) -> bytes:
    """Definition/repetition levels for data page v1: RLE-hybrid stream with a
    4-byte little-endian length prefix."""
    body = rle_hybrid_encode(levels, bit_width(max_level))
    return struct.pack("<I", len(body)) + body


# ---------------------------------------------------------------------------
# PLAIN encoding per physical type
# ---------------------------------------------------------------------------

_PLAIN_DTYPES = {
    PhysicalType.INT32: np.dtype("<i4"),
    PhysicalType.INT64: np.dtype("<i8"),
    PhysicalType.FLOAT: np.dtype("<f4"),
    PhysicalType.DOUBLE: np.dtype("<f8"),
}


def plain_encode(values, physical_type: int) -> bytes:
    """PLAIN-encode values.  ``values`` is an ndarray for fixed-width types,
    or a list/array of ``bytes`` for BYTE_ARRAY."""
    if physical_type == PhysicalType.BOOLEAN:
        return bitpack(np.asarray(values, np.uint8), 1)
    if physical_type == PhysicalType.BYTE_ARRAY:
        return byte_array_plain_encode(values)
    if physical_type == PhysicalType.FIXED_LEN_BYTE_ARRAY:
        return b"".join(values)
    dtype = _PLAIN_DTYPES[physical_type]
    return np.ascontiguousarray(values, dtype=dtype).tobytes()


def byte_array_plain_encode(values) -> bytes:
    """BYTE_ARRAY PLAIN: 4-byte LE length + raw bytes per value."""
    if len(values) == 0:
        return b""
    return b"".join(struct.pack("<I", len(v)) + v for v in values)


# ---------------------------------------------------------------------------
# Dictionary encoding
# ---------------------------------------------------------------------------

def dictionary_build(values, physical_type: int):
    """Return (dictionary_values, indices:np.uint32).

    Canonical dictionary order = ascending *bit pattern* (floats viewed as
    unsigned ints, byte strings lexicographic).  parquet readers don't care
    about dictionary order; ascending order is the cheapest deterministic
    choice for the TPU sort-based builder (kpw_tpu.ops.dictionary), matches
    the mesh-global merged dictionaries (kpw_tpu.parallel.dict_merge), and
    this CPU oracle produces the identical bytes."""
    if physical_type == PhysicalType.BYTE_ARRAY or physical_type == PhysicalType.FIXED_LEN_BYTE_ARRAY:
        if not isinstance(values, list):
            values = list(values)  # ByteColumn etc.: the oracle works on lists
        # Vectorized path: numpy 'S' arrays sort bytes lexicographically, same
        # order as python bytes.  'S' storage strips trailing NULs and is
        # fixed-width (n x max_len), so gate on both: trailing-NUL data and
        # length-skewed data (one huge value would blow the allocation up to
        # n*max_len) take the exact hash-map path.
        if (
            len(values)
            and len(values) * max(map(len, values)) <= 1 << 28  # 256 MiB cap
            and not any(v[-1:] == b"\x00" for v in values)
        ):
            arr = np.array(values, dtype="S")
            uniq, inv = np.unique(arr, return_inverse=True)
            return [bytes(u) for u in uniq], inv.astype(np.uint32)
        table = sorted(set(values))
        slots = {v: i for i, v in enumerate(table)}
        idx = np.fromiter((slots[v] for v in values), np.uint32, count=len(values))
        return table, idx
    arr = np.asarray(values)
    # unsigned bit-pattern keys for 4/8-byte types so the order matches the
    # device sort exactly (which compares uint32 key halves); narrow types
    # (never device-eligible) sort by value
    if arr.dtype.itemsize in (4, 8):
        key = arr.view(np.uint32 if arr.dtype.itemsize == 4 else np.uint64)
        uniq_keys, inv = np.unique(key, return_inverse=True)
        return uniq_keys.view(arr.dtype), inv.astype(np.uint32)
    uniq, inv = np.unique(arr, return_inverse=True)
    return uniq, inv.astype(np.uint32)


def dictionary_indices_encode(indices: np.ndarray, dict_size: int) -> bytes:
    """Data-page body for PLAIN_DICTIONARY/RLE_DICTIONARY: 1-byte bit width
    followed by the RLE-hybrid stream of indices."""
    width = bit_width(max(dict_size - 1, 0))
    return bytes([width]) + rle_hybrid_encode(indices, width)


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED (ints) — parquet delta encoding
# ---------------------------------------------------------------------------

_DELTA_BLOCK = 128
_DELTA_MINIBLOCKS = 4
_DELTA_MB_SIZE = _DELTA_BLOCK // _DELTA_MINIBLOCKS  # 32


def delta_binary_packed_encode(values: np.ndarray, bit_size: int = 64) -> bytes:
    """DELTA_BINARY_PACKED per the spec: header (block size, miniblock count,
    total count, zigzag first value) then per-block min-delta + per-miniblock
    bit widths + packed deltas.  ``bit_size`` selects the ring arithmetic:
    INT32 columns use 32-bit wraparound deltas (so widths never exceed 32),
    INT64 uses 64-bit — matching what readers decode into."""
    itype = np.int64 if bit_size == 64 else np.int32
    utype = np.uint64 if bit_size == 64 else np.uint32
    v = np.asarray(values, itype)
    n = len(v)
    out = bytearray()
    out += varint_bytes(_DELTA_BLOCK)
    out += varint_bytes(_DELTA_MINIBLOCKS)
    out += varint_bytes(n)
    if n == 0:
        out += varint_bytes(0)
        return bytes(out)
    out += varint_bytes(zigzag(int(v[0])))
    if n == 1:
        return bytes(out)
    # Ring arithmetic: readers decode the zigzag min_delta into a wrapping
    # 32/64-bit int, so we must produce the same wraparound (numpy signed
    # subtraction wraps).
    with np.errstate(over="ignore"):
        deltas = v[1:] - v[:-1]
    pos = 0
    while pos < len(deltas):
        block = deltas[pos : pos + _DELTA_BLOCK]
        pos += _DELTA_BLOCK
        min_delta = int(block.min())
        out += varint_bytes(zigzag(min_delta))
        with np.errstate(over="ignore"):
            rel = (block - itype(min_delta)).view(utype)
        widths = []
        packed_parts = []
        for mb in range(_DELTA_MINIBLOCKS):
            seg = rel[mb * _DELTA_MB_SIZE : (mb + 1) * _DELTA_MB_SIZE]
            if len(seg) == 0:
                widths.append(0)
                packed_parts.append(b"")
                continue
            w = bit_width(int(seg.max()))
            widths.append(w)
            if w:
                full = np.zeros(_DELTA_MB_SIZE, np.uint64)
                full[: len(seg)] = seg
                packed_parts.append(bitpack(full, w))
            else:
                packed_parts.append(b"")
        out += bytes(widths)
        for p in packed_parts:
            out += p
    return bytes(out)


def delta_length_byte_array_encode(values) -> bytes:
    """DELTA_LENGTH_BYTE_ARRAY: delta-packed int32 lengths (per spec) then
    concatenated bytes."""
    lens = np.fromiter((len(v) for v in values), np.int64, count=len(values))
    return delta_binary_packed_encode(lens, bit_size=32) + b"".join(values)


# ---------------------------------------------------------------------------
# BYTE_STREAM_SPLIT (fixed-width values) — byte-plane transpose
# ---------------------------------------------------------------------------

def byte_stream_split_encode(values, physical_type: int) -> bytes:
    """BYTE_STREAM_SPLIT per the spec: the K byte planes of N K-byte values,
    concatenated — plane j holds byte j of every value in order.  Same byte
    COUNT as PLAIN; the win is that grouping same-significance bytes makes
    the stream compress far better (float mantissa noise stays contained in
    its own planes).  Defined for FLOAT/DOUBLE since format 2.8 and for
    INT32/INT64/FIXED_LEN_BYTE_ARRAY since 2.11."""
    dtype = _PLAIN_DTYPES.get(physical_type)
    if dtype is None:
        raise ValueError(
            f"BYTE_STREAM_SPLIT needs a fixed-width type, got {physical_type}")
    v = np.ascontiguousarray(values, dtype=dtype)
    n = len(v)
    if n == 0:
        return b""
    return v.view(np.uint8).reshape(n, dtype.itemsize).T.tobytes()


def byte_stream_split_decode(data: bytes, physical_type: int) -> np.ndarray:
    """Inverse of :func:`byte_stream_split_encode` (tests / readback)."""
    dtype = _PLAIN_DTYPES[physical_type]
    k = dtype.itemsize
    if len(data) % k:
        raise ValueError("BYTE_STREAM_SPLIT payload not a multiple of width")
    n = len(data) // k
    if n == 0:
        return np.zeros(0, dtype)
    planes = np.frombuffer(data, np.uint8).reshape(k, n)
    return np.ascontiguousarray(planes.T).reshape(-1).view(dtype).copy()
