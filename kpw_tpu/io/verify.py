"""Independent structural verifier for parquet files — no pyarrow, no
shared write-path code beyond the thrift decoder.

The write side (core/writer.py, core/pages.py) emits page CRCs and a
thrift footer, but until this module nothing in the repo could *check* a
published file: a torn final (kill -9 between a page-cache write and the
fsync that never happened) or a bit-flipped page body was invisible until
some downstream reader choked.  This verifier walks the physical layout
from the bytes alone:

* ``PAR1`` magic at both ends,
* footer-length sanity (the 4-byte little-endian length must frame a
  region inside the file),
* thrift-compact footer parse (bounds-checked ``core.thrift.CompactReader``
  — corruption raises ``ThriftDecodeError``, never an IndexError),
* row-group / column-chunk offsets and sizes in-bounds and non-overlapping
  with the footer,
* a full page-header walk of every column chunk (header parse, body
  in-bounds, page-type sanity, per-chunk byte accounting),
* CRC-32 (gzip polynomial, PARQUET-1539) check of every page body that
  carries the optional crc field — the write side's
  ``Builder.page_checksums(True)`` checksums verified on read,
* row/value-count consistency (row-group rows sum to the footer's
  ``num_rows``; each chunk's data-page values sum to its meta's
  ``num_values``),
* the query-ready footer sections (PARQUET-922 page indexes, split-block
  bloom filters, ``sorting_columns`` — the write side is
  ``core/index.py``): index offsets/lengths in-bounds and thrift-parsable,
  OffsetIndex page locations matching the walked pages one for one,
  ColumnIndex list lengths consistent with the page count and its
  declared boundary order consistent with the page min/max stats, bloom
  headers sane with in-bounds bitsets, and every declared sorting column
  consistent with its column index's ordering (a file CLAIMING sortedness
  its pages contradict fails verification — sort-on-compact publishes
  through this check).

It deliberately does NOT decode values: the contract is "structurally
valid parquet whose every byte is where the footer says it is", which is
what the recovery pass (runtime/writer.py ``recover``) needs to decide
publish-vs-quarantine, and what the crash harness (tests/test_crash.py,
``bench.py --crash``) asserts for every acked offset's file.

CLI: ``python -m kpw_tpu.io.verify <file-or-dir> [...]`` — exit 0 iff
every file verifies; ``--json`` dumps the reports as one JSON array;
``--summary`` replaces the per-file report with ONE JSON rollup
(files/rows/row groups/pages/failing paths) so a compaction run can
assert directory-level integrity in a single call.
"""

from __future__ import annotations

import json
import os
import struct
import sys
import zlib
from dataclasses import dataclass, field

from ..core.schema import Codec, PageType
from ..core.thrift import CompactReader, ThriftDecodeError
from .fs import FileSystem, LocalFileSystem

MAGIC = b"PAR1"
# trailing frame: 4-byte little-endian footer length + magic
_TAIL = 8
# FileMetaData field ids (parquet.thrift; mirrors core/metadata.py's writer)
_FMD_VERSION, _FMD_SCHEMA, _FMD_NUM_ROWS, _FMD_ROW_GROUPS = 1, 2, 3, 4
# SchemaElement
_SE_TYPE, _SE_NUM_CHILDREN = 1, 5
_SE_REPETITION, _SE_NAME, _SE_CONVERTED = 3, 4, 6
# RowGroup
_RG_COLUMNS, _RG_NUM_ROWS, _RG_SORTING = 1, 3, 4
# SortingColumn
_SC_COLUMN_IDX = 1
# ColumnChunk / ColumnMetaData
_CC_META = 3
_CC_OI_OFF, _CC_OI_LEN, _CC_CI_OFF, _CC_CI_LEN = 4, 5, 6, 7
_CM_TYPE = 1
_CM_CODEC, _CM_NUM_VALUES = 4, 5
_CM_TOTAL_COMPRESSED = 7
_CM_DATA_PAGE_OFFSET, _CM_DICT_PAGE_OFFSET = 9, 11
_CM_BLOOM_OFF, _CM_BLOOM_LEN = 14, 15
# PageHeader
_PH_TYPE, _PH_UNCOMPRESSED, _PH_COMPRESSED, _PH_CRC = 1, 2, 3, 4
_PH_DATA_HEADER, _PH_DICT_HEADER, _PH_V2_HEADER = 5, 7, 8
_DPH_NUM_VALUES = 1  # in both v1 and v2 data-page headers
# ColumnIndex / OffsetIndex / PageLocation (PARQUET-922)
_CI_NULL_PAGES, _CI_MIN, _CI_MAX, _CI_ORDER, _CI_NULL_COUNTS = 1, 2, 3, 4, 5
_OI_LOCATIONS = 1
_PL_OFFSET, _PL_SIZE, _PL_FIRST_ROW = 1, 2, 3
_BO_UNORDERED, _BO_ASCENDING, _BO_DESCENDING = 0, 1, 2
# BloomFilterHeader
_BFH_NUM_BYTES, _BFH_ALGO, _BFH_HASH, _BFH_COMP = 1, 2, 3, 4
# physical types whose stats decode to numbers (parquet.thrift Type)
_PT_STRUCT_FMT = {1: "<i", 2: "<q", 4: "<f", 5: "<d"}


@dataclass
class FileReport:
    """Structured verdict for one file.  ``ok`` iff ``errors`` is empty;
    every failed check appends one human-readable entry (the walk keeps
    going where it safely can, so one report carries every independent
    defect it could reach)."""

    path: str
    size: int = 0
    ok: bool = False
    errors: list = field(default_factory=list)
    num_rows: int | None = None
    row_groups: int = 0
    columns: int = 0
    pages: int = 0
    pages_crc_checked: int = 0
    footer_bytes: int = 0
    # query-ready sections (core/index.py write side): structurally
    # validated page-index/bloom/sorting counts
    column_indexes: int = 0
    offset_indexes: int = 0
    pages_indexed: int = 0
    bloom_filters: int = 0
    sorted_row_groups: int = 0

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "size": self.size,
            "ok": self.ok,
            "errors": list(self.errors),
            "num_rows": self.num_rows,
            "row_groups": self.row_groups,
            "columns": self.columns,
            "pages": self.pages,
            "pages_crc_checked": self.pages_crc_checked,
            "footer_bytes": self.footer_bytes,
            "column_indexes": self.column_indexes,
            "offset_indexes": self.offset_indexes,
            "pages_indexed": self.pages_indexed,
            "bloom_filters": self.bloom_filters,
            "sorted_row_groups": self.sorted_row_groups,
        }


def _require_int(report: FileReport, container: dict, fid: int,
                 what: str) -> int | None:
    v = container.get(fid)
    if not isinstance(v, int) or isinstance(v, bool):
        report.errors.append(f"{what} missing or not an integer")
        return None
    return v


def _walk_chunk(data: bytes, report: FileReport, rg_i: int, col_i: int,
                meta: dict, footer_start: int) -> list | None:
    """Page-header walk of one column chunk: every page header must parse,
    every body must lie inside the chunk, the bytes must account exactly
    for total_compressed_size, data-page values must sum to num_values,
    and any page carrying a crc field must match its body's CRC-32.
    Returns the walked data pages as [(header_pos, total_size), ...] —
    what the OffsetIndex cross-check matches location by location — or
    None when the walk had to stop early."""
    where = f"row group {rg_i} column {col_i}"
    num_values = _require_int(report, meta, _CM_NUM_VALUES,
                              f"{where}: num_values")
    total = _require_int(report, meta, _CM_TOTAL_COMPRESSED,
                         f"{where}: total_compressed_size")
    data_off = _require_int(report, meta, _CM_DATA_PAGE_OFFSET,
                            f"{where}: data_page_offset")
    if num_values is None or total is None or data_off is None:
        return None
    dict_off = meta.get(_CM_DICT_PAGE_OFFSET)
    if dict_off is not None and (not isinstance(dict_off, int)
                                 or isinstance(dict_off, bool)):
        # same int discipline as the required fields: a corrupt footer can
        # flip field 11's type nibble, and the verifier must diagnose that,
        # not crash computing offsets with bytes
        report.errors.append(
            f"{where}: dictionary_page_offset is not an integer")
        return None
    start = dict_off if dict_off is not None else data_off
    end = start + total
    if start < len(MAGIC) or total < 0 or end > footer_start:
        report.errors.append(
            f"{where}: chunk [{start}, {end}) outside data region "
            f"[{len(MAGIC)}, {footer_start})")
        return None
    if not start <= data_off < end:
        report.errors.append(
            f"{where}: data_page_offset {data_off} outside chunk "
            f"[{start}, {end})")
        return None
    codec = meta.get(_CM_CODEC, Codec.UNCOMPRESSED)
    pos = start
    values_seen = 0
    first = True
    first_data_pos = None
    data_pages: list = []
    while pos < end:
        r = CompactReader(data, pos, limit=end)
        try:
            ph = r.read_struct()
        except ThriftDecodeError as e:
            report.errors.append(
                f"{where}: page header at byte {pos} unreadable: {e}")
            return None
        ptype = ph.get(_PH_TYPE)
        comp = ph.get(_PH_COMPRESSED)
        uncomp = ph.get(_PH_UNCOMPRESSED)
        if not isinstance(comp, int) or not isinstance(uncomp, int) \
                or comp < 0 or uncomp < 0:
            report.errors.append(
                f"{where}: page at byte {pos} has invalid sizes "
                f"(compressed={comp!r}, uncompressed={uncomp!r})")
            return None
        body_start = r.pos
        body_end = body_start + comp
        if body_end > end:
            report.errors.append(
                f"{where}: page body [{body_start}, {body_end}) overruns "
                f"chunk end {end} — torn page")
            return None
        if ptype == PageType.DICTIONARY_PAGE:
            if not first or dict_off != pos:
                report.errors.append(
                    f"{where}: dictionary page at byte {pos} not the "
                    f"chunk's first page at dictionary_page_offset")
        elif ptype in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
            if first_data_pos is None:
                first_data_pos = pos
            hdr_fid = (_PH_DATA_HEADER if ptype == PageType.DATA_PAGE
                       else _PH_V2_HEADER)
            hdr = ph.get(hdr_fid)
            nv = hdr.get(_DPH_NUM_VALUES) if isinstance(hdr, dict) else None
            if not isinstance(nv, int):
                report.errors.append(
                    f"{where}: data page at byte {pos} missing its "
                    f"num_values header")
                return None
            values_seen += nv
            data_pages.append((pos, body_end - pos))
        else:
            report.errors.append(
                f"{where}: page at byte {pos} has unknown type {ptype!r}")
            return None
        if codec == Codec.UNCOMPRESSED and comp != uncomp:
            report.errors.append(
                f"{where}: uncompressed page at byte {pos} has "
                f"compressed={comp} != uncompressed={uncomp}")
        crc = ph.get(_PH_CRC)
        if isinstance(crc, int):
            got = zlib.crc32(data[body_start:body_end])
            if got != crc & 0xFFFFFFFF:
                report.errors.append(
                    f"{where}: page at byte {pos} CRC mismatch "
                    f"(header {crc & 0xFFFFFFFF:#010x}, body {got:#010x})")
            report.pages_crc_checked += 1
        report.pages += 1
        first = False
        pos = body_end
    if pos != end:
        report.errors.append(
            f"{where}: pages account for {pos - start} bytes, footer says "
            f"{total}")
    if first_data_pos is not None and first_data_pos != data_off:
        report.errors.append(
            f"{where}: first data page at byte {first_data_pos}, footer "
            f"says {data_off}")
    if values_seen != num_values:
        report.errors.append(
            f"{where}: data pages carry {values_seen} values, footer says "
            f"{num_values}")
    return data_pages


def _decode_stat(value, physical_type):
    """Plain-encoded ColumnIndex min/max bytes -> comparable value, or
    None when empty/undecodable (the verifier then skips the compare
    rather than guessing)."""
    if not isinstance(value, (bytes, bytearray)) or not value:
        return None
    fmt = _PT_STRUCT_FMT.get(physical_type)
    if fmt is None:
        return bytes(value)
    if len(value) != struct.calcsize(fmt):
        return None
    return struct.unpack(fmt, value)[0]


def _leaf_types(fmd: dict) -> list:
    """Schema leaves' physical types, in column order (a SchemaElement
    without num_children is a leaf; the writer mirrors this rule)."""
    out = []
    for el in (fmd.get(_FMD_SCHEMA) or [])[1:]:
        if isinstance(el, dict) and not el.get(_SE_NUM_CHILDREN):
            out.append(el.get(_SE_TYPE))
    return out


def _section_in_bounds(report: FileReport, where: str, what: str,
                       off, length, footer_start: int) -> bool:
    """Offset/length pair sanity for one index/bloom section: both ints,
    non-negative, and the region inside the data area before the footer."""
    if not isinstance(off, int) or isinstance(off, bool) \
            or not isinstance(length, int) or isinstance(length, bool):
        report.errors.append(f"{where}: {what} offset/length not integers")
        return False
    if off < len(MAGIC) or length <= 0 or off + length > footer_start:
        report.errors.append(
            f"{where}: {what} [{off}, {off + length}) outside data region "
            f"[{len(MAGIC)}, {footer_start})")
        return False
    return True


def _computed_orders(mins: list, maxs: list, null_pages: list,
                     leaf_type) -> tuple[bool, bool]:
    """(ascending_consistent, descending_consistent) of the non-null
    pages' decoded min/max sequences — what a declared boundary order (or
    a declared sorting column) is checked against."""
    keys = []
    for i, (lo, hi) in enumerate(zip(mins, maxs)):
        if i < len(null_pages) and null_pages[i]:
            continue
        dlo, dhi = _decode_stat(lo, leaf_type), _decode_stat(hi, leaf_type)
        if dlo is None or dhi is None:
            continue  # undecodable entry: checked elsewhere, not here
        keys.append((dlo, dhi))
    asc = all(a[0] <= b[0] and a[1] <= b[1]
              for a, b in zip(keys, keys[1:]))
    desc = all(a[0] >= b[0] and a[1] >= b[1]
               for a, b in zip(keys, keys[1:]))
    return asc, desc


def _walk_index_sections(data: bytes, report: FileReport, rg_i: int,
                         col_i: int, cc: dict, meta: dict,
                         footer_start: int, leaf_type,
                         data_pages: list | None):
    """Structural walk of one chunk's query-ready sections: OffsetIndex
    locations must match the walked pages one for one, ColumnIndex lists
    must be page-count-consistent with a boundary order the stats support,
    and a bloom header must frame an in-bounds bitset.  Returns the
    ColumnIndex's computed (asc_ok, desc_ok) for the sorting-declaration
    cross-check, or None when no ColumnIndex parsed."""
    where = f"row group {rg_i} column {col_i}"
    orders = None
    oi_off, oi_len = cc.get(_CC_OI_OFF), cc.get(_CC_OI_LEN)
    ci_off, ci_len = cc.get(_CC_CI_OFF), cc.get(_CC_CI_LEN)
    n_pages = None
    if (oi_off is None) != (oi_len is None):
        report.errors.append(
            f"{where}: offset index offset/length must come as a pair")
    elif oi_off is not None and _section_in_bounds(
            report, where, "offset index", oi_off, oi_len, footer_start):
        r = CompactReader(data, oi_off, limit=oi_off + oi_len)
        try:
            oi = r.read_struct()
        except ThriftDecodeError as e:
            report.errors.append(f"{where}: offset index unreadable: {e}")
            oi = None
        if oi is not None:
            locs = oi.get(_OI_LOCATIONS)
            if not isinstance(locs, list):
                report.errors.append(
                    f"{where}: offset index has no page_locations list")
            else:
                report.offset_indexes += 1
                n_pages = len(locs)
                report.pages_indexed += n_pages
                last_row = -1
                for p_i, loc in enumerate(locs):
                    trip = (loc.get(_PL_OFFSET), loc.get(_PL_SIZE),
                            loc.get(_PL_FIRST_ROW)) \
                        if isinstance(loc, dict) else (None, None, None)
                    if not all(isinstance(v, int) and not isinstance(v, bool)
                               for v in trip):
                        report.errors.append(
                            f"{where}: page location {p_i} malformed")
                        break
                    off, size, first_row = trip
                    if data_pages is not None:
                        if p_i >= len(data_pages):
                            report.errors.append(
                                f"{where}: offset index lists {len(locs)} "
                                f"pages, chunk walk found "
                                f"{len(data_pages)}")
                            break
                        wpos, wsize = data_pages[p_i]
                        if off != wpos or size != wsize:
                            report.errors.append(
                                f"{where}: page location {p_i} says "
                                f"[{off}, +{size}), walked page at "
                                f"[{wpos}, +{wsize})")
                    if first_row <= last_row or (p_i == 0 and first_row):
                        report.errors.append(
                            f"{where}: page location {p_i} first_row_index "
                            f"{first_row} not increasing from 0")
                        break
                    last_row = first_row
                else:
                    if data_pages is not None and len(locs) != len(
                            data_pages):
                        report.errors.append(
                            f"{where}: offset index lists {len(locs)} "
                            f"pages, chunk walk found {len(data_pages)}")
    if (ci_off is None) != (ci_len is None):
        report.errors.append(
            f"{where}: column index offset/length must come as a pair")
    elif ci_off is not None and _section_in_bounds(
            report, where, "column index", ci_off, ci_len, footer_start):
        r = CompactReader(data, ci_off, limit=ci_off + ci_len)
        try:
            ci = r.read_struct()
        except ThriftDecodeError as e:
            report.errors.append(f"{where}: column index unreadable: {e}")
            ci = None
        if ci is not None:
            null_pages = ci.get(_CI_NULL_PAGES)
            mins, maxs = ci.get(_CI_MIN), ci.get(_CI_MAX)
            order = ci.get(_CI_ORDER)
            null_counts = ci.get(_CI_NULL_COUNTS)
            if not (isinstance(null_pages, list) and isinstance(mins, list)
                    and isinstance(maxs, list)):
                report.errors.append(
                    f"{where}: column index missing a required page list")
            elif not len(null_pages) == len(mins) == len(maxs):
                report.errors.append(
                    f"{where}: column index page lists disagree "
                    f"({len(null_pages)}/{len(mins)}/{len(maxs)})")
            elif n_pages is not None and len(mins) != n_pages:
                report.errors.append(
                    f"{where}: column index covers {len(mins)} pages, "
                    f"offset index {n_pages}")
            elif null_counts is not None and (
                    not isinstance(null_counts, list)
                    or len(null_counts) != len(mins)):
                report.errors.append(
                    f"{where}: column index null_counts length mismatch")
            elif order not in (_BO_UNORDERED, _BO_ASCENDING,
                               _BO_DESCENDING):
                report.errors.append(
                    f"{where}: column index boundary_order {order!r} "
                    f"invalid")
            elif isinstance(null_counts, list) and any(
                    flag and isinstance(nc, int) and not isinstance(nc, bool)
                    and nc == 0
                    for flag, nc in zip(null_pages, null_counts)):
                # null_pages=true claims EVERY value on the page is null;
                # a zero null_count on the same page is a contradiction a
                # pruning reader would act on
                report.errors.append(
                    f"{where}: column index declares a null page with "
                    f"null_count 0")
            else:
                report.column_indexes += 1
                orders = _computed_orders(mins, maxs, null_pages, leaf_type)
                if ((order == _BO_ASCENDING and not orders[0])
                        or (order == _BO_DESCENDING and not orders[1])):
                    report.errors.append(
                        f"{where}: boundary_order "
                        f"{'ASCENDING' if order == _BO_ASCENDING else 'DESCENDING'}"
                        f" contradicted by the page min/max stats")
    bloom_off = meta.get(_CM_BLOOM_OFF)
    if bloom_off is not None:
        if not isinstance(bloom_off, int) or isinstance(bloom_off, bool) \
                or not len(MAGIC) <= bloom_off < footer_start:
            report.errors.append(
                f"{where}: bloom_filter_offset {bloom_off!r} invalid")
        else:
            r = CompactReader(data, bloom_off, limit=footer_start)
            try:
                hdr = r.read_struct()
            except ThriftDecodeError as e:
                report.errors.append(
                    f"{where}: bloom filter header unreadable: {e}")
                hdr = None
            if hdr is not None:
                nb = hdr.get(_BFH_NUM_BYTES)
                bad = None
                if not isinstance(nb, int) or isinstance(nb, bool) \
                        or nb < 32 or nb % 32:
                    bad = f"numBytes {nb!r} (need a multiple of 32 >= 32)"
                elif r.pos + nb > footer_start:
                    bad = (f"bitset [{r.pos}, {r.pos + nb}) overruns the "
                           f"data region")
                else:
                    for fid, what in ((_BFH_ALGO, "algorithm"),
                                      (_BFH_HASH, "hash"),
                                      (_BFH_COMP, "compression")):
                        union = hdr.get(fid)
                        if not isinstance(union, dict) or 1 not in union:
                            bad = f"{what} union missing variant 1"
                            break
                bloom_len = meta.get(_CM_BLOOM_LEN)
                if bad is None and isinstance(bloom_len, int) \
                        and not isinstance(bloom_len, bool) \
                        and bloom_len != (r.pos - bloom_off) + nb:
                    bad = (f"bloom_filter_length {bloom_len} != header + "
                           f"bitset {(r.pos - bloom_off) + nb}")
                if bad is not None:
                    report.errors.append(
                        f"{where}: bloom filter header: {bad}")
                else:
                    report.bloom_filters += 1
    return orders


def verify_bytes(data: bytes, path: str = "<bytes>") -> FileReport:
    """Structurally verify one parquet file given its full contents."""
    report = FileReport(path=path, size=len(data))
    if len(data) < len(MAGIC) * 2 + 4:
        report.errors.append(
            f"file of {len(data)} bytes cannot frame magic + footer")
        return report
    if data[: len(MAGIC)] != MAGIC:
        report.errors.append("leading PAR1 magic missing")
    if data[-len(MAGIC):] != MAGIC:
        report.errors.append("trailing PAR1 magic missing — torn tail")
        return report  # without the tail frame nothing below is anchored
    footer_len = int.from_bytes(data[-_TAIL:-len(MAGIC)], "little")
    report.footer_bytes = footer_len
    footer_start = len(data) - _TAIL - footer_len
    if footer_len <= 0 or footer_start < len(MAGIC):
        report.errors.append(
            f"footer length {footer_len} does not fit the file "
            f"({len(data)} bytes)")
        return report
    r = CompactReader(data, footer_start, limit=len(data) - _TAIL)
    try:
        fmd = r.read_struct()
    except ThriftDecodeError as e:
        report.errors.append(f"footer thrift parse failed: {e}")
        return report
    if r.pos != len(data) - _TAIL:
        report.errors.append(
            f"footer parse consumed {r.pos - footer_start} bytes, "
            f"frame says {footer_len}")
    if not isinstance(fmd.get(_FMD_SCHEMA), list) or not fmd.get(_FMD_SCHEMA):
        report.errors.append("footer has no schema elements")
    num_rows = _require_int(report, fmd, _FMD_NUM_ROWS, "footer num_rows")
    report.num_rows = num_rows
    rgs = fmd.get(_FMD_ROW_GROUPS)
    if not isinstance(rgs, list):
        report.errors.append("footer has no row-group list")
        return report
    report.row_groups = len(rgs)
    leaf_types = _leaf_types(fmd)
    rows_sum = 0
    for rg_i, rg in enumerate(rgs):
        if not isinstance(rg, dict):
            report.errors.append(f"row group {rg_i} is not a struct")
            continue
        rg_rows = _require_int(report, rg, _RG_NUM_ROWS,
                               f"row group {rg_i} num_rows")
        if rg_rows is not None:
            rows_sum += rg_rows
        cols = rg.get(_RG_COLUMNS)
        if not isinstance(cols, list) or not cols:
            report.errors.append(f"row group {rg_i} has no column chunks")
            continue
        col_orders: dict[int, tuple] = {}
        for col_i, cc in enumerate(cols):
            meta = cc.get(_CC_META) if isinstance(cc, dict) else None
            if not isinstance(meta, dict):
                report.errors.append(
                    f"row group {rg_i} column {col_i} has no metadata")
                continue
            report.columns += 1
            pages = _walk_chunk(data, report, rg_i, col_i, meta,
                                footer_start)
            orders = _walk_index_sections(
                data, report, rg_i, col_i, cc, meta, footer_start,
                leaf_types[col_i] if col_i < len(leaf_types) else None,
                pages)
            if orders is not None:
                col_orders[col_i] = orders
        # sorting_columns declarations: structurally sane, and consistent
        # with the declared column's page-index ordering when one exists
        sorting = rg.get(_RG_SORTING)
        if sorting is not None:
            if not isinstance(sorting, list):
                report.errors.append(
                    f"row group {rg_i}: sorting_columns is not a list")
            else:
                ok = True
                for s_i, sc in enumerate(sorting):
                    idx = sc.get(_SC_COLUMN_IDX) if isinstance(sc, dict) \
                        else None
                    if not isinstance(idx, int) or isinstance(idx, bool) \
                            or not 0 <= idx < len(cols):
                        report.errors.append(
                            f"row group {rg_i}: sorting column {s_i} "
                            f"ordinal {idx!r} out of range")
                        ok = False
                        continue
                    descending = bool(sc.get(2))
                    orders = col_orders.get(idx)
                    # only the PRIMARY sort key's page order is globally
                    # implied by the declaration (secondary keys order
                    # only within equal primary prefixes)
                    if s_i == 0 and orders is not None and \
                            not orders[1 if descending else 0]:
                        report.errors.append(
                            f"row group {rg_i}: declared "
                            f"{'descending' if descending else 'ascending'}"
                            f" sort on column {idx} contradicted by its "
                            f"column index page stats")
                        ok = False
                if ok:
                    report.sorted_row_groups += 1
    if num_rows is not None and rows_sum != num_rows:
        report.errors.append(
            f"row groups sum to {rows_sum} rows, footer says {num_rows}")
    report.ok = not report.errors
    return report


def verify_file(fs: FileSystem, path: str) -> FileReport:
    """Read ``path`` through ``fs`` and structurally verify it.  A file
    that cannot even be read reports that as its (only) error."""
    try:
        with fs.open_read(path) as f:
            data = f.read()
    except (OSError, KeyError) as e:  # KeyError: MemoryFileSystem miss
        report = FileReport(path=path)
        report.errors.append(f"unreadable: {e!r}")
        return report
    return verify_bytes(data, path)


def verify_dir(fs: FileSystem, target_dir: str,
               extension: str = ".parquet",
               exclude_dirs: tuple = ("tmp", "quarantine",
                                      "compacted")) -> list[FileReport]:
    """Verify every published ``extension`` file under ``target_dir``,
    excluding the writer's working subtrees (``tmp/`` holds open files
    that are legitimately incomplete; ``quarantine/`` holds files already
    condemned; ``compacted/`` holds retired compaction inputs — tombstoned
    duplicates whose rows live on in a merged published file)."""
    target = target_dir.rstrip("/")
    skips = tuple(f"{target}/{d}/" for d in exclude_dirs)
    out = []
    for p in fs.list_files(target, extension=extension):
        if any(p.startswith(s) for s in skips):
            continue
        out.append(verify_file(fs, p))
    return out


def schema_leaves_from_bytes(data: bytes,
                             path: str = "<bytes>") -> dict[str, tuple]:
    """The leaf schema of one parquet file from its bytes: dotted column
    path -> ``(physical_type, repetition, converted_type)`` thrift ints.
    Raises ``ValueError`` on anything whose footer cannot be parsed —
    callers auditing a tree route unreadable files through the
    structural verifier instead of guessing a schema for them."""
    if len(data) < _TAIL + len(MAGIC) or data[-len(MAGIC):] != MAGIC:
        raise ValueError(f"{path}: trailing PAR1 magic missing")
    footer_len = int.from_bytes(data[-_TAIL:-len(MAGIC)], "little")
    footer_start = len(data) - _TAIL - footer_len
    if footer_len <= 0 or footer_start < len(MAGIC):
        raise ValueError(
            f"{path}: footer length {footer_len} does not fit the file")
    r = CompactReader(data, footer_start, limit=len(data) - _TAIL)
    try:
        fmd = r.read_struct()
    except ThriftDecodeError as e:
        raise ValueError(f"{path}: footer thrift parse failed: {e}")
    elems = fmd.get(_FMD_SCHEMA)
    if not isinstance(elems, list) or not elems:
        raise ValueError(f"{path}: footer has no schema elements")
    leaves: dict[str, tuple] = {}
    pos = [0]

    def walk(prefix: str) -> None:
        if pos[0] >= len(elems):
            raise ValueError(f"{path}: schema element list truncated")
        el = elems[pos[0]]
        pos[0] += 1
        if not isinstance(el, dict):
            raise ValueError(f"{path}: schema element is not a struct")
        name = el.get(_SE_NAME)
        name = (name.decode("utf-8", "replace")
                if isinstance(name, bytes) else str(name))
        dotted = f"{prefix}.{name}" if prefix else name
        nchildren = el.get(_SE_NUM_CHILDREN)
        if isinstance(nchildren, int) and nchildren > 0:
            for _ in range(nchildren):
                walk(dotted)
        else:
            leaves[dotted] = (el.get(_SE_TYPE), el.get(_SE_REPETITION),
                              el.get(_SE_CONVERTED))

    root = elems[pos[0]]
    pos[0] += 1
    n_top = root.get(_SE_NUM_CHILDREN) if isinstance(root, dict) else None
    if not isinstance(n_top, int) or n_top <= 0:
        raise ValueError(f"{path}: schema root has no children")
    for _ in range(n_top):
        walk("")
    return leaves


def file_schema(fs: FileSystem, path: str) -> dict[str, tuple]:
    """Read ``path`` through ``fs`` and return its leaf schema (see
    :func:`schema_leaves_from_bytes`)."""
    with fs.open_read(path) as f:
        data = f.read()
    return schema_leaves_from_bytes(data, path)


#: the writer's working subtrees a schema verdict must never read from:
#: ``tmp/`` holds open files, ``quarantine/`` condemned ones,
#: ``compacted/`` tombstoned duplicates, ``deadletter/`` raw frames
SCHEMA_EXCLUDE_DIRS = ("tmp", "quarantine", "compacted", "deadletter")


def tree_schemas(fs: FileSystem, target_dir: str,
                 extension: str = ".parquet",
                 exclude_dirs: tuple = SCHEMA_EXCLUDE_DIRS,
                 ) -> tuple[dict, list]:
    """Walk one partition tree's published files and collect each one's
    leaf schema: ``(per_file, unreadable)`` where ``per_file`` maps path
    -> the :func:`file_schema` leaf dict and ``unreadable`` lists files
    whose footer could not be parsed (the structural verifier's problem,
    not a schema verdict).  The ONE tree-walk the schema audit and the
    route-level schema guard share — the exclude set and the
    unreadable-file policy cannot diverge between them."""
    target = target_dir.rstrip("/")
    skips = tuple(f"{target}/{d}/" for d in exclude_dirs)
    per_file: dict[str, dict] = {}
    unreadable: list[dict] = []
    try:
        files = fs.list_files(target, extension=extension)
    except FileNotFoundError:
        return per_file, unreadable
    for p in files:
        if any(p.startswith(s) for s in skips):
            continue
        try:
            per_file[p] = file_schema(fs, p)
        except (ValueError, OSError, KeyError) as e:
            unreadable.append({"path": p, "error": repr(e)})
    return per_file, unreadable


def audit_schema_consistency(
        fs: FileSystem, target_dir: str, extension: str = ".parquet",
        exclude_dirs: tuple = SCHEMA_EXCLUDE_DIRS) -> dict:
    """Cross-file schema-consistency audit over one partition tree — the
    schema half of the PR-9 structural verifier, grown for schema
    evolution (multi-tenant routes write one tree per tenant over a
    proto lineage that changes additively over time):

    * a **conflict** is one dotted leaf path carrying more than one
      physical type across the tree's published files — a merged-schema
      reader (pyarrow dataset schema unification) cannot reconcile
      ``int64`` and ``byte_array`` under one name, so this is the shape
      the route-level schema guard dead-letters and this audit flags;
    * **additive columns** (present in some files, absent in others) are
      the EXPECTED evolution shape — merged reads surface them as nulls
      for the older files — and are reported, never flagged;
    * unreadable/unparsable files are listed separately (they are the
      structural verifier's problem, not a schema verdict).

    Returns ``{"files", "consistent", "conflicts", "additive_columns",
    "by_partition", "unreadable"}`` with each conflict naming the column,
    its observed types, and up to 3 carrier files per type."""
    target = target_dir.rstrip("/")
    per_file, unreadable = tree_schemas(fs, target_dir, extension,
                                        exclude_dirs)
    # column -> physical type -> carrier files
    types: dict[str, dict[int, list]] = {}
    by_partition: dict[str, int] = {}
    for p, leaves in per_file.items():
        rel_dir = p[len(target) + 1:].rsplit("/", 1)
        by_partition[rel_dir[0] if len(rel_dir) == 2 else "."] = (
            by_partition.get(rel_dir[0] if len(rel_dir) == 2 else ".", 0) + 1)
        for col, (pt, _rep, _conv) in leaves.items():
            types.setdefault(col, {}).setdefault(pt, []).append(p)
    conflicts = []
    for col in sorted(types):
        if len(types[col]) > 1:
            conflicts.append({
                "column": col,
                "types": {str(pt): sorted(files)[:3]
                          for pt, files in sorted(types[col].items(),
                                                  key=lambda kv: str(kv[0]))},
            })
    additive = sorted(
        col for col, by_type in types.items()
        if sum(len(f) for f in by_type.values()) < len(per_file))
    return {
        "files": len(per_file),
        "consistent": not conflicts,
        "conflicts": conflicts,
        "additive_columns": additive,
        "by_partition": by_partition,
        "unreadable": unreadable,
    }


def summarize(reports: list[FileReport]) -> dict:
    """Directory-level rollup of many reports: file/row/page totals plus
    the failing paths — the one-call integrity verdict compaction runs
    assert on (``--summary``)."""
    bad = [r for r in reports if not r.ok]
    return {
        "files": len(reports),
        "ok": len(reports) - len(bad),
        "failed": len(bad),
        "rows": sum(r.num_rows or 0 for r in reports if r.ok),
        "row_groups": sum(r.row_groups for r in reports),
        "pages": sum(r.pages for r in reports),
        "pages_crc_checked": sum(r.pages_crc_checked for r in reports),
        # query-readiness counters: how much of the directory a selective
        # reader can prune (pages under a validated page index), how many
        # bloom filters were header-checked, and how many row groups
        # declare a sort order the index stats support
        "pages_indexed": sum(r.pages_indexed for r in reports),
        "column_indexes": sum(r.column_indexes for r in reports),
        "bloom_filters_checked": sum(r.bloom_filters for r in reports),
        "sorted_row_groups": sum(r.sorted_row_groups for r in reports),
        "bytes": sum(r.size for r in reports),
        "failures": [r.path for r in bad],
    }


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    as_summary = "--summary" in argv
    paths = [a for a in argv if a not in ("--json", "--summary")]
    if not paths:
        print("usage: python -m kpw_tpu.io.verify [--json] [--summary] "
              "<file-or-dir> [...]", file=sys.stderr)
        return 2
    fs = LocalFileSystem()
    reports: list[FileReport] = []
    for p in paths:
        if os.path.isdir(p):
            reports.extend(verify_dir(fs, p))
        else:
            reports.append(verify_file(fs, p))
    if as_summary:
        print(json.dumps(summarize(reports), indent=1))
    elif as_json:
        print(json.dumps([r.to_dict() for r in reports], indent=1))
    else:
        for r in reports:
            if r.ok:
                print(f"OK   {r.path}  rows={r.num_rows} "
                      f"row_groups={r.row_groups} pages={r.pages} "
                      f"crc_checked={r.pages_crc_checked} "
                      f"pages_indexed={r.pages_indexed} "
                      f"bloom_filters={r.bloom_filters}")
            else:
                print(f"FAIL {r.path}")
                for e in r.errors:
                    print(f"     - {e}")
    bad = sum(1 for r in reports if not r.ok)
    print(f"{len(reports) - bad}/{len(reports)} file(s) structurally valid",
          file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
