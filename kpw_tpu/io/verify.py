"""Independent structural verifier for parquet files — no pyarrow, no
shared write-path code beyond the thrift decoder.

The write side (core/writer.py, core/pages.py) emits page CRCs and a
thrift footer, but until this module nothing in the repo could *check* a
published file: a torn final (kill -9 between a page-cache write and the
fsync that never happened) or a bit-flipped page body was invisible until
some downstream reader choked.  This verifier walks the physical layout
from the bytes alone:

* ``PAR1`` magic at both ends,
* footer-length sanity (the 4-byte little-endian length must frame a
  region inside the file),
* thrift-compact footer parse (bounds-checked ``core.thrift.CompactReader``
  — corruption raises ``ThriftDecodeError``, never an IndexError),
* row-group / column-chunk offsets and sizes in-bounds and non-overlapping
  with the footer,
* a full page-header walk of every column chunk (header parse, body
  in-bounds, page-type sanity, per-chunk byte accounting),
* CRC-32 (gzip polynomial, PARQUET-1539) check of every page body that
  carries the optional crc field — the write side's
  ``Builder.page_checksums(True)`` checksums verified on read,
* row/value-count consistency (row-group rows sum to the footer's
  ``num_rows``; each chunk's data-page values sum to its meta's
  ``num_values``).

It deliberately does NOT decode values: the contract is "structurally
valid parquet whose every byte is where the footer says it is", which is
what the recovery pass (runtime/writer.py ``recover``) needs to decide
publish-vs-quarantine, and what the crash harness (tests/test_crash.py,
``bench.py --crash``) asserts for every acked offset's file.

CLI: ``python -m kpw_tpu.io.verify <file-or-dir> [...]`` — exit 0 iff
every file verifies; ``--json`` dumps the reports as one JSON array;
``--summary`` replaces the per-file report with ONE JSON rollup
(files/rows/row groups/pages/failing paths) so a compaction run can
assert directory-level integrity in a single call.
"""

from __future__ import annotations

import json
import os
import sys
import zlib
from dataclasses import dataclass, field

from ..core.schema import Codec, PageType
from ..core.thrift import CompactReader, ThriftDecodeError
from .fs import FileSystem, LocalFileSystem

MAGIC = b"PAR1"
# trailing frame: 4-byte little-endian footer length + magic
_TAIL = 8
# FileMetaData field ids (parquet.thrift; mirrors core/metadata.py's writer)
_FMD_VERSION, _FMD_SCHEMA, _FMD_NUM_ROWS, _FMD_ROW_GROUPS = 1, 2, 3, 4
# RowGroup
_RG_COLUMNS, _RG_NUM_ROWS = 1, 3
# ColumnChunk / ColumnMetaData
_CC_META = 3
_CM_CODEC, _CM_NUM_VALUES = 4, 5
_CM_TOTAL_COMPRESSED = 7
_CM_DATA_PAGE_OFFSET, _CM_DICT_PAGE_OFFSET = 9, 11
# PageHeader
_PH_TYPE, _PH_UNCOMPRESSED, _PH_COMPRESSED, _PH_CRC = 1, 2, 3, 4
_PH_DATA_HEADER, _PH_DICT_HEADER, _PH_V2_HEADER = 5, 7, 8
_DPH_NUM_VALUES = 1  # in both v1 and v2 data-page headers


@dataclass
class FileReport:
    """Structured verdict for one file.  ``ok`` iff ``errors`` is empty;
    every failed check appends one human-readable entry (the walk keeps
    going where it safely can, so one report carries every independent
    defect it could reach)."""

    path: str
    size: int = 0
    ok: bool = False
    errors: list = field(default_factory=list)
    num_rows: int | None = None
    row_groups: int = 0
    columns: int = 0
    pages: int = 0
    pages_crc_checked: int = 0
    footer_bytes: int = 0

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "size": self.size,
            "ok": self.ok,
            "errors": list(self.errors),
            "num_rows": self.num_rows,
            "row_groups": self.row_groups,
            "columns": self.columns,
            "pages": self.pages,
            "pages_crc_checked": self.pages_crc_checked,
            "footer_bytes": self.footer_bytes,
        }


def _require_int(report: FileReport, container: dict, fid: int,
                 what: str) -> int | None:
    v = container.get(fid)
    if not isinstance(v, int) or isinstance(v, bool):
        report.errors.append(f"{what} missing or not an integer")
        return None
    return v


def _walk_chunk(data: bytes, report: FileReport, rg_i: int, col_i: int,
                meta: dict, footer_start: int) -> None:
    """Page-header walk of one column chunk: every page header must parse,
    every body must lie inside the chunk, the bytes must account exactly
    for total_compressed_size, data-page values must sum to num_values,
    and any page carrying a crc field must match its body's CRC-32."""
    where = f"row group {rg_i} column {col_i}"
    num_values = _require_int(report, meta, _CM_NUM_VALUES,
                              f"{where}: num_values")
    total = _require_int(report, meta, _CM_TOTAL_COMPRESSED,
                         f"{where}: total_compressed_size")
    data_off = _require_int(report, meta, _CM_DATA_PAGE_OFFSET,
                            f"{where}: data_page_offset")
    if num_values is None or total is None or data_off is None:
        return
    dict_off = meta.get(_CM_DICT_PAGE_OFFSET)
    if dict_off is not None and (not isinstance(dict_off, int)
                                 or isinstance(dict_off, bool)):
        # same int discipline as the required fields: a corrupt footer can
        # flip field 11's type nibble, and the verifier must diagnose that,
        # not crash computing offsets with bytes
        report.errors.append(
            f"{where}: dictionary_page_offset is not an integer")
        return
    start = dict_off if dict_off is not None else data_off
    end = start + total
    if start < len(MAGIC) or total < 0 or end > footer_start:
        report.errors.append(
            f"{where}: chunk [{start}, {end}) outside data region "
            f"[{len(MAGIC)}, {footer_start})")
        return
    if not start <= data_off < end:
        report.errors.append(
            f"{where}: data_page_offset {data_off} outside chunk "
            f"[{start}, {end})")
        return
    codec = meta.get(_CM_CODEC, Codec.UNCOMPRESSED)
    pos = start
    values_seen = 0
    first = True
    first_data_pos = None
    while pos < end:
        r = CompactReader(data, pos, limit=end)
        try:
            ph = r.read_struct()
        except ThriftDecodeError as e:
            report.errors.append(
                f"{where}: page header at byte {pos} unreadable: {e}")
            return
        ptype = ph.get(_PH_TYPE)
        comp = ph.get(_PH_COMPRESSED)
        uncomp = ph.get(_PH_UNCOMPRESSED)
        if not isinstance(comp, int) or not isinstance(uncomp, int) \
                or comp < 0 or uncomp < 0:
            report.errors.append(
                f"{where}: page at byte {pos} has invalid sizes "
                f"(compressed={comp!r}, uncompressed={uncomp!r})")
            return
        body_start = r.pos
        body_end = body_start + comp
        if body_end > end:
            report.errors.append(
                f"{where}: page body [{body_start}, {body_end}) overruns "
                f"chunk end {end} — torn page")
            return
        if ptype == PageType.DICTIONARY_PAGE:
            if not first or dict_off != pos:
                report.errors.append(
                    f"{where}: dictionary page at byte {pos} not the "
                    f"chunk's first page at dictionary_page_offset")
        elif ptype in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
            if first_data_pos is None:
                first_data_pos = pos
            hdr_fid = (_PH_DATA_HEADER if ptype == PageType.DATA_PAGE
                       else _PH_V2_HEADER)
            hdr = ph.get(hdr_fid)
            nv = hdr.get(_DPH_NUM_VALUES) if isinstance(hdr, dict) else None
            if not isinstance(nv, int):
                report.errors.append(
                    f"{where}: data page at byte {pos} missing its "
                    f"num_values header")
                return
            values_seen += nv
        else:
            report.errors.append(
                f"{where}: page at byte {pos} has unknown type {ptype!r}")
            return
        if codec == Codec.UNCOMPRESSED and comp != uncomp:
            report.errors.append(
                f"{where}: uncompressed page at byte {pos} has "
                f"compressed={comp} != uncompressed={uncomp}")
        crc = ph.get(_PH_CRC)
        if isinstance(crc, int):
            got = zlib.crc32(data[body_start:body_end])
            if got != crc & 0xFFFFFFFF:
                report.errors.append(
                    f"{where}: page at byte {pos} CRC mismatch "
                    f"(header {crc & 0xFFFFFFFF:#010x}, body {got:#010x})")
            report.pages_crc_checked += 1
        report.pages += 1
        first = False
        pos = body_end
    if pos != end:
        report.errors.append(
            f"{where}: pages account for {pos - start} bytes, footer says "
            f"{total}")
    if first_data_pos is not None and first_data_pos != data_off:
        report.errors.append(
            f"{where}: first data page at byte {first_data_pos}, footer "
            f"says {data_off}")
    if values_seen != num_values:
        report.errors.append(
            f"{where}: data pages carry {values_seen} values, footer says "
            f"{num_values}")


def verify_bytes(data: bytes, path: str = "<bytes>") -> FileReport:
    """Structurally verify one parquet file given its full contents."""
    report = FileReport(path=path, size=len(data))
    if len(data) < len(MAGIC) * 2 + 4:
        report.errors.append(
            f"file of {len(data)} bytes cannot frame magic + footer")
        return report
    if data[: len(MAGIC)] != MAGIC:
        report.errors.append("leading PAR1 magic missing")
    if data[-len(MAGIC):] != MAGIC:
        report.errors.append("trailing PAR1 magic missing — torn tail")
        return report  # without the tail frame nothing below is anchored
    footer_len = int.from_bytes(data[-_TAIL:-len(MAGIC)], "little")
    report.footer_bytes = footer_len
    footer_start = len(data) - _TAIL - footer_len
    if footer_len <= 0 or footer_start < len(MAGIC):
        report.errors.append(
            f"footer length {footer_len} does not fit the file "
            f"({len(data)} bytes)")
        return report
    r = CompactReader(data, footer_start, limit=len(data) - _TAIL)
    try:
        fmd = r.read_struct()
    except ThriftDecodeError as e:
        report.errors.append(f"footer thrift parse failed: {e}")
        return report
    if r.pos != len(data) - _TAIL:
        report.errors.append(
            f"footer parse consumed {r.pos - footer_start} bytes, "
            f"frame says {footer_len}")
    if not isinstance(fmd.get(_FMD_SCHEMA), list) or not fmd.get(_FMD_SCHEMA):
        report.errors.append("footer has no schema elements")
    num_rows = _require_int(report, fmd, _FMD_NUM_ROWS, "footer num_rows")
    report.num_rows = num_rows
    rgs = fmd.get(_FMD_ROW_GROUPS)
    if not isinstance(rgs, list):
        report.errors.append("footer has no row-group list")
        return report
    report.row_groups = len(rgs)
    rows_sum = 0
    for rg_i, rg in enumerate(rgs):
        if not isinstance(rg, dict):
            report.errors.append(f"row group {rg_i} is not a struct")
            continue
        rg_rows = _require_int(report, rg, _RG_NUM_ROWS,
                               f"row group {rg_i} num_rows")
        if rg_rows is not None:
            rows_sum += rg_rows
        cols = rg.get(_RG_COLUMNS)
        if not isinstance(cols, list) or not cols:
            report.errors.append(f"row group {rg_i} has no column chunks")
            continue
        for col_i, cc in enumerate(cols):
            meta = cc.get(_CC_META) if isinstance(cc, dict) else None
            if not isinstance(meta, dict):
                report.errors.append(
                    f"row group {rg_i} column {col_i} has no metadata")
                continue
            report.columns += 1
            _walk_chunk(data, report, rg_i, col_i, meta, footer_start)
    if num_rows is not None and rows_sum != num_rows:
        report.errors.append(
            f"row groups sum to {rows_sum} rows, footer says {num_rows}")
    report.ok = not report.errors
    return report


def verify_file(fs: FileSystem, path: str) -> FileReport:
    """Read ``path`` through ``fs`` and structurally verify it.  A file
    that cannot even be read reports that as its (only) error."""
    try:
        with fs.open_read(path) as f:
            data = f.read()
    except (OSError, KeyError) as e:  # KeyError: MemoryFileSystem miss
        report = FileReport(path=path)
        report.errors.append(f"unreadable: {e!r}")
        return report
    return verify_bytes(data, path)


def verify_dir(fs: FileSystem, target_dir: str,
               extension: str = ".parquet",
               exclude_dirs: tuple = ("tmp", "quarantine",
                                      "compacted")) -> list[FileReport]:
    """Verify every published ``extension`` file under ``target_dir``,
    excluding the writer's working subtrees (``tmp/`` holds open files
    that are legitimately incomplete; ``quarantine/`` holds files already
    condemned; ``compacted/`` holds retired compaction inputs — tombstoned
    duplicates whose rows live on in a merged published file)."""
    target = target_dir.rstrip("/")
    skips = tuple(f"{target}/{d}/" for d in exclude_dirs)
    out = []
    for p in fs.list_files(target, extension=extension):
        if any(p.startswith(s) for s in skips):
            continue
        out.append(verify_file(fs, p))
    return out


def summarize(reports: list[FileReport]) -> dict:
    """Directory-level rollup of many reports: file/row/page totals plus
    the failing paths — the one-call integrity verdict compaction runs
    assert on (``--summary``)."""
    bad = [r for r in reports if not r.ok]
    return {
        "files": len(reports),
        "ok": len(reports) - len(bad),
        "failed": len(bad),
        "rows": sum(r.num_rows or 0 for r in reports if r.ok),
        "row_groups": sum(r.row_groups for r in reports),
        "pages": sum(r.pages for r in reports),
        "pages_crc_checked": sum(r.pages_crc_checked for r in reports),
        "bytes": sum(r.size for r in reports),
        "failures": [r.path for r in bad],
    }


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    as_summary = "--summary" in argv
    paths = [a for a in argv if a not in ("--json", "--summary")]
    if not paths:
        print("usage: python -m kpw_tpu.io.verify [--json] [--summary] "
              "<file-or-dir> [...]", file=sys.stderr)
        return 2
    fs = LocalFileSystem()
    reports: list[FileReport] = []
    for p in paths:
        if os.path.isdir(p):
            reports.extend(verify_dir(fs, p))
        else:
            reports.append(verify_file(fs, p))
    if as_summary:
        print(json.dumps(summarize(reports), indent=1))
    elif as_json:
        print(json.dumps([r.to_dict() for r in reports], indent=1))
    else:
        for r in reports:
            if r.ok:
                print(f"OK   {r.path}  rows={r.num_rows} "
                      f"row_groups={r.row_groups} pages={r.pages} "
                      f"crc_checked={r.pages_crc_checked}")
            else:
                print(f"FAIL {r.path}")
                for e in r.errors:
                    print(f"     - {e}")
    bad = sum(1 for r in reports if not r.ok)
    print(f"{len(reports) - bad}/{len(reports)} file(s) structurally valid",
          file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
