"""Pluggable filesystems for the writer sink (local FS + in-memory HDFS
analog), with the atomic tmp→rename publish the correctness protocol needs
(reference renameAndMoveTempFile, KafkaProtoParquetWriter.java:359-378)."""

from .fs import FileSystem, LocalFileSystem, MemoryFileSystem  # noqa: F401
from .hdfs import HdfsFileSystem  # noqa: F401  (needs libhdfs at construction)
# lint: fault-isolation ok — the package's public opt-in seam: tests and
# benchmarks import these names from here; no production call path
# references them (enforced by tools/analyze's fault-isolation pass on
# every other module)
from .faults import (  # noqa: F401
    FaultInjectingFileSystem,
    FaultSchedule,
    InjectedFault,
    objectstore_persona,
)
from .failover import FailoverFileSystem  # noqa: F401
from .objectstore import (  # noqa: F401
    BandwidthBudget,
    BandwidthBudgetedFileSystem,
    EmulatedObjectStore,
    ObjectStoreFileSystem,
)
# NOTE: .verify is deliberately NOT imported here — it is a runnable module
# (`python -m kpw_tpu.io.verify <file-or-dir>`), and a package-level import
# would make runpy warn about the double import.  Import it directly:
#   from kpw_tpu.io.verify import verify_file, verify_dir, FileReport
