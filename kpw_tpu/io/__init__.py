"""Pluggable filesystems for the writer sink (local FS + in-memory HDFS
analog), with the atomic tmp→rename publish the correctness protocol needs
(reference renameAndMoveTempFile, KafkaProtoParquetWriter.java:359-378)."""

from .fs import FileSystem, LocalFileSystem, MemoryFileSystem  # noqa: F401
from .hdfs import HdfsFileSystem  # noqa: F401  (needs libhdfs at construction)
from .faults import (  # noqa: F401
    FaultInjectingFileSystem,
    FaultSchedule,
    InjectedFault,
)
