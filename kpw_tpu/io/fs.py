"""Filesystem abstraction: the subset of the Hadoop ``FileSystem`` API the
reference uses (mkdirs / create / atomic rename / list — KPW.java:359-378,
test utils HdfsTestUtil.java:79-91), with two implementations:

* :class:`LocalFileSystem` — posix dirs/files; `os.replace` is the atomic
  publish.
* :class:`MemoryFileSystem` — in-process page store standing in for HDFS the
  way MiniDFSCluster does in the reference tests (SURVEY.md §4 rebuild
  mapping), with the same atomic-rename semantics.
"""

from __future__ import annotations

import io
import os
import threading


class FileSystem:
    # capability seam: rename-capable filesystems (posix, HDFS, the
    # in-memory analog) publish via (durable_)rename; an object-store
    # sink has no rename — it flips this False and implements
    # publish_commit (multipart-complete / atomic PUT at the destination
    # key).  publish_file() below is the ONE decision point every
    # publish path (worker, process child, compactor) routes through.
    supports_rename = True

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def publish_commit(self, src: str, dst: str) -> None:
        """Atomic publish for rename-less filesystems (object stores):
        make the staged file at ``src`` visible at ``dst`` in one store
        operation.  Only meaningful when ``supports_rename`` is False —
        NOT an abstract member of the surface: rename-capable
        filesystems never implement it (publish_file routes them through
        the rename protocol), so calling it on one is a caller bug, not
        a missing override.  Deliberately not an OSError: the retry
        layer must never spin on a protocol-dispatch mistake."""
        raise TypeError(
            "this filesystem publishes by rename (supports_rename=True); "
            "publish via io.fs.publish_file, which dispatches on the "
            "capability")

    def open_write(self, path: str):
        """Create (overwrite) a file for binary writing."""
        raise NotImplementedError

    def open_append(self, path: str):
        """Open for binary appending (creating if missing) — existing
        contents are never truncated, so a failed append can lose at most
        the new tail (the dead-letter durability requirement)."""
        raise NotImplementedError

    def open_read(self, path: str):
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Atomic move; parent of dst must exist."""
        raise NotImplementedError

    def sync(self, path: str) -> None:
        """Force ``path``'s contents to stable storage (fsync).  A plain
        close() only hands the bytes to the OS page cache — they survive a
        process kill but NOT a machine crash/power cut.  Implementations
        whose close IS durable (MemoryFileSystem's atomic store publish,
        HDFS pipeline close) no-op."""
        raise NotImplementedError

    def sync_dir(self, path: str) -> None:
        """Force the DIRECTORY ENTRY updates under ``path`` (a rename's new
        name, a create) to stable storage.  POSIX makes this a separate
        fsync on the directory fd; filesystems without that distinction
        no-op."""
        raise NotImplementedError

    def durable_rename(self, src: str, dst: str) -> None:
        """Crash-consistent publish: fsync the file, atomically rename it,
        then fsync the destination's parent directory — the full
        fsync-before-rename + dir-fsync discipline, so after this returns
        the published file survives kill -9 AND power loss.  One default
        composition over the three primitives; wrappers that intercept
        sync/rename (fault injection) inherit the decomposed ops.

        Retry-safe for the SAME (src, dst) pair: unlike a bare rename, this
        can fail AFTER the rename landed (the trailing dir fsync), so a
        retried call finds src gone and dst present — it resumes at the
        pending dir fsync instead of raising ENOENT on the fsync of a file
        that was already published."""
        if self.exists(src):
            self.sync(src)
            self.rename(src, dst)
        elif not self.exists(dst):
            raise FileNotFoundError(src)
        self.sync_dir(dst.rsplit("/", 1)[0] if "/" in dst else ".")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        raise NotImplementedError


def publish_file(fs: FileSystem, src: str, dst: str,
                 durable: bool = True) -> None:
    """THE publish decision point (ISSUE 12 capability seam): every
    publish path — thread worker, process-mode child, compactor merge,
    compactor write-ahead plan — calls this, so the protocol choice
    cannot drift between them.

    * ``fs.supports_rename`` (posix/HDFS/memory): the historical
      tmp→rename protocol — ``durable_rename`` (fsync + rename + dir
      fsync) when ``durable``, plain atomic ``rename`` otherwise.
    * object-store sinks (``supports_rename = False``): multipart
      ``publish_commit`` — visibility flips when the store completes the
      staged upload at the destination key; there is no fsync to issue,
      so ``durable`` is moot (complete IS the durability point).

    Both branches are retry-safe for the same (src, dst) pair: the
    rename branch resumes at the pending dir fsync, the commit branch
    returns when the destination already materialized."""
    if getattr(fs, "supports_rename", True):
        if durable:
            fs.durable_rename(src, dst)
        else:
            fs.rename(src, dst)
    else:
        fs.publish_commit(src, dst)


class LocalFileSystem(FileSystem):
    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def open_write(self, path: str):
        return open(path, "wb")

    def open_append(self, path: str):
        return open(path, "ab")

    def open_read(self, path: str):
        return open(path, "rb")

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def sync(self, path: str) -> None:
        # O_RDONLY is enough to fsync file DATA on linux; no O_RDWR needed
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def sync_dir(self, path: str) -> None:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        os.remove(path)

    def size(self, path: str) -> int:
        return os.path.getsize(path)

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        out = []
        if not os.path.isdir(path):
            return out
        if recursive:
            for root, _dirs, files in os.walk(path):
                for f in files:
                    out.append(os.path.join(root, f))
        else:
            out = [os.path.join(path, f) for f in os.listdir(path)
                   if os.path.isfile(os.path.join(path, f))]
        if extension is not None:
            out = [f for f in out if f.endswith(extension)]
        return sorted(out)


class _MemFile(io.BytesIO):
    """BytesIO that publishes its contents to the store on close.  In
    append mode the buffer is seeded with the existing contents and the
    whole value republishes atomically under the store lock."""

    def __init__(self, fs: "MemoryFileSystem", path: str,
                 append: bool = False) -> None:
        super().__init__()
        self._fs = fs
        self._path = path
        if append:
            existing = fs._store_get(path)
            if existing:
                self.write(existing)

    def close(self) -> None:
        self._fs._store_put(self._path, self.getvalue())
        super().close()


class MemoryFileSystem(FileSystem):
    """In-memory FS with directory semantics and atomic rename."""

    def __init__(self) -> None:
        self._files: dict[str, bytes] = {}
        self._dirs: set[str] = {"/"}
        self._lock = threading.RLock()

    @staticmethod
    def _norm(path: str) -> str:
        out = os.path.normpath("/" + path.lstrip("/"))
        return out

    def _store_put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._files[self._norm(path)] = data

    def _store_get(self, path: str) -> bytes:
        with self._lock:
            return self._files.get(self._norm(path), b"")

    def mkdirs(self, path: str) -> None:
        with self._lock:
            p = self._norm(path)
            while p not in self._dirs:
                self._dirs.add(p)
                p = os.path.dirname(p)

    def open_write(self, path: str):
        return _MemFile(self, path)

    def open_append(self, path: str):
        return _MemFile(self, path, append=True)

    def open_read(self, path: str):
        with self._lock:
            return io.BytesIO(self._files[self._norm(path)])

    def rename(self, src: str, dst: str) -> None:
        with self._lock:
            s, d = self._norm(src), self._norm(dst)
            if s not in self._files:
                raise FileNotFoundError(src)
            if os.path.dirname(d) not in self._dirs:
                raise FileNotFoundError(f"parent dir missing: {dst}")
            self._files[d] = self._files.pop(s)

    def sync(self, path: str) -> None:
        # the store IS stable storage here; still raise on a missing file so
        # durability bugs (sync before close, wrong path) surface in tests
        with self._lock:
            if self._norm(path) not in self._files:
                raise FileNotFoundError(path)

    def sync_dir(self, path: str) -> None:
        with self._lock:
            p = self._norm(path)
            if p not in self._dirs:
                raise FileNotFoundError(path)

    def exists(self, path: str) -> bool:
        with self._lock:
            p = self._norm(path)
            return p in self._files or p in self._dirs

    def delete(self, path: str) -> None:
        with self._lock:
            p = self._norm(path)
            if p not in self._files:
                raise FileNotFoundError(path)  # match LocalFileSystem
            del self._files[p]

    def size(self, path: str) -> int:
        with self._lock:
            return len(self._files[self._norm(path)])

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        with self._lock:
            prefix = self._norm(path).rstrip("/") + "/"
            out = []
            for p in self._files:
                if not p.startswith(prefix):
                    continue
                rest = p[len(prefix):]
                if not recursive and "/" in rest:
                    continue
                if extension is not None and not p.endswith(extension):
                    continue
                out.append(p)
            return sorted(out)
