"""HDFS sink behind the pluggable FileSystem interface.

The reference writes through Hadoop's ``FileSystem`` API resolved from the
mandatory ``fs.defaultFS`` (KafkaProtoParquetWriter.java:137-141) and
publishes files with an atomic ``rename`` (KPW.java:371-375).  Here the same
capability rides pyarrow's libhdfs binding
(``pyarrow.fs.HadoopFileSystem``), adapted to the seven-method
``kpw_tpu.io.fs.FileSystem`` surface the writer runtime uses — so

    Builder().filesystem(HdfsFileSystem(host="namenode", port=8020))

targets a real cluster, while tests keep the in-memory stand-in
(``MemoryFileSystem``), mirroring the reference's MiniDFSCluster strategy
(SURVEY.md §4).  HDFS rename has the same atomicity contract the publish
protocol needs.  Connecting requires libhdfs + a Hadoop install
(CLASSPATH); constructing without them raises with guidance instead of
failing at first write.
"""

from __future__ import annotations

import posixpath

from .fs import FileSystem


class HdfsFileSystem(FileSystem):
    def __init__(self, host: str = "default", port: int = 8020,
                 user: str | None = None, replication: int | None = None,
                 **kwargs) -> None:
        try:
            from pyarrow.fs import HadoopFileSystem
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "HdfsFileSystem needs pyarrow with HDFS support") from e
        extra = dict(kwargs)
        if replication is not None:
            extra["replication"] = replication
        from pyarrow.fs import FileSelector, FileType

        self._FileType = FileType
        self._FileSelector = FileSelector
        try:
            self._fs = HadoopFileSystem(host, port, user=user, **extra)
        except Exception as e:  # libhdfs/CLASSPATH missing
            raise RuntimeError(
                "could not connect to HDFS — libhdfs and a Hadoop client "
                "install (CLASSPATH from `hadoop classpath --glob`) are "
                f"required: {e}") from e

    def mkdirs(self, path: str) -> None:
        self._fs.create_dir(path, recursive=True)

    def open_write(self, path: str):
        return self._fs.open_output_stream(path)

    def open_append(self, path: str):
        return self._fs.open_append_stream(path)

    def open_read(self, path: str):
        # random-access reader: Local/Memory open_read are seekable, and
        # parquet read-back (footer-first) requires seeks
        return self._fs.open_input_file(path)

    def rename(self, src: str, dst: str) -> None:
        self._fs.move(src, dst)  # HDFS NameNode rename: atomic

    def sync(self, path: str) -> None:
        # HDFS close() already waits for the write pipeline's replica acks
        # (the durability POSIX fsync provides locally); there is no
        # path-level fsync in the libhdfs surface, so sync is a no-op — but
        # keep the Local/Memory contract of raising on a lost file
        if not self.exists(path):
            raise FileNotFoundError(path)

    def sync_dir(self, path: str) -> None:
        pass  # namespace edits are journaled by the NameNode at rename time

    def exists(self, path: str) -> bool:
        return self._fs.get_file_info(path).type != self._FileType.NotFound

    def delete(self, path: str) -> None:
        # Parity with Local/Memory FS: delete() is a *file* operation —
        # raise on a directory (never recursively wipe published output)
        # and on a missing path.
        info = self._fs.get_file_info(path)
        if info.type == self._FileType.NotFound:
            raise FileNotFoundError(path)
        if info.type == self._FileType.Directory:
            raise IsADirectoryError(path)
        self._fs.delete_file(path)

    def size(self, path: str) -> int:
        info = self._fs.get_file_info(path)
        if info.type == self._FileType.NotFound:  # match Local/Memory FS:
            raise FileNotFoundError(path)  # never report a lost file as 0 B
        return int(info.size or 0)

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        sel = self._FileSelector(path, recursive=recursive,
                                 allow_not_found=True)
        out = []
        try:
            infos = self._fs.get_file_info(sel)
        except FileNotFoundError:
            # despite allow_not_found, pyarrow can raise when the
            # directory is being CREATED concurrently (observed racing a
            # recursive create_dir) — Local/Memory parity is an empty
            # listing for a dir that isn't fully there yet
            return out
        for info in infos:
            if info.type != self._FileType.File:
                continue
            if extension is None or info.path.endswith(extension):
                out.append(posixpath.join("/", info.path)
                           if not info.path.startswith("/") else info.path)
        return sorted(out)
