"""Small-file compaction service: merge published under-size files into
~target-size files, without ever putting the at-least-once contract at risk.

Rotation × partitions × workers is the classic small-file explosion: a
partitioned streaming writer (``Builder.partition_by``) multiplies every
rotation across its live partitions, and scan cost downstream is dominated
by file/page layout, not bytes.  :class:`Compactor` is the tier behind the
writer that pays that debt back — a background service (modeled on the
``io/failover.py`` reconciler loop) that repeatedly:

1. **Scans** closed published ``.parquet`` files per directory (per
   partition in a partitioned layout; the flat root works too), excluding
   the writer's working subtrees (``tmp/``, ``quarantine/``,
   ``compacted/``, ``deadletter/``).
2. **Plans** merges: files under ``small_file_ratio * target_size`` are
   binned, in name order (time order under the writer's naming scheme),
   into groups of ``>= min_files`` whose sum approaches ``target_size``.
3. **Rewrites** each group through the existing encode machinery
   (pyarrow read-back -> protobuf messages -> ``runtime.ParquetFile``
   encode) into one merged tmp under ``{target_dir}/tmp/``.
4. **Verifies** the merged tmp with the independent structural verifier
   (``io/verify.py``) — including an exact row-count match against the
   inputs — BEFORE any publish.  A tmp that fails is quarantined (moved,
   never deleted) and the inputs are left untouched.
5. **Publishes** via ``durable_rename`` and only THEN **retires** the
   inputs — moved into the ``{target_dir}/compacted/`` tombstone tree
   (never deleted in place), so a ``kill -9`` at any instant leaves every
   row in at least one verified published file.

Crash consistency rides a tiny write-ahead plan: before the publish, the
group's manifest (inputs, output, rows) is durably written under
``{target_dir}/compacted/.plans/``; :meth:`recover` (run at service start
and before every round) rolls a surviving plan forward (output verified ->
finish retiring the inputs, so a duplicate-published final never outlives
the next startup) or back (output missing/torn -> quarantine the torn
output, restore any already-retired inputs from their tombstones, drop the
plan).  The merged-tmp sweep only touches THIS instance's
``{instance}_compact_*.tmp`` names, mirroring the writer's scoped tmp GC.

Meters (canonical, ``runtime/metrics.py``): ``parquet.compactor.merged``
(merge outputs published), ``parquet.compactor.retired`` (inputs
tombstoned), ``parquet.compactor.failed`` (verify failures + aborted merge
attempts).  :meth:`compactor_stats` is surfaced as
``writer.stats()["compactor"]`` when ``Builder.compaction`` is configured.
"""

from __future__ import annotations

import json
import logging
import random
import re
import threading
import time

from .fs import FileSystem, publish_file
from .verify import verify_file

logger = logging.getLogger(__name__)

# subtrees never scanned for merge inputs: the writer's working dirs plus
# this service's own tombstone tree
EXCLUDE_DIRS = ("tmp", "quarantine", "compacted", "deadletter")
_PLANS_SUBDIR = "compacted/.plans"


def _parent(path: str) -> str:
    return path.rsplit("/", 1)[0] if "/" in path else "."


def _basename(path: str) -> str:
    return path.rsplit("/", 1)[-1]


class MergeGroup:
    """One planned merge: ``inputs`` (>= min_files published small files,
    name order) in directory ``dir``, ``rows``/``bytes`` summed from their
    verified footers."""

    __slots__ = ("dir", "inputs", "rows", "bytes")

    def __init__(self, dir: str, inputs: list[str], rows: int,
                 nbytes: int) -> None:
        self.dir = dir
        self.inputs = inputs
        self.rows = rows
        self.bytes = nbytes


class Compactor:
    """Background small-file compaction over one writer target directory.

    Parameters
    ----------
    fs, target_dir:
        The writer's sink filesystem and target directory.
    proto_class, properties:
        The writer's message class and ``WriterProperties`` — the rewrite
        runs through the exact same encode machinery as the writer (CPU
        encoder; compaction is a background tier, not the hot path).
    target_size:
        Merged files aim at this many bytes (default 128 MiB).
    small_file_ratio:
        A published file below ``small_file_ratio * target_size`` is a
        merge candidate (default 0.5 — an already-compacted output near
        the target never re-enters the plan).
    min_files:
        Never merge fewer than this many inputs (default 2; a lone small
        file stays as is — merging it would rewrite bytes for nothing).
    scan_interval_s:
        Background loop cadence (``start()``); ``compact_once()`` is the
        synchronous single-round entry tests and benches drive.
    registry:
        Optional ``MetricRegistry`` for the canonical compactor meters.
    instance_name:
        Scopes this service's tmp names and the stale-tmp sweep.
    sort_by:
        Sort-on-compact: ``None`` preserves input row order (name-order
        concatenation); a field name — or ``(field_name, descending)`` —
        physically re-sorts every merged output by that proto field and
        declares it as ``sorting_columns`` row-group metadata
        (core/metadata.py), so compaction is where streaming output
        acquires the sort order selective readers exploit.  Null field
        values sort last.  The merged tmp must then pass the structural
        verifier's sort-vs-page-index consistency check AND declare every
        row group sorted before it publishes — a buggy sort can never
        reach readers.
    bandwidth_bytes_per_s / request_budget_per_round / partition_quota:
        The REMOTE tier (object-store targets, where compaction traffic
        shares the fleet's network and every request is billed):
        ``bandwidth_bytes_per_s`` throttles merge READS and merge-output
        WRITES through one shared token bucket
        (``io/objectstore.py`` :class:`BandwidthBudget` — observed
        throughput stays <= budget); ``request_budget_per_round`` defers
        further merge groups once a round has issued that many
        filesystem requests; ``partition_quota`` caps merge groups
        executed per partition directory per round so one hot partition
        cannot monopolize the round.  All None by default (local tier:
        no throttling, no accounting wrapper on the hot path).
    """

    def __init__(self, fs: FileSystem, target_dir: str, proto_class,
                 properties, *, target_size: int = 128 * 1024 * 1024,
                 small_file_ratio: float = 0.5, min_files: int = 2,
                 scan_interval_s: float = 5.0, registry=None,
                 instance_name: str = "compactor",
                 batch_size: int = 4096,
                 sort_by=None,
                 bandwidth_bytes_per_s: float | None = None,
                 request_budget_per_round: int | None = None,
                 partition_quota: int | None = None,
                 bandwidth_budget=None) -> None:
        # runtime imports are deferred (the failover-module pattern):
        # io.compact is imported during kpw_tpu.io package init, while
        # kpw_tpu.runtime may still be mid-initialization
        from ..models.proto_bridge import ProtoColumnarizer
        from ..runtime import metrics as M

        if min_files < 2:
            raise ValueError("min_files must be >= 2")
        if not 0.0 < small_file_ratio <= 1.0:
            raise ValueError("small_file_ratio must be in (0, 1]")
        if target_size <= 0:
            raise ValueError("target_size must be positive")
        # sort-on-compact: the merge rewrites through writer properties
        # that DECLARE the order (core/metadata.py SortingColumn), so the
        # merged footer carries sorting_columns and the verifier's
        # boundary-order cross-check guards the publish
        self._columnarizer = ProtoColumnarizer(proto_class)
        self.sort_by: str | None = None
        self.sort_descending = False
        if sort_by is not None:
            if isinstance(sort_by, (tuple, list)):
                if not 1 <= len(sort_by) <= 2:
                    raise ValueError(
                        "sort_by tuple must be (field,) or "
                        f"(field, descending), got {sort_by!r}")
                self.sort_by = sort_by[0]
                self.sort_descending = (bool(sort_by[1])
                                        if len(sort_by) == 2 else False)
            else:
                self.sort_by = sort_by
            # fail at construction, not inside every background merge
            # round: an unknown name would otherwise raise from the
            # rewrite's ParquetFile after the tmp sink is already open,
            # and _run would log-and-retry it forever
            leaf = next((c for c in self._columnarizer.schema.columns
                         if c.name == self.sort_by
                         or ".".join(c.path) == self.sort_by), None)
            if leaf is None:
                raise ValueError(
                    f"sort_by column {self.sort_by!r} is not a schema "
                    "leaf (have "
                    f"{[c.name for c in self._columnarizer.schema.columns]})")
            if leaf.max_rep > 0:
                raise ValueError(
                    f"sort_by column {self.sort_by!r} is repeated — a "
                    "row has no single value to order by")
            # the rewrite sorts pyarrow row dicts: a nested leaf lives at
            # row[seg0][seg1]..., keyed by the declared dotted path
            self._sort_path = tuple(leaf.path)
            import dataclasses

            # write_page_index is forced ON with the declaration: the
            # verifier's declared-order-vs-page-stats cross-check only
            # exists against a ColumnIndex, and without it the
            # verify-before-publish sort gate would be vacuous
            properties = dataclasses.replace(
                properties,
                write_page_index=True,
                sorting_columns=((self.sort_by, self.sort_descending,
                                  False),))
        if bandwidth_bytes_per_s is not None and bandwidth_bytes_per_s <= 0:
            raise ValueError("bandwidth_bytes_per_s must be positive")
        if (request_budget_per_round is not None
                and request_budget_per_round < 1):
            raise ValueError("request_budget_per_round must be >= 1")
        if partition_quota is not None and partition_quota < 1:
            raise ValueError("partition_quota must be >= 1")
        self.bandwidth_bytes_per_s = bandwidth_bytes_per_s
        self.request_budget_per_round = request_budget_per_round
        self.partition_quota = partition_quota
        self._budget = None
        if (bandwidth_budget is not None
                or bandwidth_bytes_per_s is not None
                or request_budget_per_round):
            # remote tier: wrap the sink in the byte-throttling +
            # request-counting composite (reads and writes draw from ONE
            # token bucket, so total traffic stays under the budget).
            # ``bandwidth_budget`` is a caller-owned BandwidthBudget —
            # the multi-tenant compaction service passes ONE bucket to
            # every route's compactor so the merged background traffic
            # shares a single cap instead of multiplying per tenant.
            from .objectstore import (BandwidthBudget,
                                      BandwidthBudgetedFileSystem)

            if bandwidth_budget is not None:
                self._budget = bandwidth_budget
                # surface the SHARED bucket's rate in compactor_stats'
                # remote block (this compactor draws from it even though
                # no per-compactor rate was configured)
                self.bandwidth_bytes_per_s = bandwidth_budget.rate
            elif bandwidth_bytes_per_s is not None:
                self._budget = BandwidthBudget(bandwidth_bytes_per_s)
            fs = BandwidthBudgetedFileSystem(fs, self._budget)
        self.fs = fs
        self.target_dir = target_dir.rstrip("/")
        self.proto_class = proto_class
        self.properties = properties
        self.target_size = target_size
        self.small_file_ratio = small_file_ratio
        self.min_files = min_files
        self.scan_interval_s = scan_interval_s
        self.instance_name = instance_name
        self.batch_size = batch_size
        self._merged_meter = (registry.meter(M.COMPACTOR_MERGED_METER)
                              if registry else M.Meter())
        self._retired_meter = (registry.meter(M.COMPACTOR_RETIRED_METER)
                               if registry else M.Meter())
        self._failed_meter = (registry.meter(M.COMPACTOR_FAILED_METER)
                              if registry else M.Meter())
        # counters guarded by _mu; NO filesystem op ever runs under it
        # (lock-discipline: fs calls block, and the lint/lockcheck gates
        # reject blocking ops under a held kpw_tpu lock)
        self._mu = threading.Lock()
        self._rounds = 0
        self._bytes_rewritten = 0
        self._rows_rewritten = 0
        self._recovered_forward = 0
        self._recovered_rollback = 0
        self._last_round: dict = {}
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Start the background scan loop (recover() first, then one
        round per ``scan_interval_s``)."""
        if self._thread is not None:
            raise ValueError("compactor already started")
        self._thread = threading.Thread(
            target=self._loop, name=f"KPW-compactor-{self.instance_name}",
            daemon=True)
        self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the loop.  A round in flight finishes its current group
        (the plan protocol makes any interruption recoverable anyway)."""
        self._closed.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def _loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.recover()
                self.compact_once()
            except Exception:
                logger.exception("compactor round failed (will retry)")
            if self._closed.wait(self.scan_interval_s):
                return

    # -- scan + plan ---------------------------------------------------------
    def _excluded(self) -> tuple:
        return tuple(f"{self.target_dir}/{d}/" for d in EXCLUDE_DIRS)

    def scan(self) -> dict[str, list[tuple[str, int]]]:
        """Published small files grouped by directory: ``{dir: [(path,
        size), ...]}``, name-sorted, working subtrees excluded."""
        threshold = int(self.target_size * self.small_file_ratio)
        skips = self._excluded()
        groups: dict[str, list[tuple[str, int]]] = {}
        for p in self.fs.list_files(self.target_dir, extension=".parquet",
                                    recursive=True):
            if any(p.startswith(s) for s in skips):
                continue
            try:
                size = self.fs.size(p)
            except OSError:
                continue  # racing a concurrent rename/quarantine
            if size >= threshold:
                continue
            groups.setdefault(_parent(p), []).append((p, size))
        for files in groups.values():
            files.sort()
        return groups

    def plan(self) -> list[MergeGroup]:
        """Greedy name-order bin pack of each directory's small files into
        merge groups: a group closes when adding the next file would cross
        ``1.25 * target_size``; groups under ``min_files`` are dropped —
        BEFORE any verification, so the steady-state leftovers (a lone
        small file per partition) cost zero re-read per round.  Members of
        viable groups are then structurally verified; an unverifiable
        input is skipped (left for the writer's quarantine machinery,
        which owns condemnation), never merged, and a group that shrinks
        below ``min_files`` is dropped."""
        out: list[MergeGroup] = []
        for d, files in sorted(self.scan().items()):
            raw: list[list[tuple[str, int]]] = [[]]
            cur_bytes = 0
            for path, size in files:
                if raw[-1] and cur_bytes + size > self.target_size * 1.25:
                    raw.append([])
                    cur_bytes = 0
                raw[-1].append((path, size))
                cur_bytes += size
            for grp in raw:
                if len(grp) < self.min_files:
                    continue
                inputs: list[str] = []
                rows = nbytes = 0
                for path, size in grp:
                    rep = verify_file(self.fs, path)
                    if not rep.ok or rep.num_rows is None:
                        logger.warning(
                            "compactor: input %s failed structural "
                            "verification (%s); skipping it (never merged,"
                            " never touched)", path, rep.errors[:2])
                        continue
                    inputs.append(path)
                    rows += rep.num_rows
                    nbytes += size
                if len(inputs) >= self.min_files:
                    out.append(MergeGroup(d, inputs, rows, nbytes))
        return out

    # -- execute -------------------------------------------------------------
    def compact_once(self) -> dict:
        """One synchronous planning + merge round.  Returns a summary dict
        (also kept as ``compactor_stats()['last_round']``).  An OSError
        mid-round aborts the remaining groups — the sink is sick, and the
        next round (after ``recover()``) resumes where the plans left
        off."""
        groups = self.plan()
        summary = {"planned_groups": len(groups), "merged": 0, "retired": 0,
                   "failed": 0, "rows": 0, "bytes_in": 0,
                   "deferred_quota": 0, "deferred_requests": 0}
        req0 = (self.fs.requests_total()
                if hasattr(self.fs, "requests_total") else 0)
        per_dir: dict[str, int] = {}
        for g in groups:
            if self._closed.is_set():
                break
            # remote-tier gates: per-partition quota (one hot partition
            # must not monopolize the round) and the per-round request
            # budget (deferred groups re-plan next round — the inputs
            # are untouched, so deferral is always safe)
            if (self.partition_quota is not None
                    and per_dir.get(g.dir, 0) >= self.partition_quota):
                summary["deferred_quota"] += 1
                continue
            if (self.request_budget_per_round is not None
                    and (self.fs.requests_total() - req0
                         >= self.request_budget_per_round)):
                summary["deferred_requests"] += 1
                continue
            per_dir[g.dir] = per_dir.get(g.dir, 0) + 1
            try:
                retired = self._execute(g)
                if retired is None:
                    summary["failed"] += 1
                else:
                    summary["merged"] += 1
                    summary["retired"] += retired
                    summary["rows"] += g.rows
                    summary["bytes_in"] += g.bytes
            except OSError as e:
                self._failed_meter.mark()
                summary["failed"] += 1
                logger.warning("compactor: merge round aborted on %r; "
                               "plans recover next round", e)
                break
        if hasattr(self.fs, "requests_total"):
            summary["requests_used"] = self.fs.requests_total() - req0
        with self._mu:
            self._rounds += 1
            self._last_round = dict(summary)
        return summary

    def _execute(self, g: MergeGroup):
        """Merge one group.  Order is the correctness protocol: rewrite ->
        verify tmp -> durable plan -> durable publish -> retire inputs ->
        drop plan.  Returns the number of inputs retired (the merge
        PUBLISHED; a shortfall keeps the plan for recover()), or None when
        the merged tmp failed verification (tmp quarantined, inputs
        untouched, nothing published)."""
        from ..utils.tracing import stage

        tmp = (f"{self.target_dir}/tmp/"
               f"{self.instance_name}_compact_{random.getrandbits(63)}.tmp")
        self.fs.mkdirs(f"{self.target_dir}/tmp")
        with stage("compactor.merge"):
            rows = self._rewrite(g.inputs, tmp)
        rep = verify_file(self.fs, tmp)
        # sort-on-compact publishes only outputs whose EVERY row group
        # both declares the order and survives the verifier's
        # boundary-order cross-check (a silent sort bug must quarantine,
        # not publish)
        unsorted = (self.sort_by is not None
                    and rep.sorted_row_groups != rep.row_groups)
        if not rep.ok or rep.num_rows != g.rows or rows != g.rows \
                or unsorted:
            self._failed_meter.mark()
            qpath = self._quarantine(tmp)
            logger.error(
                "compactor: merged tmp for %s failed verification "
                "(rows %s/%s vs %s expected, sorted_rgs %s/%s, errors %s);"
                " quarantined to %s, inputs untouched", g.dir,
                rep.num_rows, rows, g.rows, rep.sorted_row_groups,
                rep.row_groups, rep.errors[:3], qpath)
            return None
        dest = self._output_path(g)
        # tombstone destinations are fixed HERE and recorded in the plan:
        # retire and crash-rollback must agree on where each input went.
        # The plan also records the merged TMP: on an object-store target
        # that is the staging key of an uncompleted multipart upload, and
        # recovery must be able to abort it deterministically
        pairs = [(p, self._tombstone_path(p)) for p in g.inputs]
        self._write_plan(dest, g, pairs, tmp)
        # the one publish decision point (io/fs.py): durable_rename on
        # rename-capable sinks, multipart-complete on object stores
        publish_file(self.fs, tmp, dest)
        self._merged_meter.mark()
        retired = self._retire(pairs)
        if retired == len(pairs):
            self._drop_plan(dest)
        else:
            # a partially-retired group keeps its plan: recover() owns
            # finishing the retire, and dropping the plan here would make
            # the remaining duplicate-published inputs permanent
            logger.warning("compactor: plan for %s kept (retire "
                           "incomplete; recover() finishes it)", dest)
        with self._mu:
            self._bytes_rewritten += g.bytes
            self._rows_rewritten += g.rows
        logger.info("compactor: merged %d file(s) (%d rows) -> %s; %d/%d "
                    "inputs retired to compacted/", len(g.inputs), g.rows,
                    dest, retired, len(pairs))
        return retired

    def _rewrite(self, inputs: list[str], tmp_path: str) -> int:
        """Read every input row (pyarrow read-back — the reader dep lives
        here, off the writer hot path) and re-encode the union through the
        writer's own machinery into ``tmp_path``.  With ``sort_by`` the
        union is materialized and sorted by the field first (nulls last) —
        the group is bounded by ``target_size``, so the sort buffer is
        too.  Returns rows written."""
        import pyarrow.parquet as pq

        from ..runtime.parquet_file import ParquetFile

        pf = ParquetFile(self.fs, tmp_path, self._columnarizer,
                         self.properties, batch_size=self.batch_size)
        rows = 0
        try:
            if self.sort_by is not None:
                union: list[dict] = []
                for path in inputs:
                    with self.fs.open_read(path) as f:
                        union.extend(pq.read_table(f).to_pylist())

                # pyarrow rows are NESTED dicts: a dotted sort leaf lives
                # at row[seg0][seg1]... (r.get("a.b") is always None).
                # NaN keys bucket with the nulls: list.sort with NaN keys
                # leaves non-NaN elements arbitrarily ordered (every
                # comparison is False), which would publish-attempt an
                # unsorted-but-declared output the verify gate quarantines
                # on every re-planned round — and page-stat min/max mask
                # NaNs anyway, so "last, with the nulls" is the one
                # ordering the declaration can actually be checked against
                def sort_value(r):
                    for seg in self._sort_path:
                        if not isinstance(r, dict):
                            return None
                        r = r.get(seg)
                    if isinstance(r, float) and r != r:
                        return None
                    return r

                present = [r for r in union if sort_value(r) is not None]
                absent = [r for r in union if sort_value(r) is None]
                present.sort(key=sort_value,
                             reverse=self.sort_descending)
                for row in present + absent:  # nulls last
                    pf.append_record(row_to_message(self.proto_class, row))
                    pf.flush_if_full()
                    rows += 1
            else:
                for path in inputs:
                    with self.fs.open_read(path) as f:
                        table = pq.read_table(f)
                    msgs = [row_to_message(self.proto_class, row)
                            for row in table.to_pylist()]
                    pf.append_records(msgs)
                    pf.flush_if_full()
                    rows += len(msgs)
            pf.close()
        except Exception:
            # free the sink on any failure; the torn tmp is swept by
            # recover()'s scoped tmp GC (never published: no rename ran)
            pf.abandon()
            raise
        return rows

    def _output_path(self, g: MergeGroup) -> str:
        """Merged destination in the group's own directory, named from the
        FIRST input (time order preserved for readers sorting by name)
        with a ``compacted`` tag; collisions get a numeric suffix.  An
        input that is itself a previous merge output contributes its BARE
        stem — re-merging under ongoing ingest must not grow
        ``-compacted-compacted-…`` names without bound (a long-running
        service would eventually hit the filesystem name limit)."""
        stem = _basename(g.inputs[0])
        stem = stem[:-len(".parquet")] if stem.endswith(".parquet") else stem
        stem = re.sub(r"(?:-compacted(?:-\d+)?)+$", "", stem)
        dest = f"{g.dir}/{stem}-compacted.parquet"
        seq = 0
        while self.fs.exists(dest):
            seq += 1
            dest = f"{g.dir}/{stem}-compacted-{seq}.parquet"
        return dest

    def _retire(self, pairs: list[tuple[str, str]]) -> int:
        """Tombstone every input under ``{target_dir}/compacted/`` —
        renamed, NEVER deleted (retired bytes are evidence and the crash
        rollback's restore source).  The relative directory layout is
        preserved so a tombstone is traceable to its partition.  Returns
        how many inputs were retired."""
        retired = 0
        for path, dest in pairs:
            try:
                self.fs.mkdirs(_parent(dest))
                self.fs.rename(path, dest)
                self._retired_meter.mark()
                retired += 1
            except OSError as e:
                # the plan survives until every input is retired; the
                # next recover() finishes the job
                logger.warning("compactor: could not retire %s (%r); "
                               "recover() will finish it", path, e)
        return retired

    def _tombstone_path(self, path: str) -> str:
        rel = path[len(self.target_dir) + 1:] if path.startswith(
            self.target_dir + "/") else _basename(path)
        dest = f"{self.target_dir}/compacted/{rel}"
        seq = 0
        while self.fs.exists(dest):
            seq += 1
            dest = f"{self.target_dir}/compacted/{rel}.{seq}"
        return dest

    def _quarantine(self, path: str) -> str:
        qdir = f"{self.target_dir}/quarantine"
        self.fs.mkdirs(qdir)
        dest = f"{qdir}/{_basename(path)}"
        seq = 0
        while self.fs.exists(dest):
            seq += 1
            dest = f"{qdir}/{_basename(path)}.{seq}"
        self.fs.rename(path, dest)
        return dest

    # -- write-ahead plan ----------------------------------------------------
    def _plans_dir(self) -> str:
        return f"{self.target_dir}/{_PLANS_SUBDIR}"

    def _plan_path(self, dest: str) -> str:
        # one plan per output, keyed by the output's TARGET-RELATIVE path
        # (flattened): two partitions routinely produce outputs with the
        # same basename, and colliding plan names would let one group's
        # cleanup delete another group's still-needed plan
        rel = (dest[len(self.target_dir) + 1:]
               if dest.startswith(self.target_dir + "/")
               else _basename(dest))
        return f"{self._plans_dir()}/{rel.replace('/', '__')}.plan.json"

    def _write_plan(self, dest: str, g: MergeGroup,
                    pairs: list[tuple[str, str]],
                    merge_tmp: str | None = None) -> None:
        """Durably record the merge BEFORE its publish: a crash after the
        publish can then always finish retiring the inputs instead of
        leaving duplicate-published finals forever.  ``merge_tmp`` (the
        staged merge output) rides along so a crash BETWEEN parts and
        complete on an object-store target resolves deterministically:
        rollback aborts exactly the upload the plan names."""
        self.fs.mkdirs(self._plans_dir())
        path = self._plan_path(dest)
        tmp = f"{path}.tmp"
        with self.fs.open_write(tmp) as f:
            f.write(json.dumps({
                "output": dest,
                "inputs": [{"path": p, "tombstone": t} for p, t in pairs],
                "rows": g.rows,
                "tmp": merge_tmp,
                "instance": self.instance_name,
            }).encode())
        publish_file(self.fs, tmp, path)

    def _drop_plan(self, dest: str) -> None:
        try:
            self.fs.delete(self._plan_path(dest))
        except OSError:
            logger.warning("compactor: plan for %s not deletable; "
                           "recover() re-resolves it (idempotent)", dest)

    def recover(self) -> dict:
        """Resolve every surviving write-ahead plan, then sweep this
        instance's stale merged tmps.  Forward: the output exists and
        verifies -> finish retiring its inputs (a duplicate-published
        final must not outlive recovery).  Rollback: the output is
        missing or torn -> quarantine a torn output, restore any
        already-retired inputs from their tombstones, drop the plan —
        every row stays in at least one verified published file
        throughout."""
        out = {"plans": 0, "rolled_forward": 0, "rolled_back": 0,
               "tmp_swept": 0}
        try:
            plans = self.fs.list_files(self._plans_dir(),
                                       extension=".plan.json",
                                       recursive=False)
        except OSError:
            plans = []
        for ppath in plans:
            out["plans"] += 1
            try:
                with self.fs.open_read(ppath) as f:
                    plan = json.loads(f.read().decode())
            except (OSError, KeyError, ValueError) as e:
                logger.error("compactor: unreadable plan %s (%r); leaving "
                             "it for inspection", ppath, e)
                continue
            forward, resolved = self._resolve_plan(plan)
            if forward:
                out["rolled_forward"] += 1
            else:
                out["rolled_back"] += 1
            if not resolved:
                # a retire/restore rename failed: the plan must survive
                # — dropping it now would make the half-state permanent
                # (a duplicate-published final, or rows visible only
                # under compacted/); the next round retries
                logger.warning("compactor: plan %s only partially "
                               "resolved; kept for the next round", ppath)
                continue
            try:
                self.fs.delete(ppath)
            except OSError:
                logger.warning("compactor: resolved plan %s not deletable",
                               ppath)
        out["tmp_swept"] = self._sweep_tmps()
        if out["plans"] or out["tmp_swept"]:
            with self._mu:
                self._recovered_forward += out["rolled_forward"]
                self._recovered_rollback += out["rolled_back"]
            logger.info("compactor recover: %s", out)
        return out

    def _resolve_plan(self, plan: dict) -> tuple[bool, bool]:
        """(rolled_forward, fully_resolved).  ``fully_resolved`` False
        means a retire/restore rename failed and the plan must be KEPT so
        the next round retries — idempotent in both directions (the
        quarantine of a torn output happens at most once; remaining
        retires/restores are re-derived from what still exists).

        The multipart crash window (object-store targets): a crash
        BETWEEN parts and complete leaves the plan, no output, and an
        orphaned multipart upload at the plan's recorded ``tmp`` key —
        rolled BACK deterministically (the upload is aborted via the
        fs delete seam, the inputs were never retired); a crash AFTER
        complete rolls FORWARD exactly like the rename protocol (the
        output verifies, retiring finishes).  Aborted-or-completed, from
        the write-ahead plan alone."""
        output = plan["output"]
        if self.fs.exists(output) and verify_file(self.fs, output).ok:
            pending = [(inp["path"], inp["tombstone"])
                       for inp in plan["inputs"]
                       if self.fs.exists(inp["path"])]
            return True, self._retire(pending) == len(pending)
        merge_tmp = plan.get("tmp")
        if merge_tmp and self.fs.exists(merge_tmp):
            # the staged merge output the publish never completed: on an
            # object store this ABORTS the orphaned multipart upload (and
            # on a posix sink it sweeps the torn tmp) — the inputs are
            # still published, so dropping the stage loses nothing
            try:
                self.fs.delete(merge_tmp)
                logger.info("compactor: aborted orphaned merge stage %s "
                            "from its write-ahead plan", merge_tmp)
            except OSError as e:
                logger.warning("compactor: could not abort orphaned merge "
                               "stage %s (%r); the scoped tmp sweep "
                               "retries", merge_tmp, e)
        if self.fs.exists(output):
            # torn publish: condemned, never deleted
            self._failed_meter.mark()
            qpath = self._quarantine(output)
            logger.error("compactor: planned output %s failed verification"
                         " after a crash; quarantined to %s", output, qpath)
        resolved = True
        for inp in plan["inputs"]:
            # restore retired inputs: their rows are no longer covered by
            # a published output
            if not self.fs.exists(inp["path"]) and self.fs.exists(
                    inp["tombstone"]):
                try:
                    self.fs.rename(inp["tombstone"], inp["path"])
                except OSError as e:
                    resolved = False
                    logger.error("compactor: could not restore %s from its "
                                 "tombstone (%r); plan kept, retried next "
                                 "round", inp["path"], e)
        return False, resolved

    def _sweep_tmps(self) -> int:
        """Remove THIS instance's abandoned merged tmps (the scoped
        pattern the writer's own GC uses: other instances sharing the
        directory keep their live files)."""
        pat = re.compile(re.escape(self.instance_name) + r"_compact_\d+\.tmp$")
        try:
            stale = [p for p in self.fs.list_files(
                f"{self.target_dir}/tmp", extension=".tmp", recursive=True)
                if pat.fullmatch(_basename(p))]
        except OSError:
            return 0
        swept = 0
        for p in stale:
            try:
                self.fs.delete(p)
                swept += 1
            except OSError:
                logger.warning("compactor: could not sweep stale tmp %s", p)
        return swept

    # -- observability -------------------------------------------------------
    def compactor_stats(self) -> dict:
        remote = None
        if (self.bandwidth_bytes_per_s is not None
                or self.request_budget_per_round is not None
                or self.partition_quota is not None):
            remote = {
                "bandwidth_bytes_per_s": self.bandwidth_bytes_per_s,
                "request_budget_per_round": self.request_budget_per_round,
                "partition_quota": self.partition_quota,
                "requests_total": (self.fs.requests_total()
                                   if hasattr(self.fs, "requests_total")
                                   else None),
            }
            if self._budget is not None:
                remote["budget"] = self._budget.observed()
        with self._mu:
            return {
                "remote": remote,
                "running": (self._thread is not None
                            and self._thread.is_alive()),
                "target_size": self.target_size,
                "small_file_threshold": int(self.target_size
                                            * self.small_file_ratio),
                "min_files": self.min_files,
                "sort_by": self.sort_by,
                "sort_descending": self.sort_descending,
                "scan_interval_s": self.scan_interval_s,
                "rounds": self._rounds,
                "merged": self._merged_meter.count,
                "retired": self._retired_meter.count,
                "failed": self._failed_meter.count,
                "bytes_rewritten": self._bytes_rewritten,
                "rows_rewritten": self._rows_rewritten,
                "recovered_forward": self._recovered_forward,
                "recovered_rollback": self._recovered_rollback,
                "last_round": dict(self._last_round),
            }


def row_to_message(cls, row: dict):
    """Reconstruct one protobuf message from a pyarrow row dict (the
    read-back half of the rewrite): nested message fields recurse,
    repeated fields extend, absent/None fields stay unset."""
    msg = cls()
    _fill_message(msg, row)
    return msg


def _is_repeated(fd) -> bool:
    # protobuf >= 5.27 deprecates FieldDescriptor.label for is_repeated
    rep = getattr(fd, "is_repeated", None)
    if rep is None:
        return fd.label == fd.LABEL_REPEATED
    return bool(rep() if callable(rep) else rep)


def _fill_message(msg, row: dict) -> None:
    for fd in msg.DESCRIPTOR.fields:
        if fd.name not in row:
            continue
        v = row[fd.name]
        if v is None:
            continue
        if _is_repeated(fd):
            if fd.type == fd.TYPE_MESSAGE:
                for item in v:
                    _fill_message(getattr(msg, fd.name).add(), item or {})
            else:
                getattr(msg, fd.name).extend(v)
        elif fd.type == fd.TYPE_MESSAGE:
            if isinstance(v, dict):
                sub = getattr(msg, fd.name)
                # presence must survive the rewrite: a set-but-empty
                # submessage reads back as a dict of Nones, and recursing
                # without marking presence would re-encode it as ABSENT —
                # compaction silently changing data
                sub.SetInParent()
                _fill_message(sub, v)
        else:
            setattr(msg, fd.name, v)
