"""Spillover failover filesystem: keep publishing while the primary is down.

The retry layer heals *transient* primary failures and the fatal-errno
classification turns *persistent* ones (disk full, read-only remount) into
worker deaths — but death is the wrong answer when a perfectly good local
disk is sitting right there.  :class:`FailoverFileSystem` is a
primary/fallback composite over any two :class:`~kpw_tpu.io.fs.FileSystem`
implementations:

* **Healthy**: every operation routes to the primary; the fallback is idle.
* **Degrade**: a fatal-classified errno from a primary mutating op (or an
  explicit :meth:`declare_primary_down` — the hung-IO watchdog's verdict)
  flips the composite into degraded mode.  The failing creation op is
  transparently redone on the fallback, so the calling worker never sees
  the fatal error; publishes (tmp→rename) now land on the fallback and are
  recorded as *spilled*.
* **Reconcile**: a background reconciler probes the primary on an interval;
  once a probe write succeeds, every spilled final is migrated back —
  verified with the independent structural verifier (``kpw_tpu.io.verify``)
  FIRST, copied, then published on the primary via ``durable_rename``
  semantics (tmp copy → fsync → atomic rename → dir fsync).  A spill that
  fails verification is quarantined on the fallback (moved, NEVER deleted
  — the PR-4 rule); a migration IO failure is metered and retried on the
  next probe round.  When nothing spilled remains, the composite flips
  back to the primary.

The at-least-once contract is preserved throughout: an ack only ever
follows a successful (possibly spilled) publish, and reconciliation moves
bytes that were already durable on the fallback — it deletes a fallback
copy only after the primary copy is durably renamed into place.

Meters (registered when a ``MetricRegistry`` is supplied, always counted):
``parquet.writer.spilled`` (finals published onto the fallback),
``parquet.writer.reconciled`` (spills migrated back to the primary),
``parquet.writer.reconcile.failed`` (verify failures → quarantine, and
migration IO errors → retried).  :meth:`failover_stats` returns the full
pull-based snapshot; ``writer.stats()["failover"]`` surfaces it when the
writer's filesystem is this composite.
"""

from __future__ import annotations

import logging
import threading
import time

from .fs import FileSystem

logger = logging.getLogger(__name__)

_PROBE_NAME = ".kpw_failover_probe"


def _parent(path: str) -> str:
    return path.rsplit("/", 1)[0] if "/" in path else "."


class _PrimaryWriteObserver:
    """Thin wrapper over a primary-opened write handle: a fatal errno from
    ``write``/``flush``/``close`` flips the composite into degraded mode
    *before* re-raising — the bytes already written to this handle cannot
    be replayed here (the caller's retry/supervision/pause layer owns
    that), but the NEXT open must route to the fallback immediately."""

    def __init__(self, fs: "FailoverFileSystem", inner) -> None:
        self._fs = fs
        self._inner = inner

    def _guard(self, fn, *args):
        try:
            return fn(*args)
        except OSError as e:
            if self._fs._is_fatal(e):
                self._fs._degrade(f"primary {fn.__name__} failed: {e!r}")
            raise

    def write(self, data):
        return self._guard(self._inner.write, data)

    def writelines(self, parts):
        return self._guard(self._inner.writelines, parts)

    def flush(self):
        return self._guard(self._inner.flush)

    def close(self):
        return self._guard(self._inner.close)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __getattr__(self, name):  # seek/tell/... pass through
        return getattr(self._inner, name)


# lint: protocol-exhaustiveness ok — rename-based by contract: the
# constructor REJECTS rename-less sides (supports_rename False raises
# ValueError below), so the inherited supports_rename=True /
# publish_commit TypeError defaults are correct for every constructible
# instance; the spill/reconcile protocol itself is rename-based
class FailoverFileSystem(FileSystem):
    """Primary/fallback composite with background reconciliation.

    Parameters
    ----------
    primary, fallback:
        Any two FileSystems.  The fallback is typically a local spill
        directory standing in for the HDFS/remote primary.
    probe_interval_s:
        How often the reconciler probes a downed primary.
    registry:
        Optional ``MetricRegistry``; the spill/reconcile meters register
        under their canonical names when given.
    fatal_errnos:
        Which errnos flip failover (default: the retry layer's
        ``FATAL_ERRNOS`` — ENOSPC/EROFS/EDQUOT).
    probe_dir:
        Directory on the primary the recovery probe writes into; defaults
        to the first directory ``mkdirs`` is asked for (the writer's tmp
        dir), so zero-config wiring through ``Builder.filesystem`` works.
    """

    def __init__(self, primary: FileSystem, fallback: FileSystem, *,
                 probe_interval_s: float = 1.0, registry=None,
                 fatal_errnos=None, probe_dir: str | None = None) -> None:
        from ..runtime import metrics as M
        from ..runtime.retry import FATAL_ERRNOS

        # capability guard: the spill/reconcile protocol is built on the
        # RENAME publish discipline (durable_rename onto the fallback,
        # salvage renames, migrate-then-rename reconciliation).  A
        # rename-less side (an object-store adapter) would silently fall
        # back to non-atomic copy+delete mid-protocol — reject loudly at
        # construction instead of drifting at the first degraded publish
        for side, fs in (("primary", primary), ("fallback", fallback)):
            if not getattr(fs, "supports_rename", True):
                raise ValueError(
                    f"FailoverFileSystem requires rename-capable "
                    f"filesystems; the {side} is a rename-less "
                    f"(object-store) sink — the failover tier does not "
                    f"support the multipart publish protocol yet")
        self.primary = primary
        self.fallback = fallback
        self.probe_interval_s = probe_interval_s
        self._fatal_errnos = frozenset(
            fatal_errnos if fatal_errnos is not None else FATAL_ERRNOS)
        self._probe_dir = probe_dir
        self._degraded = threading.Event()
        self._lock = threading.Lock()
        self._cause: str | None = None
        self._degraded_since: float | None = None
        self._failover_count = 0
        self._recovered_count = 0
        self._spilled: list[str] = []       # fallback finals awaiting migration
        self._quarantined: list[dict] = []  # spills that failed verification
        self._spill_sources: list[str] = []  # primary tmps a spilled rename
        # could not remove (best-effort cleanup once the primary heals)
        self._spilled_meter = (registry.meter(M.SPILLED_METER)
                               if registry else M.Meter())
        self._reconciled_meter = (registry.meter(M.RECONCILED_METER)
                                  if registry else M.Meter())
        self._reconcile_failed_meter = (
            registry.meter(M.RECONCILE_FAILED_METER)
            if registry else M.Meter())
        self._closed = threading.Event()
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, name="KPW-failover-reconciler",
            daemon=True)
        self._reconciler.start()

    # -- state -------------------------------------------------------------
    def degraded(self) -> bool:
        return self._degraded.is_set()

    def declare_primary_down(self, reason: str) -> None:
        """External verdict (the hung-IO watchdog, an operator) that the
        primary is unusable even though it never returned an errno."""
        self._degrade(f"declared down: {reason}")

    def _is_fatal(self, e: OSError) -> bool:
        return e.errno in self._fatal_errnos

    def _degrade(self, cause: str) -> None:
        with self._lock:
            if self._degraded.is_set():
                return
            self._cause = cause
            self._degraded_since = time.monotonic()
            self._failover_count += 1
            self._degraded.set()
        logger.error("failover: primary filesystem degraded (%s); "
                     "publishes spill to the fallback", cause)

    def _recover(self) -> None:
        with self._lock:
            if not self._degraded.is_set():
                return
            self._recovered_count += 1
            self._cause = None
            self._degraded_since = None
            self._degraded.clear()
        logger.warning("failover: primary recovered and every spill "
                       "reconciled; routing back to the primary")

    def failover_stats(self) -> dict:
        with self._lock:
            since = self._degraded_since
            return {
                "degraded": self._degraded.is_set(),
                "cause": self._cause,
                "degraded_age_s": (round(time.monotonic() - since, 3)
                                   if since is not None else 0.0),
                "failovers": self._failover_count,
                "recoveries": self._recovered_count,
                "spilled": self._spilled_meter.count,
                "spilled_pending": list(self._spilled),
                "reconciled": self._reconciled_meter.count,
                "reconcile_failed": self._reconcile_failed_meter.count,
                "quarantined_spills": [dict(q) for q in self._quarantined],
            }

    def close(self, timeout: float = 5.0) -> None:
        """Stop the reconciler thread.  Spills still pending stay on the
        fallback (durable, verified-before-migration on the next run).
        Routing state is untouched: closing a healthy composite must not
        make it look degraded."""
        self._closed.set()
        if self._reconciler.is_alive():
            self._reconciler.join(timeout=timeout)

    # -- routed operations ---------------------------------------------------
    def mkdirs(self, path: str) -> None:
        if self._probe_dir is None:
            # first dir the writer asks for (its tmp dir): a known-writable
            # location on the primary for the recovery probe
            self._probe_dir = path
        if self._degraded.is_set():
            self.fallback.mkdirs(path)
            return
        try:
            self.primary.mkdirs(path)
        except OSError as e:
            if not self._is_fatal(e):
                raise
            self._degrade(f"primary mkdirs failed: {e!r}")
            self.fallback.mkdirs(path)

    def open_write(self, path: str):
        if self._degraded.is_set():
            return self.fallback.open_write(path)
        try:
            return _PrimaryWriteObserver(self, self.primary.open_write(path))
        except OSError as e:
            if not self._is_fatal(e):
                raise
            self._degrade(f"primary open_write failed: {e!r}")
            return self.fallback.open_write(path)

    def open_append(self, path: str):
        if self._degraded.is_set():
            return self.fallback.open_append(path)
        try:
            return _PrimaryWriteObserver(self, self.primary.open_append(path))
        except OSError as e:
            if not self._is_fatal(e):
                raise
            self._degrade(f"primary open_append failed: {e!r}")
            return self.fallback.open_append(path)

    def open_read(self, path: str):
        first, second = self._route_order()
        try:
            return first.open_read(path)
        except (OSError, KeyError):
            return second.open_read(path)

    def rename(self, src: str, dst: str) -> None:
        if not self._degraded.is_set():
            try:
                # the bring-home check only matters after a degraded
                # window has existed — guarding on the failover count
                # keeps the never-degraded hot path at parity with a
                # plain filesystem (no extra stat RPC per publish)
                if (self._failover_count > 0
                        and not self.primary.exists(src)
                        and self.fallback.exists(src)):
                    # src was written during a degraded window and is
                    # publishing AFTER recovery: bring it home first, then
                    # publish on the primary directly — no spill, no
                    # reconciliation debt
                    self.primary.mkdirs(_parent(src))
                    _copy_file(self.fallback, src, self.primary, src)
                    try:
                        self.fallback.delete(src)
                    except OSError:
                        pass  # duplicate tmp on the fallback, never wrong
                self.primary.rename(src, dst)
                return
            except OSError as e:
                if not self._is_fatal(e):
                    raise
                self._degrade(f"primary rename failed: {e!r}")
        # degraded: the publish must land on the fallback.  The tmp may
        # live on the PRIMARY (degradation flipped mid-publish): salvage
        # by copying it over — a full disk usually still reads fine — then
        # rename on the fallback.
        if not self.fallback.exists(src) and self.primary.exists(src):
            self.fallback.mkdirs(_parent(src))
            _copy_file(self.primary, src, self.fallback, src)
            try:
                self.primary.delete(src)
            except OSError:
                with self._lock:
                    self._spill_sources.append(src)
        self.fallback.mkdirs(_parent(dst))
        self.fallback.rename(src, dst)
        if "/quarantine/" not in dst and not dst.endswith(".tmp"):
            # a rename onto the fallback outside tmp/quarantine is a
            # spilled PUBLISH: the reconciler owes it to the primary
            with self._lock:
                self._spilled.append(dst)
            self._spilled_meter.mark()
            logger.warning("failover: published %s on the FALLBACK "
                           "(spill #%d)", dst, self._spilled_meter.count)

    def sync(self, path: str) -> None:
        fs = self._fs_holding(path)
        try:
            fs.sync(path)
        except OSError as e:
            # an fsync leg cannot be transparently redone (the bytes live
            # on the failing side), but a fatal errno must still flip the
            # route so the caller's NEXT attempt spills
            if fs is self.primary and self._is_fatal(e):
                self._degrade(f"primary sync failed: {e!r}")
            raise

    def sync_dir(self, path: str) -> None:
        if self._degraded.is_set():
            self.fallback.sync_dir(path)
            return
        try:
            self.primary.sync_dir(path)
        except OSError as e:
            if self._is_fatal(e):
                self._degrade(f"primary sync_dir failed: {e!r}")
            raise

    def exists(self, path: str) -> bool:
        # routed side first; the NON-routed (possibly sick) side is
        # consulted second and tolerated if it raises — while degraded, a
        # dead primary whose stat calls error must not take down publish
        # bookkeeping (the collision probe, durable_rename's src check)
        first, second = self._route_order()
        if first.exists(path):
            return True
        try:
            return second.exists(path)
        except OSError:
            return False

    def delete(self, path: str) -> None:
        self._fs_holding(path).delete(path)

    def size(self, path: str) -> int:
        return self._fs_holding(path).size(path)

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        out = set()
        for fs in (self.primary, self.fallback):
            try:
                out.update(fs.list_files(path, extension=extension,
                                         recursive=recursive))
            except OSError:
                continue  # a sick side contributes nothing, not an error
        return sorted(out)

    def _route_order(self) -> tuple[FileSystem, FileSystem]:
        if self._degraded.is_set():
            return self.fallback, self.primary
        return self.primary, self.fallback

    def _fs_holding(self, path: str) -> FileSystem:
        first, second = self._route_order()
        if first.exists(path):
            return first
        try:
            if second.exists(path):
                return second
        except OSError:
            pass  # sick non-routed side holds nothing we can use
        return first  # let the routed side raise its native not-found

    # -- reconciliation ------------------------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._closed.is_set():
            # bounded wait, NOT a bare event hijackable by close(): the
            # loop notices either a degrade or a close within one tick
            if not self._degraded.wait(timeout=0.2):
                continue
            if self._closed.wait(self.probe_interval_s):
                return
            try:
                if not self._probe_primary():
                    continue
                if self._reconcile_round():
                    self._recover()
            except Exception:
                logger.exception("failover reconciler round failed "
                                 "(will retry)")

    def _probe_primary(self) -> bool:
        """One write-path probe against the primary: mkdirs + create +
        write + close + delete.  Only a full round trip counts as healthy
        — a read-only remount happily lists files."""
        d = self._probe_dir
        if d is None:
            return False  # nothing was ever written; nowhere safe to probe
        path = f"{d}/{_PROBE_NAME}"
        try:
            self.primary.mkdirs(d)
            with self.primary.open_write(path) as f:
                f.write(b"kpw failover probe")
            self.primary.delete(path)
            return True
        except OSError:
            return False

    def reconcile_now(self) -> bool:
        """Synchronous probe + reconcile round (deterministic tests, an
        operator forcing the issue).  Returns True when the primary is
        healthy and no spilled final remains."""
        if not self._probe_primary():
            return False
        if self._reconcile_round():
            self._recover()
            return True
        return False

    def _reconcile_round(self) -> bool:
        """Migrate every spilled final fallback → primary.  Returns True
        when the spill list drained (quarantined entries excluded — they
        are out of the published set by design)."""
        from .verify import verify_file

        with self._lock:
            pending = list(self._spilled)
        for path in pending:
            if self._closed.is_set():
                return False
            if not self.fallback.exists(path):
                self._drop_spilled(path)  # already migrated (racing round)
                continue
            rep = verify_file(self.fallback, path)
            if not rep.ok:
                # verification failed: quarantine ON the fallback — moved,
                # never deleted (the PR-4 rule: unverified data is
                # evidence, not garbage) — and out of the migration set
                qpath = self._quarantine_spill(path, rep.errors[:3])
                self._reconcile_failed_meter.mark()
                self._drop_spilled(path)
                logger.error("failover: spilled file %s failed structural "
                             "verification; quarantined to %s (NOT "
                             "migrated, NOT deleted)", path, qpath)
                continue
            try:
                self._migrate(path)
            except OSError as e:
                # primary sickened again mid-migration: meter, keep the
                # spill, abort the round — the probe loop will retry
                self._reconcile_failed_meter.mark()
                logger.warning("failover: migration of %s failed (%r); "
                               "will retry next probe round", path, e)
                return False
            self._drop_spilled(path)
            self._reconciled_meter.mark()
            logger.info("failover: reconciled %s back to the primary", path)
        self._cleanup_spill_sources()
        with self._lock:
            return not self._spilled

    def _migrate(self, path: str) -> None:
        """Copy one verified spill to the primary and publish it there
        with durable_rename semantics; delete the fallback copy only after
        the primary copy is durably in place."""
        tmp = f"{path}.reconcile.tmp"
        self.primary.mkdirs(_parent(path))
        _copy_file(self.fallback, path, self.primary, tmp)
        self.primary.durable_rename(tmp, path)
        try:
            self.fallback.delete(path)
        except OSError:
            logger.warning("failover: fallback copy of %s not deletable; "
                           "left in place (duplicate, never wrong)", path)

    def _quarantine_spill(self, path: str, errors) -> str:
        qdir = f"{_parent(path)}/quarantine"
        self.fallback.mkdirs(qdir)
        name = path.rsplit("/", 1)[-1]
        dest = f"{qdir}/{name}"
        seq = 0
        while self.fallback.exists(dest):
            seq += 1
            dest = f"{qdir}/{name}.{seq}"
        self.fallback.rename(path, dest)
        with self._lock:
            self._quarantined.append({"path": path, "quarantined_to": dest,
                                      "errors": list(errors)})
        return dest

    def _drop_spilled(self, path: str) -> None:
        with self._lock:
            try:
                self._spilled.remove(path)
            except ValueError:
                pass

    def _cleanup_spill_sources(self) -> None:
        """Best-effort removal of primary-side tmps a mid-publish salvage
        copy left behind (their contents were republished via the
        fallback, so they are plain duplicates)."""
        with self._lock:
            sources = list(self._spill_sources)
        for src in sources:
            try:
                if self.primary.exists(src):
                    self.primary.delete(src)
            except OSError:
                continue
            with self._lock:
                try:
                    self._spill_sources.remove(src)
                except ValueError:
                    pass


def _copy_file(src_fs: FileSystem, src: str, dst_fs: FileSystem,
               dst: str) -> None:
    with src_fs.open_read(src) as fin:
        data = fin.read()
    with dst_fs.open_write(dst) as fout:
        fout.write(data)
