"""Object-store tier: emulated S3/GCS-class store + multipart FileSystem
adapter with upload-hidden-under-encode pipelining.

Production fleets publish to object stores, not local disks, and object
stores have none of the primitives the posix publish protocol leans on:
no atomic rename, no fsync, no append — what they have instead is
*multipart upload* (create → upload parts → complete), whose ``complete``
is the atomic visibility point, plus per-request costs and throttling
("Towards an Arrow-native Storage System", PAPERS.md).  This module makes
that target real enough to prove the writer's contracts against:

* :class:`EmulatedObjectStore` — an in-process store with buckets,
  objects, multipart create/upload-part/complete/abort, list-with-prefix,
  request + byte accounting, configurable per-request latency, and an
  optional fault schedule consulted per request (op names
  ``objstore.put|get|head|delete|copy|list|create_multipart|upload_part|
  complete|abort`` — the 503/throttle/slow-part/complete-fails persona of
  ``io/faults.py`` fires here).
* :class:`ObjectStoreFileSystem` — a :class:`~kpw_tpu.io.fs.FileSystem`
  adapter whose "atomic publish" is **multipart-complete instead of
  rename** (``supports_rename = False`` + :meth:`publish_commit`, routed
  through the single ``io/fs.py`` ``publish_file`` decision point shared
  by the worker and the compactor).  ``open_write`` streams full parts to
  a background part-uploader **while the file is still open** (the
  ``upload.part`` stage; the same overlap trick as ``--hostasm``), so on
  close only the tail part remains and the publish is one ``complete``
  call.  Generic ``rename`` (compactor retire/tombstone/quarantine) is
  server-side copy + delete — it works, it is just not atomic and costs
  two requests, which the accounting makes visible.
* :class:`BandwidthBudget` / :class:`BandwidthBudgetedFileSystem` — a
  token-bucket bytes/s budget shared across reads and writes plus
  request-count accounting, the Compactor's remote tier
  (``Builder.compaction(bandwidth_bytes_per_s=...)``).

Emulator relaxations vs real S3, both documented where they matter: (1)
``complete_multipart`` accepts the final key at *complete* time (S3 fixes
it at create; a real adapter names the upload at file-open from the same
publish-name pattern — the protocol is otherwise identical), and (2) a
sealed-but-uncompleted upload's bytes can be read back
(:meth:`EmulatedObjectStore.pending_part_bytes`) so verify-before-publish
works; a production adapter verifies its local staging buffer instead.
The write handle retains the file bytes until it seals (the seek-back
retry protocol of ``core/writer.py`` can rewind into already-shipped
parts, which are then re-uploaded under the same part number — last
upload of a part number wins, exactly S3's semantics).  Retention is
spill-bounded: past ``spill_threshold_bytes`` the retained bytes roll
to an anonymous local tmp file (:class:`_RetainedBuffer`) so handle
memory stays bounded at GiB-rotation scale, released at seal.
"""

from __future__ import annotations

import io
import logging
import os
import queue
import threading
import time
from collections import deque

from ..utils import schedcheck
from ..utils.tracing import stage
from .fs import FileSystem

logger = logging.getLogger(__name__)


class _Upload:
    """One in-progress multipart upload, server side."""

    __slots__ = ("upload_id", "bucket", "key", "parts")

    def __init__(self, upload_id: str, bucket: str, key: str) -> None:
        self.upload_id = upload_id
        self.bucket = bucket
        self.key = key
        self.parts: dict[int, bytes] = {}  # part number (1-based) -> bytes


class EmulatedObjectStore:
    """In-process S3/GCS-class object store.

    Parameters
    ----------
    latency_s:
        Simulated per-request latency (every request sleeps this long
        before touching store state) — the knob that makes the network
        leg cost real time in benchmarks.
    min_part_size:
        Multipart parts below this size are rejected at ``complete``
        unless they are the last part (S3's 5 MiB rule; 0 disables).
    schedule:
        Optional fault schedule (duck-typed ``check(op)`` — an
        ``io/faults.py`` ``FaultSchedule``) consulted once per request
        under op names ``objstore.<op>``; a raising rule models a 503 /
        throttle response, a delay rule a slow part.
    """

    def __init__(self, *, latency_s: float = 0.0, min_part_size: int = 0,
                 schedule=None) -> None:
        self.latency_s = latency_s
        self.min_part_size = min_part_size
        self._schedule = schedule
        self._lk = threading.Lock()
        self._buckets: set[str] = set()
        self._objects: dict[tuple[str, str], bytes] = {}
        self._uploads: dict[str, _Upload] = {}
        self._next_id = 0
        # accounting: per-op request counts, bytes in/out of the store,
        # multipart part/abort/complete tallies, and a rolling byte window
        # for the observed-bandwidth gauge
        self._requests: dict[str, int] = {}
        self._bytes_in = 0
        self._bytes_out = 0
        self._parts_uploaded = 0
        self._aborted = 0
        self._completed = 0
        self._recent: deque = deque()  # (monotonic t, nbytes)
        self._observers: list = []

    # -- plumbing ------------------------------------------------------------
    def add_observer(self, fn) -> None:
        """``fn(op, nbytes)`` called after every request (outside the
        store lock) — the adapter's canonical-meter feed."""
        with self._lk:
            self._observers.append(fn)

    def _request(self, op: str, nbytes: int = 0,
                 inbound: bool = True) -> None:
        """One store request: fault schedule first (a covered ordinal
        raises/stalls exactly like a server 503/slow response), then the
        simulated latency, then the accounting.  A faulted request
        mutates nothing — callers account before they mutate."""
        if self._schedule is not None:
            self._schedule.check(f"objstore.{op}")
        if self.latency_s > 0.0:
            time.sleep(self.latency_s)
        now = time.monotonic()
        with self._lk:
            self._requests[op] = self._requests.get(op, 0) + 1
            if nbytes:
                if inbound:
                    self._bytes_in += nbytes
                else:
                    self._bytes_out += nbytes
                self._recent.append((now, nbytes))
                while self._recent and self._recent[0][0] < now - 30.0:
                    self._recent.popleft()
            observers = list(self._observers)
        for fn in observers:
            fn(op, nbytes)

    def _bucket_check(self, bucket: str) -> None:
        if bucket not in self._buckets:
            raise FileNotFoundError(f"no such bucket: {bucket}")

    # -- buckets + objects ---------------------------------------------------
    def create_bucket(self, name: str) -> None:
        with self._lk:
            self._buckets.add(name)

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        self._request("put", len(data))
        with self._lk:
            self._bucket_check(bucket)
            self._objects[(bucket, key)] = bytes(data)

    def get_object(self, bucket: str, key: str) -> bytes:
        with self._lk:
            self._bucket_check(bucket)
            data = self._objects.get((bucket, key))
        if data is None:
            raise FileNotFoundError(f"{bucket}/{key}")
        self._request("get", len(data), inbound=False)
        return data

    def head_object(self, bucket: str, key: str) -> int | None:
        """Object size, or None when absent (a HEAD is a billed request
        either way — existence probes cost money on a real store)."""
        self._request("head")
        with self._lk:
            data = self._objects.get((bucket, key))
            return len(data) if data is not None else None

    def delete_object(self, bucket: str, key: str) -> None:
        self._request("delete")
        with self._lk:
            if (bucket, key) not in self._objects:
                raise FileNotFoundError(f"{bucket}/{key}")
            del self._objects[(bucket, key)]

    def copy_object(self, bucket: str, src: str, dst: str) -> None:
        """Server-side copy: one request, no client byte transfer."""
        self._request("copy")
        with self._lk:
            data = self._objects.get((bucket, src))
            if data is None:
                raise FileNotFoundError(f"{bucket}/{src}")
            self._objects[(bucket, dst)] = data

    def list_objects(self, bucket: str,
                     prefix: str = "") -> list[tuple[str, int]]:
        self._request("list")
        with self._lk:
            return sorted((k, len(v)) for (b, k), v in self._objects.items()
                          if b == bucket and k.startswith(prefix))

    # -- multipart -----------------------------------------------------------
    def create_multipart(self, bucket: str, key: str) -> str:
        self._request("create_multipart")
        with self._lk:
            self._bucket_check(bucket)
            self._next_id += 1
            uid = f"mp-{self._next_id}"
            self._uploads[uid] = _Upload(uid, bucket, key)
            return uid

    def upload_part(self, upload_id: str, part_number: int,
                    data: bytes) -> None:
        """Upload (or RE-upload — last write of a part number wins, the
        S3 semantics the retry protocol leans on) one part."""
        if part_number < 1:
            raise ValueError("part numbers are 1-based")
        self._request("upload_part", len(data))
        with self._lk:
            up = self._uploads.get(upload_id)
            if up is None:
                raise FileNotFoundError(f"no such upload: {upload_id}")
            up.parts[part_number] = bytes(data)
            self._parts_uploaded += 1

    def complete_multipart(self, upload_id: str,
                           final_key: str | None = None) -> str:
        """Atomic publish: the object materializes under ``final_key``
        (default: the creation key) in one step, and the upload is gone.
        Parts must be contiguous from 1 and respect ``min_part_size``
        (except the last).  Emulator relaxation, documented in the module
        docstring: real S3 fixes the key at create."""
        self._request("complete")
        with self._lk:
            up = self._uploads.get(upload_id)
            if up is None:
                raise FileNotFoundError(f"no such upload: {upload_id}")
            nums = sorted(up.parts)
            if nums != list(range(1, len(nums) + 1)):
                raise ValueError(
                    f"multipart {upload_id}: non-contiguous parts {nums}")
            if self.min_part_size:
                for n in nums[:-1]:
                    if len(up.parts[n]) < self.min_part_size:
                        raise ValueError(
                            f"part {n} below min_part_size "
                            f"({len(up.parts[n])} < {self.min_part_size})")
            key = final_key if final_key is not None else up.key
            self._objects[(up.bucket, key)] = b"".join(
                up.parts[n] for n in nums)
            del self._uploads[upload_id]
            self._completed += 1
            return key

    def abort_multipart(self, upload_id: str) -> None:
        self._request("abort")
        with self._lk:
            if upload_id not in self._uploads:
                raise FileNotFoundError(f"no such upload: {upload_id}")
            del self._uploads[upload_id]
            self._aborted += 1

    def list_multipart_uploads(
            self, bucket: str,
            prefix: str = "") -> list[tuple[str, str, int, int]]:
        """Orphan discovery: ``(key, upload_id, n_parts, n_bytes)`` of
        every in-progress upload under the prefix."""
        self._request("list")
        with self._lk:
            return sorted(
                (u.key, u.upload_id, len(u.parts),
                 sum(len(p) for p in u.parts.values()))
                for u in self._uploads.values()
                if u.bucket == bucket and u.key.startswith(prefix))

    def upload_at(self, bucket: str, key: str) -> str | None:
        """The upload_id of an in-progress upload staged at ``key`` (no
        request accounting: recovery bookkeeping over state the adapter
        would normally hold client-side)."""
        with self._lk:
            for u in self._uploads.values():
                if u.bucket == bucket and u.key == key:
                    return u.upload_id
            return None

    def pending_part_bytes(self, upload_id: str) -> bytes:
        """Concatenated staged parts of an uncompleted upload — the
        emulator stand-in for the local staging buffer a real adapter
        verifies before publish (real S3 cannot read uncompleted parts).
        No request accounting for the same reason."""
        with self._lk:
            up = self._uploads.get(upload_id)
            if up is None:
                raise FileNotFoundError(f"no such upload: {upload_id}")
            nums = sorted(up.parts)
            return b"".join(up.parts[n] for n in nums)

    def pending_size(self, upload_id: str) -> int:
        with self._lk:
            up = self._uploads.get(upload_id)
            if up is None:
                raise FileNotFoundError(f"no such upload: {upload_id}")
            return sum(len(p) for p in up.parts.values())

    # -- accounting ----------------------------------------------------------
    def observed_bytes_per_s(self, window_s: float = 5.0) -> float:
        """Bytes moved through the store over the trailing window — the
        ``parquet.writer.objstore.bandwidth`` gauge's provider."""
        now = time.monotonic()
        with self._lk:
            total = sum(n for t, n in self._recent if t >= now - window_s)
        return total / window_s

    def stats(self) -> dict:
        with self._lk:
            return {
                "requests_by_op": dict(sorted(self._requests.items())),
                "requests_total": sum(self._requests.values()),
                "bytes_in": self._bytes_in,
                "bytes_out": self._bytes_out,
                "parts_uploaded": self._parts_uploaded,
                "multipart_completed": self._completed,
                "multipart_aborted": self._aborted,
                "multipart_pending": len(self._uploads),
                "objects": len(self._objects),
                "latency_s": self.latency_s,
            }


class _Pending:
    """One staged-but-unpublished file, adapter side: either a sealed
    small object (``single_data``) or a multipart upload whose parts are
    on the server and whose ``complete`` is deferred to the publish."""

    __slots__ = ("key", "upload_id", "n_parts", "size", "single_data",
                 "sealed", "async_s", "inflight", "failed_low", "error")

    def __init__(self, key: str) -> None:
        self.key = key
        self.upload_id: str | None = None
        self.n_parts = 0
        self.size = 0
        self.single_data: bytes | None = None
        self.sealed = False
        # upload-pipelining accounting: seconds of background part
        # uploads, in-flight background tasks, and the lowest part number
        # whose background upload failed (close re-ships from there)
        self.async_s = 0.0
        self.inflight = 0
        self.failed_low: int | None = None
        self.error: BaseException | None = None


class _RetainedBuffer:
    """The write handle's retained file bytes, spill-bounded: an
    in-memory bytearray until ``spill_threshold_bytes``, then rolled to
    an anonymous local tmp file (``tempfile.TemporaryFile`` — unlinked
    at creation, gone on process death) so the handle's memory stays
    bounded at GiB-rotation scale while seek-back rewrites into shipped
    territory and close-time re-ships stay byte-perfect (random
    ``write_at`` + ranged ``read`` work identically in both modes;
    sparse seek-ahead gaps read back as zeros either way).  ``None``
    threshold = never spill (the pre-spill behavior, byte for byte)."""

    __slots__ = ("_threshold", "_mem", "_file", "_size", "spilled",
                 "_on_spill")

    def __init__(self, threshold: int | None, on_spill=None) -> None:
        self._threshold = threshold
        self._mem: bytearray | None = bytearray()
        self._file = None
        self._size = 0
        self.spilled = False
        self._on_spill = on_spill

    def __len__(self) -> int:
        return self._size

    def _roll(self) -> None:
        import tempfile

        f = tempfile.TemporaryFile(prefix="kpw-objstore-spill-")
        f.write(bytes(self._mem))
        self._file = f
        self._mem = None
        self.spilled = True
        if self._on_spill is not None:
            self._on_spill()

    def write_at(self, pos: int, b: bytes) -> None:
        if self._file is None:
            mem = self._mem
            if pos > len(mem):  # sparse seek-ahead: zero-fill the gap
                mem.extend(b"\x00" * (pos - len(mem)))
            mem[pos:pos + len(b)] = b
            self._size = len(mem)
            if self._threshold is not None and self._size > self._threshold:
                self._roll()
        else:
            # a write past EOF leaves a hole that reads back as zeros —
            # the same sparse-gap semantics as the bytearray mode
            self._file.seek(pos)
            self._file.write(b)
            self._size = max(self._size, pos + len(b))

    def read(self, start: int, end: int) -> bytes:
        end = min(end, self._size)
        if start >= end:
            return b""
        if self._file is None:
            return bytes(self._mem[start:end])
        self._file.seek(start)
        return self._file.read(end - start)

    def to_bytes(self) -> bytes:
        return self.read(0, self._size)

    def release(self) -> None:
        """Drop the retained bytes (close the spill file / free the
        bytearray) once every byte is on the server — after seal, the
        handle can never be asked to re-ship."""
        f, self._file = self._file, None
        self._mem = bytearray()
        if f is not None:
            f.close()


class _ObjectWriteFile:
    """Write handle over the adapter: buffers the file locally, streams
    completed ``part_size`` slices to the background uploader while the
    producer keeps encoding (upload hides under encode), and seals — tail
    part uploaded, ``complete`` deferred — at close.  Supports
    ``seek``/``tell`` so the core writer's positioned retry protocol
    works: a rewind into an already-shipped part marks it dirty and close
    re-uploads it under the same part number (last write wins).

    Background upload failures never surface mid-write: the handle keeps
    the bytes, notes the lowest failed part, and close re-ships
    synchronously inside the worker's retried ``close`` seam.  The
    retained bytes are SPILL-BOUNDED (``spill_threshold_bytes`` on the
    adapter): past the threshold they live in an anonymous local tmp
    file instead of memory (:class:`_RetainedBuffer`), released once the
    handle seals."""

    def __init__(self, fs: "ObjectStoreFileSystem", path: str) -> None:
        self._fs = fs
        self._path = path
        self._data = _RetainedBuffer(fs.spill_threshold_bytes,
                                     on_spill=fs._note_spill)
        self._pos = 0
        self._clean_parts = 0  # parts 1..n uploaded and not overwritten
        self._pending = _Pending(fs._key(path))
        self._closed = False
        fs._register_pending(path, self._pending)

    # -- file protocol -------------------------------------------------------
    def write(self, data) -> int:
        b = bytes(data)
        pos = self._pos
        self._data.write_at(pos, b)
        self._pos = pos + len(b)
        if pos < self._clean_parts * self._fs.part_size:
            # rewind-overwrite into shipped territory: those parts are
            # dirty; close re-uploads them under the same part numbers
            self._clean_parts = pos // self._fs.part_size
        self._ship_full_parts()
        return len(b)

    def writelines(self, parts) -> None:
        for p in parts:
            self.write(p)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos += self._pos
        elif whence == 2:
            pos += len(self._data)
        if pos < 0:
            raise OSError("negative seek position")
        self._pos = pos
        return pos

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        pass  # durability is complete/put semantics, not flush

    def _part_bytes(self, idx: int) -> bytes:
        ps = self._fs.part_size
        return self._data.read(idx * ps, (idx + 1) * ps)

    def _ship_full_parts(self) -> None:
        """Hand every newly-completed part_size slice to the uploader
        (pipelined) or upload it inline (pipelining off — the baseline
        arm the overlap accounting compares against)."""
        fs = self._fs
        p = self._pending
        while (self._clean_parts + 1) * fs.part_size <= len(self._data):
            idx = self._clean_parts
            if p.upload_id is None:
                p.upload_id = fs.store.create_multipart(fs.bucket, p.key)
            data = self._part_bytes(idx)
            self._clean_parts = idx + 1
            if fs.pipeline_uploads:
                fs._submit_part(p, idx + 1, data)
            else:
                t0 = time.perf_counter()
                try:
                    with stage("upload.part"):
                        fs.store.upload_part(p.upload_id, idx + 1, data)
                except OSError as e:
                    # deferred like the background path: the bytes are
                    # retained, close re-ships from here
                    logger.warning("inline part upload failed (%r); close "
                                   "re-ships part %d", e, idx + 1)
                    with fs._mu:
                        p.failed_low = (idx + 1 if p.failed_low is None
                                        else min(p.failed_low, idx + 1))
                fs._note_sync_upload(time.perf_counter() - t0)

    def close(self) -> None:
        """Seal: wait out background parts, re-ship failures + the tail
        part synchronously, record the overlap accounting.  ``complete``
        is NOT called — that is the publish (``publish_commit``) or the
        materialize-on-read fallback.  Safe to retry: a raise leaves the
        handle open with all bytes retained."""
        if self._closed:
            return
        fs = self._fs
        p = self._pending
        t0 = time.perf_counter()
        total = len(self._data)
        if p.upload_id is None and total < fs.part_size:
            # small file: stage locally, publish is a single PUT
            p.single_data = self._data.to_bytes()
            p.size = total
            p.sealed = True
            self._closed = True
            self._data.release()
            fs._note_overlap(p, exposed_s=0.0)
            return
        with fs._mu:
            while p.inflight > 0:
                fs._cv.wait(timeout=0.1)
            if p.failed_low is not None:
                self._clean_parts = min(self._clean_parts, p.failed_low - 1)
                p.failed_low = None
                p.error = None
        if p.upload_id is None:
            p.upload_id = fs.store.create_multipart(fs.bucket, p.key)
        n_parts = max(1, (total + fs.part_size - 1) // fs.part_size)
        # close-time uploads (failed-part re-ships + the tail part) count
        # into upload_total_s like every other part upload — they are the
        # EXPOSED share of it; accrued in a finally so a raise that the
        # worker's close retry will resume still books the time spent
        t_up0 = time.perf_counter()
        try:
            for idx in range(self._clean_parts, n_parts):
                with stage("upload.part"):
                    fs.store.upload_part(p.upload_id, idx + 1,
                                         self._part_bytes(idx))
                self._clean_parts = idx + 1
        finally:
            fs._note_close_upload(time.perf_counter() - t_up0)
        p.n_parts = n_parts
        p.size = total
        p.sealed = True
        self._closed = True
        # every byte is on the server now: drop the retained buffer (and
        # its spill file, when the handle rolled past the threshold)
        self._data.release()
        fs._note_overlap(p, exposed_s=time.perf_counter() - t0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _AppendFile(io.BytesIO):
    """Read-modify-PUT append handle: object stores cannot append, so
    the whole object republishes at close (last writer wins — fine for
    the dead-letter files this path serves, whose frames are
    self-delimiting)."""

    def __init__(self, fs: "ObjectStoreFileSystem", path: str) -> None:
        super().__init__()
        self._fs = fs
        self._path = path
        try:
            self.write(fs.store.get_object(fs.bucket, fs._key(path)))
        except FileNotFoundError:
            pass  # lint: swallowed-exceptions ok — append-create of a
            # missing object starts empty by contract

    def close(self) -> None:
        self._fs.store.put_object(self._fs.bucket,
                                  self._fs._key(self._path),
                                  self.getvalue())
        super().close()


class ObjectStoreFileSystem(FileSystem):
    """FileSystem adapter over an :class:`EmulatedObjectStore` bucket.

    The capability seam: ``supports_rename = False`` routes every publish
    through :meth:`publish_commit` (multipart-complete / atomic PUT at
    the destination key) instead of ``durable_rename`` — see
    ``io/fs.py`` ``publish_file``, the one decision point the worker,
    process children and the compactor share.  ``sync``/``sync_dir`` are
    no-ops (durability is a property of ``complete``/``put``, there is no
    page cache to flush) and ``mkdirs`` is a no-op (there are no
    directories, only key prefixes)."""

    supports_rename = False

    def __init__(self, store: EmulatedObjectStore, bucket: str, *,
                 part_size: int = 8 * 1024 * 1024,
                 pipeline_uploads: bool = True,
                 spill_threshold_bytes: int | None = None,
                 registry=None) -> None:
        if part_size < 4096:
            raise ValueError("part_size must be >= 4096")
        if store.min_part_size and part_size < store.min_part_size:
            raise ValueError(
                f"part_size {part_size} below the store's min_part_size "
                f"{store.min_part_size}")
        if spill_threshold_bytes is not None and spill_threshold_bytes < 4096:
            raise ValueError("spill_threshold_bytes must be >= 4096")
        self.store = store
        self.bucket = bucket
        store.create_bucket(bucket)
        self.part_size = int(part_size)
        self.pipeline_uploads = bool(pipeline_uploads)
        # spill-to-disk bound for each write handle's retained buffer
        # (the PR-12 ROADMAP headroom): past this many bytes a handle's
        # retained file bytes roll to an anonymous local tmp file so
        # memory stays bounded at GiB-rotation scale.  None = retain in
        # memory (historical behavior).
        self.spill_threshold_bytes = (int(spill_threshold_bytes)
                                      if spill_threshold_bytes is not None
                                      else None)
        self._spilled_handles = 0
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._pending: dict[str, _Pending] = {}  # norm path -> staged file
        self._q: queue.Queue | None = None
        self._uploader: threading.Thread | None = None
        # overlap accounting (stats()['objectstore']['upload']): seconds
        # of part-upload work hidden under the open file vs exposed at
        # close, across sealed files
        self._hidden_s = 0.0
        self._exposed_s = 0.0
        self._upload_total_s = 0.0
        self._sync_upload_s = 0.0
        self._files_sealed = 0
        self._published_multipart = 0
        self._published_put = 0
        # canonical meters (runtime/metrics.py names); re-bound to a real
        # registry by the writer via bind_registry
        from ..runtime import metrics as M

        self._m_requests = (registry.meter(M.OBJSTORE_REQUESTS_METER)
                            if registry else M.Meter())
        self._m_bytes = (registry.meter(M.OBJSTORE_BYTES_METER)
                         if registry else M.Meter())
        self._m_parts = (registry.meter(M.OBJSTORE_PARTS_METER)
                         if registry else M.Meter())
        self._m_aborted = (registry.meter(M.OBJSTORE_ABORTED_METER)
                           if registry else M.Meter())
        # the store-request observer is attached only when a registry is
        # bound: observers are not removable, and recovery/verify flows
        # routinely build short-lived adapters over one long-lived store
        # — unconditional registration would accumulate a dead callback
        # (pinning the adapter) per construction forever
        self._observer_attached = False
        if registry is not None:
            registry.gauge(M.OBJSTORE_BANDWIDTH_GAUGE,
                           self.store.observed_bytes_per_s)
            self._attach_observer()

    def _attach_observer(self) -> None:
        if not self._observer_attached:
            self._observer_attached = True
            # lint: resource-pairing ok — observers are deliberately not
            # removable; attachment is once per adapter (gated by
            # _observer_attached) and only for registry-bound adapters
            # (the PR-12 dead-observer fix), so recovery/verify flows
            # building short-lived adapters attach nothing
            self.store.add_observer(self._on_store_request)

    def bind_registry(self, registry) -> None:
        """Re-point the canonical object-store meters + bandwidth gauge
        at a writer's registry (called from the writer constructor so
        both exporters render them with no per-metric wiring)."""
        from ..runtime import metrics as M

        self._m_requests = registry.meter(M.OBJSTORE_REQUESTS_METER)
        self._m_bytes = registry.meter(M.OBJSTORE_BYTES_METER)
        self._m_parts = registry.meter(M.OBJSTORE_PARTS_METER)
        self._m_aborted = registry.meter(M.OBJSTORE_ABORTED_METER)
        registry.gauge(M.OBJSTORE_BANDWIDTH_GAUGE,
                       self.store.observed_bytes_per_s)
        self._attach_observer()

    def _on_store_request(self, op: str, nbytes: int) -> None:
        self._m_requests.mark()
        if nbytes:
            self._m_bytes.mark(nbytes)
        if op == "upload_part":
            self._m_parts.mark()
        elif op == "abort":
            self._m_aborted.mark()

    # -- path plumbing -------------------------------------------------------
    @staticmethod
    def _norm(path: str) -> str:
        return os.path.normpath("/" + path.lstrip("/"))

    def _key(self, path: str) -> str:
        return self._norm(path).lstrip("/")

    def _register_pending(self, path: str, p: _Pending) -> None:
        with self._mu:
            self._pending[self._norm(path)] = p

    # -- background part uploader --------------------------------------------
    def _submit_part(self, p: _Pending, part_number: int,
                     data: bytes) -> None:
        self._ensure_uploader()
        with self._mu:
            p.inflight += 1
        self._q.put((p, part_number, data))

    def _ensure_uploader(self) -> None:
        # schedule-explorer edge: the concurrent-first-part spawn race
        # lives between this check and the start below — the singleton
        # probe on the spawn proves the lock closes the window
        schedcheck.point("objstore.uploader.ensure")
        with self._mu:
            if self._uploader is not None:
                return  # the loop never exits (daemon; no poison is sent)
            if self._q is None:
                self._q = queue.Queue()
            t = threading.Thread(target=self._uploader_loop,
                                 name="KPW-objstore-uploader", daemon=True)
            self._uploader = t
            # started INSIDE the lock: assign-then-start-outside let a
            # concurrent first-part submitter observe is_alive() False
            # and spawn a second loop on the same queue — two drainers
            # reorder a dirty re-upload behind its stale original
            schedcheck.note_uploader_spawn(id(self))
            t.start()

    def _uploader_loop(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            p, pn, data = task
            t0 = time.perf_counter()
            try:
                with stage("upload.part"):
                    self.store.upload_part(p.upload_id, pn, data)
            except Exception as e:
                # recorded, not raised: the handle retains the bytes and
                # close re-ships from the lowest failed part inside the
                # worker's retried close seam
                logger.warning("background part upload %d failed: %r", pn, e)
                with self._mu:
                    p.error = e
                    p.failed_low = (pn if p.failed_low is None
                                    else min(p.failed_low, pn))
                    p.inflight -= 1
                    self._cv.notify_all()
                continue
            dt = time.perf_counter() - t0
            with self._mu:
                p.async_s += dt
                p.inflight -= 1
                self._upload_total_s += dt
                self._cv.notify_all()

    def _note_spill(self) -> None:
        with self._mu:
            self._spilled_handles += 1

    def _note_sync_upload(self, seconds: float) -> None:
        with self._mu:
            self._sync_upload_s += seconds
            self._upload_total_s += seconds

    def _note_close_upload(self, seconds: float) -> None:
        with self._mu:
            self._upload_total_s += seconds

    def _note_overlap(self, p: _Pending, exposed_s: float) -> None:
        """Fold one sealed file into the overlap accounting: background
        upload seconds minus the close-time exposure are the hidden
        (overlapped-under-encode) share; inline uploads (pipelining off)
        and the close-time wait + tail part are exposed."""
        with self._mu:
            hidden = max(0.0, p.async_s - exposed_s)
            self._hidden_s += hidden
            self._exposed_s += exposed_s
            self._files_sealed += 1

    # -- FileSystem surface --------------------------------------------------
    def mkdirs(self, path: str) -> None:
        pass  # no directories, only key prefixes

    def open_write(self, path: str):
        return _ObjectWriteFile(self, path)

    def open_append(self, path: str):
        return _AppendFile(self, path)

    def open_read(self, path: str):
        n = self._norm(path)
        with self._mu:
            p = self._pending.get(n)
        if p is not None and p.sealed:
            if p.single_data is not None:
                return io.BytesIO(p.single_data)
            return io.BytesIO(self.store.pending_part_bytes(p.upload_id))
        return io.BytesIO(self.store.get_object(self.bucket, self._key(n)))

    def _publish_pending(self, p: _Pending, dst_key: str) -> None:
        if not p.sealed:
            raise ValueError(f"pending upload for {p.key} is not sealed")
        if p.single_data is not None:
            self.store.put_object(self.bucket, dst_key, p.single_data)
            with self._mu:
                self._published_put += 1
        else:
            self.store.complete_multipart(p.upload_id, final_key=dst_key)
            with self._mu:
                self._published_multipart += 1

    def publish_commit(self, src: str, dst: str) -> None:
        """Atomic publish on a store with no rename: complete the staged
        multipart upload (or PUT the staged small object) at the
        DESTINATION key — visibility flips in one store operation, the
        object-store analog of the rename protocol's atomicity.  Retry
        safe for the same (src, dst) pair: if a previous attempt already
        completed (complete is the final op), the resumed call finds the
        destination present and returns."""
        s, d = self._norm(src), self._norm(dst)
        with self._mu:
            p = self._pending.pop(s, None)
        if p is None:
            if self.store.head_object(self.bucket, self._key(d)) is not None:
                return  # resumed retry: the complete already landed
            if self.store.head_object(self.bucket, self._key(s)) is not None:
                # the tmp was materialized by a read path: degrade to
                # copy + delete (2 requests, not atomic-at-dest — logged
                # so the protocol drift is visible)
                logger.warning("publish_commit of materialized tmp %s: "
                               "copy+delete fallback", src)
                self.store.copy_object(self.bucket, self._key(s),
                                       self._key(d))
                self.store.delete_object(self.bucket, self._key(s))
                return
            raise FileNotFoundError(src)
        try:
            self._publish_pending(p, self._key(d))
        except OSError:
            with self._mu:
                self._pending[s] = p  # transient: the retried call resumes
            raise

    def rename(self, src: str, dst: str) -> None:
        """Generic move (NOT the publish protocol): a staged pending file
        materializes at the destination; a stored object is server-side
        copy + delete — two billed requests and no atomicity, which is
        exactly why ``publish_file`` routes publishes through
        :meth:`publish_commit` instead."""
        s, d = self._norm(src), self._norm(dst)
        with self._mu:
            p = self._pending.pop(s, None)
        if p is not None:
            try:
                self._publish_pending(p, self._key(d))
            except OSError:
                with self._mu:
                    self._pending[s] = p
                raise
            return
        skey = self._key(s)
        if self.store.head_object(self.bucket, skey) is None:
            raise FileNotFoundError(src)
        self.store.copy_object(self.bucket, skey, self._key(d))
        self.store.delete_object(self.bucket, skey)

    def sync(self, path: str) -> None:
        # durability is a property of complete/put — nothing to flush,
        # but a missing path still surfaces (MemoryFileSystem parity)
        if not self.exists(path):
            raise FileNotFoundError(path)

    def sync_dir(self, path: str) -> None:
        pass  # no directory entries to sync

    def exists(self, path: str) -> bool:
        n = self._norm(path)
        key = self._key(n)
        with self._mu:
            if n in self._pending:
                return True
            # staged files under the prefix make a "directory" exist too
            if any(q.startswith(n.rstrip("/") + "/") for q in self._pending):
                return True
        if self.store.upload_at(self.bucket, key) is not None:
            return True
        # ONE billed LIST answers both questions — the exact key and the
        # directory-prefix probe (a HEAD followed by a trailing LIST
        # double-billed the common NEGATIVE file probe, e.g. the publish
        # collision loop's exists(dest) on every published file)
        listing = self.store.list_objects(self.bucket, key)
        if not key:  # the bucket root exists iff anything is in it
            return bool(listing)
        for k, _sz in listing:
            if k == key or k.startswith(key.rstrip("/") + "/"):
                return True
        return False

    def delete(self, path: str) -> None:
        """Delete an object — or ABORT a staged/orphaned multipart
        upload at this key (the tmp-sweep path: a crashed writer's
        in-progress upload is discarded, metered as aborted)."""
        n = self._norm(path)
        with self._mu:
            p = self._pending.pop(n, None)
        if p is not None:
            if p.upload_id is not None:
                self.store.abort_multipart(p.upload_id)
            return
        uid = self.store.upload_at(self.bucket, self._key(n))
        if uid is not None:
            self.store.abort_multipart(uid)
            return
        self.store.delete_object(self.bucket, self._key(n))

    def size(self, path: str) -> int:
        n = self._norm(path)
        with self._mu:
            p = self._pending.get(n)
        if p is not None:
            if p.single_data is not None:
                return len(p.single_data)
            if p.upload_id is not None:
                return self.store.pending_size(p.upload_id)
            return 0
        sz = self.store.head_object(self.bucket, self._key(n))
        if sz is None:
            uid = self.store.upload_at(self.bucket, self._key(n))
            if uid is not None:
                return self.store.pending_size(uid)
            raise FileNotFoundError(path)
        return sz

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        """Objects + staged pending files + ORPHANED multipart uploads
        under the prefix — orphans must be listable or the startup tmp
        sweep could never find (and abort) a crashed writer's upload."""
        prefix_n = self._norm(path).rstrip("/") + "/"
        prefix_k = prefix_n.lstrip("/")
        names = {f"/{k}" for k, _ in
                 self.store.list_objects(self.bucket, prefix_k)}
        names.update(f"/{k}" for k, _uid, _np, _nb in
                     self.store.list_multipart_uploads(self.bucket, prefix_k))
        with self._mu:
            names.update(q for q in self._pending if q.startswith(prefix_n))
        out = []
        for name in names:
            rest = name[len(prefix_n):]
            if not recursive and "/" in rest:
                continue
            if extension is not None and not name.endswith(extension):
                continue
            out.append(name)
        return sorted(out)

    # -- observability -------------------------------------------------------
    def objectstore_stats(self) -> dict:
        """The ``stats()['objectstore']`` block: store request/byte
        accounting plus the upload-pipelining overlap breakdown."""
        with self._mu:
            hidden, exposed = self._hidden_s, self._exposed_s
            total = self._upload_total_s
            up = {
                "pipeline_uploads": self.pipeline_uploads,
                "part_size": self.part_size,
                "files_sealed": self._files_sealed,
                "staged_pending": len(self._pending),
                "published_multipart": self._published_multipart,
                "published_put": self._published_put,
                "upload_total_s": round(total, 6),
                "hidden_upload_s": round(hidden, 6),
                "exposed_upload_s": round(exposed, 6),
                "inline_upload_s": round(self._sync_upload_s, 6),
                "overlap_pct": round(
                    100.0 * hidden / (hidden + exposed), 2)
                if (hidden + exposed) > 0 else 0.0,
                "spill_threshold_bytes": self.spill_threshold_bytes,
                "spilled_handles": self._spilled_handles,
            }
        return {
            "bucket": self.bucket,
            "store": self.store.stats(),
            "upload": up,
            "observed_bytes_per_s": round(
                self.store.observed_bytes_per_s(), 1),
        }


class BandwidthBudget:
    """Token-bucket bytes/s budget, shared across every consumer that
    holds a reference — the compactor's merge READS and merge-output
    WRITES draw from one bucket, so total remote traffic stays under the
    budget no matter how it splits."""

    def __init__(self, bytes_per_s: float,
                 burst_bytes: int | None = None) -> None:
        if bytes_per_s <= 0:
            raise ValueError("bytes_per_s must be positive")
        self.rate = float(bytes_per_s)
        self.burst = int(burst_bytes if burst_bytes is not None
                         else max(64 * 1024, int(bytes_per_s / 4)))
        self._lk = threading.Lock()
        # start EMPTY: accrual is capped at burst, so total consumption
        # can never exceed rate * elapsed — observed throughput stays
        # at-or-under the budget from the first byte (a full initial
        # bucket would let a short run overshoot by the whole burst)
        self._tokens = 0.0
        self._last = time.monotonic()
        self._consumed = 0
        self._t0 = self._last
        self._wait_s = 0.0

    def acquire(self, nbytes: int) -> None:
        """Take ``nbytes`` tokens, sleeping off any deficit (a single
        oversized request runs, then pays its debt — long-run throughput
        stays <= rate with at most ``burst`` of slack)."""
        if nbytes <= 0:
            return
        with self._lk:
            now = time.monotonic()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            self._tokens -= nbytes
            wait = max(0.0, -self._tokens / self.rate)
            self._consumed += nbytes
            self._wait_s += wait
        if wait > 0.0:
            time.sleep(wait)

    def observed(self) -> dict:
        with self._lk:
            elapsed = time.monotonic() - self._t0
            return {
                "budget_bytes_per_s": self.rate,
                "burst_bytes": self.burst,
                "bytes_consumed": self._consumed,
                "elapsed_s": round(elapsed, 3),
                "observed_bytes_per_s": round(
                    self._consumed / elapsed, 1) if elapsed > 0 else 0.0,
                "throttle_wait_s": round(self._wait_s, 3),
            }


class _BudgetedFile:
    """File wrapper drawing read/write bytes from the shared budget."""

    def __init__(self, inner, budget: BandwidthBudget | None) -> None:
        self._inner = inner
        self._budget = budget

    def read(self, n: int = -1):
        data = self._inner.read(n)
        if self._budget is not None and data:
            self._budget.acquire(len(data))
        return data

    def write(self, data) -> int:
        if self._budget is not None:
            self._budget.acquire(len(data))
        return self._inner.write(data)

    def writelines(self, parts) -> None:
        parts = list(parts)
        if self._budget is not None:
            self._budget.acquire(sum(len(p) for p in parts))
        self._inner.writelines(parts)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __getattr__(self, name):  # seek/tell/flush/close/... pass through
        return getattr(self._inner, name)


class BandwidthBudgetedFileSystem(FileSystem):
    """Remote-tier wrapper: token-bucket byte throttling over every file
    read/write plus request-count accounting over every store-visible
    operation — the Compactor's bandwidth-budgeted seam
    (``Builder.compaction(bandwidth_bytes_per_s=...)``).  Forwards the
    publish capability (``supports_rename`` / ``publish_commit``) so the
    protocol decision point sees the real sink."""

    def __init__(self, inner: FileSystem,
                 budget: BandwidthBudget | None = None) -> None:
        self.inner = inner
        self.budget = budget
        self._lk = threading.Lock()
        self._requests = 0

    @property
    def supports_rename(self) -> bool:
        return getattr(self.inner, "supports_rename", True)

    def _count(self) -> None:
        with self._lk:
            self._requests += 1

    def requests_total(self) -> int:
        with self._lk:
            return self._requests

    def publish_commit(self, src: str, dst: str) -> None:
        self._count()
        self.inner.publish_commit(src, dst)

    def mkdirs(self, path: str) -> None:
        self._count()
        self.inner.mkdirs(path)

    def open_write(self, path: str):
        self._count()
        return _BudgetedFile(self.inner.open_write(path), self.budget)

    def open_append(self, path: str):
        self._count()
        return _BudgetedFile(self.inner.open_append(path), self.budget)

    def open_read(self, path: str):
        self._count()
        return _BudgetedFile(self.inner.open_read(path), self.budget)

    def rename(self, src: str, dst: str) -> None:
        self._count()
        self.inner.rename(src, dst)

    def sync(self, path: str) -> None:
        self._count()
        self.inner.sync(path)

    def sync_dir(self, path: str) -> None:
        self._count()
        self.inner.sync_dir(path)

    def exists(self, path: str) -> bool:
        self._count()
        return self.inner.exists(path)

    def delete(self, path: str) -> None:
        self._count()
        self.inner.delete(path)

    def size(self, path: str) -> int:
        self._count()
        return self.inner.size(path)

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        self._count()
        return self.inner.list_files(path, extension=extension,
                                     recursive=recursive)
