"""Deterministic fault injection for the write path.

The at-least-once contract (tmp→rename publish, ack strictly after rename —
KafkaProtoParquetWriter.java:38-62) is only worth anything if it holds while
the filesystem misbehaves.  This module makes misbehavior *reproducible*:
:class:`FaultSchedule` is a seeded, schedule-driven plan of which operation
ordinals fail (or stall), and :class:`FaultInjectingFileSystem` is a wrapper
over any :class:`~kpw_tpu.io.fs.FileSystem` that consults the plan on every
IO call.  Injection is strictly opt-in at the seam where a filesystem (or
broker) is handed to the Builder: unless a wrapper is installed there,
no write-path code ever consults a schedule, so the disabled hot-path
cost is zero (the module itself is exported from the package for
discoverability, but constructing a writer never touches it).

Operation names checked by the filesystem wrapper:

``open`` (open_write/open_append/open_read), ``write`` (write/writelines),
``flush``, ``close``, ``rename``, ``sync`` (sync/sync_dir — the legs of a
durable publish), ``delete``, ``mkdirs``, ``list``.

The broker-side counterpart (``fetch`` / ``commit`` / forced ``rebalance``)
lives in :mod:`kpw_tpu.ingest.faults` and shares the same schedule object,
so one seed drives the whole chaos run.

The OBJECT-STORE persona (``io/objectstore.py``): an
:class:`~kpw_tpu.io.objectstore.EmulatedObjectStore` constructed with a
schedule consults it once per request under op names
``objstore.put|get|head|delete|copy|list|create_multipart|upload_part|
complete|abort``.  The store-shaped failure modes compose from the same
rule builders — a 503/SlowDown throttle is ``fail_nth("objstore.
upload_part", n, err=errno.EAGAIN)`` (EAGAIN classifies retried-not-fatal
under the default RetryPolicy, exactly like a real throttle response), a
slow part is ``delay_nth``, a failed commit is ``fail_nth("objstore.
complete", ...)`` — or ready-made via :func:`objectstore_persona`.
"""

from __future__ import annotations

import errno as _errno
import random
import threading
import time

from .fs import FileSystem


class InjectedFault(OSError):
    """The injected error type: an OSError with a configurable errno, so
    retry classification sees exactly what a real failure would carry."""


class _Rule:
    __slots__ = ("op", "ordinals", "errno", "latency_s", "partial", "drop",
                 "hang", "hang_timeout_s", "heal_after", "healable",
                 "healed", "fired_count")

    def __init__(self, op: str, ordinals: set, errno: int | None,
                 latency_s: float, partial: float,
                 drop: bool = False, hang: bool = False,
                 hang_timeout_s: float | None = None,
                 heal_after: int | None = None,
                 healable: bool = False) -> None:
        self.op = op
        self.ordinals = ordinals  # 1-based call numbers this rule covers
        self.errno = errno        # None = latency-only (or drop/hang) rule
        self.latency_s = latency_s
        self.partial = partial    # fraction of a write to land before failing
        self.drop = drop          # crash window: swallow the op, no error
        self.hang = hang          # block until released (or hang_timeout_s)
        self.hang_timeout_s = hang_timeout_s
        # recover_after bookkeeping: a healable rule stops firing once it
        # fired heal_after times (None = only an explicit heal() heals it)
        self.heal_after = heal_after
        self.healable = healable
        self.healed = False
        self.fired_count = 0


class FaultSchedule:
    """Seeded plan: which call ordinals of which operations fail/stall.

    Deterministic by construction — random placement (:meth:`fail_random`)
    draws ordinals from the seeded RNG at *schedule-build* time, so the
    fired set depends only on the seed and per-op call counts, never on
    thread interleaving across different operations.  Every fired fault is
    recorded (op, ordinal, errno) for the chaos artifact.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: dict[str, list[_Rule]] = {}
        self._counts: dict[str, int] = {}
        self._fired: list[dict] = []
        self._lock = threading.Lock()
        self._active = True
        # hung ops park on this event (hang_nth); release_hangs()/stop()
        # set it, letting every parked caller proceed
        self._hang_release = threading.Event()

    # -- building ------------------------------------------------------------
    def fail_nth(self, op: str, nth: int, *, count: int = 1,
                 err: int = _errno.EIO, latency_s: float = 0.0,
                 partial: float = 0.0) -> "FaultSchedule":
        """Fail calls ``nth .. nth+count-1`` (1-based) of ``op`` with an
        :class:`InjectedFault` carrying ``err``.  ``partial`` (0..1, write
        ops only) lands that fraction of the payload before raising — a torn
        write the retry protocol must overwrite, not append after."""
        if nth < 1 or count < 1:
            raise ValueError("nth and count must be >= 1")
        ordinals = set(range(nth, nth + count))
        self._rules.setdefault(op, []).append(
            _Rule(op, ordinals, err, latency_s, partial))
        return self

    def fail_forever_from(self, op: str, nth: int, *,
                          err: int = _errno.EIO) -> "FaultSchedule":
        """Every call of ``op`` from ordinal ``nth`` on fails — the
        persistent-failure shape (dead disk) that exhausts restart budgets.
        (Encoded as a negative sentinel ordinal: ``n >= nth`` matches.)"""
        if nth < 1:
            raise ValueError("nth must be >= 1")
        self._rules.setdefault(op, []).append(
            _Rule(op, {-nth}, err, 0.0, 0.0))
        return self

    def drop_writes_from(self, nth: int) -> "FaultSchedule":
        """Crash window: every ``write`` op from ordinal ``nth`` on is
        silently SWALLOWED — the caller is told it succeeded, but nothing
        lands in the file.  This is the kill -9 / power-cut shape (bytes the
        process believed written never reached the disk) made reproducible
        in-process: the writer happily finalizes and publishes a file whose
        tail was never written, producing exactly the torn PUBLISHED state
        the recovery verifier must catch and quarantine.  Open-ended, like
        :meth:`fail_forever_from`."""
        if nth < 1:
            raise ValueError("nth must be >= 1")
        self._rules.setdefault("write", []).append(
            _Rule("write", {-nth}, None, 0.0, 0.0, drop=True))
        return self

    def hang_nth(self, op: str, nth: int, *, count: int = 1,
                 timeout_s: float | None = None) -> "FaultSchedule":
        """HANG calls ``nth .. nth+count-1`` of ``op``: the call blocks —
        it never returns and never raises — until :meth:`release_hangs`
        (or :meth:`stop`) fires, after which the operation proceeds
        normally.  This is the storage failure shape a finite ``latency``
        stall cannot model: a wedged NFS/HDFS pipeline that neither
        errors nor completes, invisible to errno-classified retry and
        curable only by a watchdog or a bounded ``close(deadline=...)``.
        ``timeout_s`` bounds the park (the op then proceeds) so tests
        can't wedge forever on a missed release."""
        if nth < 1 or count < 1:
            raise ValueError("nth and count must be >= 1")
        self._rules.setdefault(op, []).append(
            _Rule(op, set(range(nth, nth + count)), None, 0.0, 0.0,
                  hang=True, hang_timeout_s=timeout_s))
        return self

    def release_hangs(self) -> None:
        """Release every op parked (and any future op that would park) on
        a ``hang`` rule; the released operations proceed normally."""
        self._hang_release.set()

    def recover_after(self, op: str, nth: int = 1, *,
                      err: int = _errno.ENOSPC,
                      heal_after_ops: int | None = None) -> "FaultSchedule":
        """Dead-disk-that-heals: every call of ``op`` from ordinal ``nth``
        fails with ``err`` until the rule HEALS — after it has fired
        ``heal_after_ops`` times, or when :meth:`heal` is called
        (``heal_after_ops=None`` = only the explicit call heals).  Unlike
        ``fail_forever_from`` this models ENOSPC/EROFS conditions that an
        operator (or time) fixes: the disk fills, spills divert, the disk
        is cleared, and the same filesystem starts working again — the
        deterministic schedule behind pause/resume and failover
        reconciliation tests."""
        if nth < 1:
            raise ValueError("nth must be >= 1")
        if heal_after_ops is not None and heal_after_ops < 1:
            raise ValueError("heal_after_ops must be >= 1")
        self._rules.setdefault(op, []).append(
            _Rule(op, {-nth}, err, 0.0, 0.0,
                  heal_after=heal_after_ops, healable=True))
        return self

    def heal(self) -> None:
        """Heal every :meth:`recover_after` rule now: the dead disk is
        back.  Chaos/degrade runs call this at the scripted recovery
        moment; rules with ``heal_after_ops`` also heal on their own."""
        with self._lock:
            for rules in self._rules.values():
                for r in rules:
                    if r.healable:
                        r.healed = True

    def delay_nth(self, op: str, nth: int, latency_s: float,
                  count: int = 1) -> "FaultSchedule":
        """Stall (but do not fail) calls ``nth .. nth+count-1`` of ``op``."""
        if nth < 1 or count < 1:
            raise ValueError("nth and count must be >= 1")
        self._rules.setdefault(op, []).append(
            _Rule(op, set(range(nth, nth + count)), None, latency_s, 0.0))
        return self

    def fail_random(self, op: str, n_faults: int, window: int, *,
                    err: int = _errno.EIO,
                    latency_s: float = 0.0) -> "FaultSchedule":
        """Place ``n_faults`` failures uniformly (seeded RNG) over the first
        ``window`` calls of ``op`` — schedule-time draw, so the plan is
        fixed before the run starts."""
        if n_faults > window:
            raise ValueError("n_faults must be <= window")
        picked = set(self._rng.sample(range(1, window + 1), n_faults))
        self._rules.setdefault(op, []).append(
            _Rule(op, picked, err, latency_s, 0.0))
        return self

    def stop(self) -> None:
        """Disarm the schedule: no further faults fire (chaos runs call this
        to let the system drain and prove recovery).  Also releases every
        parked ``hang`` — a drained system must not hold hostages."""
        with self._lock:
            self._active = False
        self._hang_release.set()

    # -- plan/evidence --------------------------------------------------------
    def plan(self) -> list[dict]:
        """The full schedule as data (for the committed chaos artifact)."""
        out = []
        for op, rules in sorted(self._rules.items()):
            for r in rules:
                open_ended = any(o < 0 for o in r.ordinals)
                out.append({
                    "op": op,
                    "ordinals": ("open-ended" if open_ended
                                 else sorted(r.ordinals)),
                    "from": (-min(r.ordinals) if open_ended else None),
                    "errno": r.errno,
                    "latency_s": r.latency_s,
                    "partial": r.partial,
                    "drop": r.drop,
                    "hang": r.hang,
                    "heal_after_ops": r.heal_after,
                    "healable": r.healable,
                })
        return out

    def note(self, op: str, ordinal: int) -> None:
        """Record a non-error chaos event (e.g. a forced rebalance) in the
        fired log so the artifact carries the full timeline."""
        with self._lock:
            self._fired.append({"op": op, "ordinal": ordinal, "errno": None})

    def fired(self) -> list[dict]:
        with self._lock:
            return list(self._fired)

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    # -- runtime check --------------------------------------------------------
    def check(self, op: str, payload_writer=None) -> str | None:
        """Advance ``op``'s call count; stall and/or raise when a rule
        covers this ordinal.  ``payload_writer`` (write ops) is a callable
        ``fraction -> None`` that lands a torn prefix before the raise.
        Returns ``"drop"`` when a crash-window rule covers this ordinal —
        the caller must then swallow the operation (report success, write
        nothing); returns None otherwise."""
        rule = None
        with self._lock:
            n = self._counts.get(op, 0) + 1
            self._counts[op] = n
            if self._active:
                for r in self._rules.get(op, ()):
                    if r.healed:
                        continue
                    hit = (n in r.ordinals
                           or any(o < 0 and n >= -o for o in r.ordinals))
                    if hit:
                        rule = r
                        break
            if rule is not None:
                rule.fired_count += 1
                if (rule.heal_after is not None
                        and rule.fired_count >= rule.heal_after):
                    rule.healed = True  # this firing is the rule's last
            if rule is not None and (rule.errno is not None or rule.drop
                                     or rule.hang):
                entry = {"op": op, "ordinal": n, "errno": rule.errno}
                if rule.drop:
                    entry["drop"] = True
                if rule.hang:
                    entry["hang"] = True
                self._fired.append(entry)
        if rule is None:
            return None
        if rule.hang:
            # park OUTSIDE the lock: other ops (and release_hangs itself)
            # must keep flowing while this caller is wedged
            self._hang_release.wait(rule.hang_timeout_s)
            return None  # released (or timed out): the op proceeds
        if rule.latency_s > 0.0:
            time.sleep(rule.latency_s)
        if rule.drop:
            return "drop"
        if rule.errno is None:
            return None  # latency-only rule
        if rule.partial > 0.0 and payload_writer is not None:
            payload_writer(rule.partial)
        raise InjectedFault(rule.errno, f"injected fault: {op} call #{n}")


class _FaultFile:
    """File wrapper consulting the schedule on write/flush/close.  A torn
    write (``partial``) lands a prefix through the inner file before
    raising, so retry protocols are tested against garbage-on-disk, not
    just clean no-ops."""

    def __init__(self, inner, schedule: FaultSchedule) -> None:
        self._inner = inner
        self._schedule = schedule

    def write(self, data) -> int:
        def torn(fraction: float) -> None:
            self._inner.write(data[: int(len(data) * fraction)])
        if self._schedule.check("write", torn) == "drop":
            return len(data)  # crash window: lie like a lost page cache
        return self._inner.write(data)

    def writelines(self, parts) -> None:
        parts = list(parts)

        def torn(fraction: float) -> None:
            self._inner.writelines(parts[: int(len(parts) * fraction)])
        if self._schedule.check("write", torn) == "drop":
            return  # crash window: swallowed
        self._inner.writelines(parts)

    def flush(self) -> None:
        self._schedule.check("flush")
        self._inner.flush()

    def close(self) -> None:
        self._schedule.check("close")
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __getattr__(self, name):  # seek/tell/read/… pass through
        return getattr(self._inner, name)


def objectstore_persona(seed: int = 0, *, n_throttles: int = 4,
                        window: int = 200, slow_part_nth: int = 3,
                        slow_parts: int = 2, slow_s: float = 0.05,
                        complete_fail_nth: int | None = 1) -> FaultSchedule:
    """The object-store failure persona, ready-made: ``n_throttles``
    503/SlowDown responses (EAGAIN — retried, never fatal) scattered over
    the first ``window`` part uploads, ``slow_parts`` slow parts from
    ordinal ``slow_part_nth``, and (unless None) one failed
    multipart-complete at ordinal ``complete_fail_nth`` — the crash
    window between parts and complete.  Feed the returned schedule to
    ``EmulatedObjectStore(schedule=...)``; the chaos invariants re-prove
    against it mechanically (bench.py --objstore)."""
    sched = FaultSchedule(seed)
    if n_throttles:
        sched.fail_random("objstore.upload_part", n_throttles, window,
                          err=_errno.EAGAIN)
    if slow_parts:
        sched.delay_nth("objstore.upload_part", slow_part_nth, slow_s,
                        count=slow_parts)
    if complete_fail_nth is not None:
        sched.fail_nth("objstore.complete", complete_fail_nth,
                       err=_errno.EAGAIN)
    return sched


class FaultInjectingFileSystem(FileSystem):
    """Schedule-consulting wrapper over any FileSystem.  Read-only probes
    (``exists``/``size``) pass through unchecked — they are rotation/ack
    bookkeeping, and failing them tests nothing the write-path ops don't."""

    def __init__(self, inner: FileSystem, schedule: FaultSchedule) -> None:
        self.inner = inner
        self.schedule = schedule

    @property
    def supports_rename(self) -> bool:
        # capability pass-through: wrapping an object-store sink must not
        # silently flip its publish protocol back to rename
        return getattr(self.inner, "supports_rename", True)

    def publish_commit(self, src: str, dst: str) -> None:
        # the multipart publish is the rename protocol's analog: consult
        # the same op name so existing publish-fault rules translate
        self.schedule.check("rename")
        self.inner.publish_commit(src, dst)

    def __getattr__(self, name):
        # observability/extra-surface pass-through (bind_registry,
        # objectstore_stats, failover_stats, declare_primary_down, ...):
        # the writer gates those wirings on hasattr(fs, ...), and a
        # fault wrapper must not hide the inner sink's surfaces — only
        # the explicitly-defined IO ops above consult the schedule
        if name == "inner":  # uninitialized instance: no self-recursion
            raise AttributeError(name)
        return getattr(self.inner, name)

    def mkdirs(self, path: str) -> None:
        self.schedule.check("mkdirs")
        self.inner.mkdirs(path)

    def open_write(self, path: str):
        self.schedule.check("open")
        return _FaultFile(self.inner.open_write(path), self.schedule)

    def open_append(self, path: str):
        self.schedule.check("open")
        return _FaultFile(self.inner.open_append(path), self.schedule)

    def open_read(self, path: str):
        self.schedule.check("open")
        return self.inner.open_read(path)

    def rename(self, src: str, dst: str) -> None:
        self.schedule.check("rename")
        self.inner.rename(src, dst)

    def sync(self, path: str) -> None:
        self.schedule.check("sync")
        self.inner.sync(path)

    def sync_dir(self, path: str) -> None:
        self.schedule.check("sync")
        self.inner.sync_dir(path)

    # durable_rename deliberately NOT forwarded to inner: the base-class
    # composition (sync -> rename -> sync_dir) runs HERE, so each leg
    # consults the schedule — an fsync-failure rule fires inside the
    # durable publish exactly where a real fsync would fail

    def exists(self, path: str) -> bool:
        return self.inner.exists(path)

    def delete(self, path: str) -> None:
        self.schedule.check("delete")
        self.inner.delete(path)

    def size(self, path: str) -> int:
        return self.inner.size(path)

    def list_files(self, path: str, extension: str | None = None,
                   recursive: bool = True) -> list[str]:
        self.schedule.check("list")
        return self.inner.list_files(path, extension=extension,
                                     recursive=recursive)
