"""Runtime lock-order detector: the dynamic half of the correctness
tooling (the static half is ``tools/analyze``'s lock-discipline pass).

The static pass only sees syntactic nesting inside one function; the
deadlocks that actually ship cross function and module boundaries — a
worker thread holding the writer's inflight lock calls into a consumer
method that takes the buffer condition, while the fetcher does the
reverse.  This module catches that class LIVE, in the test suites that
already exercise the riskiest interleavings (chaos, degrade,
batch-ingest), without changing a single assertion there.

Three capabilities, all opt-in (``install()`` / the ``KPW_LOCKCHECK=1``
env var via the pytest fixture in tests/conftest.py):

* **Lock-order graph.**  Every ``threading.Lock/RLock/Condition``
  created by ``kpw_tpu`` code after install is instrumented: acquiring B
  while holding A records the edge A→B (with the acquiring stack, which
  still shows A's ``with`` frame).  An acquisition that would close a
  cycle raises :class:`LockOrderError` *before* blocking, carrying both
  edges' stacks — the seeded-inversion test asserts exactly that report.
* **Blocking-call guard.**  ``time.sleep`` is patched for the install
  window (and arbitrary callables can be wrapped via
  :func:`wrap_blocking`): a registered blocking call made while this
  thread holds any instrumented lock raises :class:`LockHeldBlockingError`.
  Waiting on a held Condition stays legal — the wrapper releases the
  held-bookkeeping around the real ``wait``.
* **Guarded-state probe.**  :func:`guard_mutations` wraps a dict so
  every mutation asserts a specific instrumented lock is held by the
  mutating thread — :class:`UnguardedMutationError` otherwise.  This is
  the exact shape of the PR-1 ``string_stats`` race (unlocked
  read-modify-write on a shared stats dict), pinned as a regression by
  tests/test_lockcheck.py reintroducing the original pattern.

Only locks created by modules whose ``__name__`` starts with one of the
instrumented prefixes (default: ``kpw_tpu``) are wrapped; stdlib
internals (queue.Queue's mutex, threading.Event's condition) keep real
primitives, so install() cannot destabilize the interpreter.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_SLEEP = time.sleep


class LockOrderError(RuntimeError):
    """Acquiring this lock here closes a cycle in the observed
    lock-order graph — two threads can deadlock.  The message carries
    the stack of this acquisition AND the stack that recorded the
    reverse edge."""


class LockHeldBlockingError(RuntimeError):
    """A registered blocking call (time.sleep, a wrapped broker/fs op)
    ran while the calling thread held an instrumented lock."""


class UnguardedMutationError(RuntimeError):
    """A guarded mapping was mutated without its lock held — the PR-1
    ``string_stats`` race shape."""


def _stack(skip: int = 2, limit: int = 14) -> str:
    return "".join(traceback.format_stack(sys._getframe(skip), limit=limit))


def _site(skip: int = 3) -> str:
    f = sys._getframe(skip)
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class Detector:
    """One install's shared state: the order graph, per-thread held
    stacks, and the violation log (every raise is also recorded here so
    a violation inside a worker thread — where the raise kills the
    thread, not the test — stays assertable)."""

    def __init__(self, prefixes: tuple[str, ...] = ("kpw_tpu",)) -> None:
        self.prefixes = prefixes
        # guards the graph + violation log; reentrant because _record
        # runs inside note_acquire's critical section when a cycle raises
        self._mu = _REAL_RLOCK()
        self._edges: dict[tuple[int, int], str] = {}   # (idA,idB) -> stack
        self._names: dict[int, str] = {}               # lock id -> label
        self._tls = threading.local()
        self.violations: list[BaseException] = []
        self.locks_created = 0

    # -- per-thread held list ---------------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def held_labels(self) -> list[str]:
        return [self._names.get(id(lk), "?") for lk in self._held()]

    # -- graph -------------------------------------------------------------
    def _record(self, exc: BaseException) -> BaseException:
        with self._mu:
            self.violations.append(exc)
        return exc

    def note_acquire(self, lock: "_InstrumentedBase") -> None:
        """Called BEFORE the real acquire: record edges held→lock and
        raise if any edge closes a cycle (so the report fires instead of
        the deadlock)."""
        held = self._held()
        if held:
            lid = id(lock)
            with self._mu:
                for h in held:
                    hid = id(h)
                    if hid == lid:
                        continue  # reentrant RLock
                    edge = (hid, lid)
                    if edge in self._edges:
                        continue
                    back = self._path(lid, hid)
                    if back is not None:
                        reverse_stack = self._edges.get(
                            (back[0], back[1]),
                            "<edge stack unavailable>")
                        raise self._record(LockOrderError(
                            f"lock-order cycle: acquiring "
                            f"{self._names.get(lid)} while holding "
                            f"{self._names.get(hid)}, but the reverse "
                            f"order was already observed.\n"
                            f"--- this acquisition ---\n{_stack(3)}"
                            f"--- first acquisition of the reverse edge "
                            f"({self._names.get(back[0])} -> "
                            f"{self._names.get(back[1])}) ---\n"
                            f"{reverse_stack}"))
                    self._edges[edge] = _stack(3)
        held.append(lock)

    def _path(self, src: int, dst: int):
        """First edge of a path src→…→dst in the edge graph, or None."""
        adj: dict[int, list[int]] = {}
        for (a, b) in self._edges:
            adj.setdefault(a, []).append(b)
        stack = [(src, (src,))]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == dst:
                    full = path + (nxt,)
                    return (full[0], full[1])
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + (nxt,)))
        return None

    def note_release(self, lock: "_InstrumentedBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def check_blocking(self, label: str) -> None:
        held = self._held()
        if held:
            raise self._record(LockHeldBlockingError(
                f"blocking call {label} while holding instrumented "
                f"lock(s) {self.held_labels()}\n{_stack(3)}"))

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "locks_created": self.locks_created,
                "edges": [(self._names.get(a, "?"), self._names.get(b, "?"))
                          for (a, b) in self._edges],
                "violations": [repr(v) for v in self.violations],
            }


class _InstrumentedBase:
    """Shared acquire/release bookkeeping over a real primitive."""

    def __init__(self, det: Detector, real, label: str) -> None:
        self._det = det
        self._real = real
        self._label = label
        self._owner: int | None = None
        self._count = 0
        det._names[id(self)] = label
        det.locks_created += 1

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._det.note_acquire(self)
        got = (self._real.acquire(blocking, timeout)
               if timeout != -1 else self._real.acquire(blocking))
        if not blocking and got:
            self._det.note_acquire(self)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
        elif blocking:
            self._det.note_release(self)
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
        self._real.release()
        self._det.note_release(self)

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident() and self._count > 0

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockcheck {type(self).__name__} {self._label}>"


class InstrumentedLock(_InstrumentedBase):
    pass


class InstrumentedRLock(_InstrumentedBase):
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._owner == threading.get_ident():
            # reentrant re-acquire: no ordering edge, no held push
            got = (self._real.acquire(blocking, timeout)
                   if timeout != -1 else self._real.acquire(blocking))
            if got:
                self._count += 1
            return got
        return super().acquire(blocking, timeout)

    def release(self) -> None:
        if self._count > 1:
            self._count -= 1
            self._real.release()
            return
        super().release()


class InstrumentedCondition(_InstrumentedBase):
    """Condition wrapper: ordering/held bookkeeping on the underlying
    lock; ``wait`` releases the held-bookkeeping for its duration (the
    real wait releases the real lock), so a waiter is never reported as
    holding the condition it sleeps on."""

    def __init__(self, det: Detector, label: str, lock=None) -> None:
        if isinstance(lock, _InstrumentedBase):
            lock = lock._real
        super().__init__(det, _REAL_CONDITION(lock), label)

    def wait(self, timeout: float | None = None) -> bool:
        self._det.note_release(self)
        owner, count = self._owner, self._count
        self._owner, self._count = None, 0
        try:
            return self._real.wait(timeout)
        finally:
            self._owner, self._count = owner, count
            self._det.note_acquire(self)

    def wait_for(self, predicate, timeout: float | None = None):
        self._det.note_release(self)
        owner, count = self._owner, self._count
        self._owner, self._count = None, 0
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            self._owner, self._count = owner, count
            self._det.note_acquire(self)

    def notify(self, n: int = 1) -> None:
        self._real.notify(n)

    def notify_all(self) -> None:
        self._real.notify_all()


class GuardedMapping(dict):
    """Dict whose mutations must run with ``lock`` held by the mutating
    thread (``lock`` must be an instrumented lock so ownership is
    knowable).  Reads are unrestricted — the probe targets the PR-1 race
    shape: concurrent read-modify-WRITE without the guard."""

    def __init__(self, det: Detector, lock: _InstrumentedBase,
                 *args, **kw) -> None:
        super().__init__(*args, **kw)
        self._det = det
        self._guard = lock

    def _check(self, op: str) -> None:
        if not self._guard.held_by_current_thread():
            raise self._det._record(UnguardedMutationError(
                f"GuardedMapping.{op} without holding "
                f"{self._guard._label}\n{_stack(3)}"))

    def __setitem__(self, k, v) -> None:
        self._check("__setitem__")
        super().__setitem__(k, v)

    def __delitem__(self, k) -> None:
        self._check("__delitem__")
        super().__delitem__(k)

    def update(self, *a, **kw) -> None:
        self._check("update")
        super().update(*a, **kw)

    def setdefault(self, k, default=None):
        self._check("setdefault")
        return super().setdefault(k, default)

    def pop(self, *a):
        self._check("pop")
        return super().pop(*a)

    def clear(self) -> None:
        self._check("clear")
        super().clear()


# -- install / uninstall -----------------------------------------------------

_active: Detector | None = None


def _caller_is_instrumented(det: Detector) -> bool:
    # the factory's caller's caller is the code running Lock()/RLock()/
    # Condition(); one frame probe per lock CREATION (rare), zero cost
    # per acquire
    mod = sys._getframe(2).f_globals.get("__name__", "")
    return any(mod == p or mod.startswith(p + ".") for p in det.prefixes)


def _lock_factory():
    det = _active
    if det is None or not _caller_is_instrumented(det):
        return _REAL_LOCK()
    return InstrumentedLock(det, _REAL_LOCK(), f"Lock@{_site(2)}")


def _rlock_factory():
    det = _active
    if det is None or not _caller_is_instrumented(det):
        return _REAL_RLOCK()
    return InstrumentedRLock(det, _REAL_RLOCK(), f"RLock@{_site(2)}")


def _condition_factory(lock=None):
    det = _active
    if det is None or not _caller_is_instrumented(det):
        if isinstance(lock, _InstrumentedBase):
            lock = lock._real
        return _REAL_CONDITION(lock)
    return InstrumentedCondition(det, f"Condition@{_site(2)}", lock)


def _guarded_sleep(seconds: float) -> None:
    det = _active
    if det is not None:
        det.check_blocking(f"time.sleep({seconds!r})")
    _REAL_SLEEP(seconds)


def install(prefixes: tuple[str, ...] = ("kpw_tpu",)) -> Detector:
    """Instrument lock creation for ``prefixes`` modules and guard
    ``time.sleep``.  Returns the live :class:`Detector`.  Locks created
    BEFORE install stay real (install early — the pytest fixture
    installs before the writer under test is constructed)."""
    global _active
    if _active is not None:
        raise RuntimeError("lockcheck already installed")
    det = Detector(prefixes)
    _active = det
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    time.sleep = _guarded_sleep
    return det


def uninstall() -> None:
    """Restore the real primitives.  Locks already handed out keep
    working (they wrap real primitives); only creation reverts."""
    global _active
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    time.sleep = _REAL_SLEEP
    _active = None


def active() -> Detector | None:
    return _active


def wrap_blocking(fn, label: str | None = None):
    """Wrap any callable as a registered blocking call: invoking it with
    an instrumented lock held raises LockHeldBlockingError (and records
    the violation on the detector)."""
    name = label or getattr(fn, "__qualname__", repr(fn))

    def wrapper(*a, **kw):
        det = _active
        if det is not None:
            det.check_blocking(name)
        return fn(*a, **kw)

    wrapper.__name__ = f"blocking[{name}]"
    return wrapper


def guard_mutations(lock: _InstrumentedBase, initial=None) -> GuardedMapping:
    """A dict whose mutations assert ``lock`` is held — the regression
    probe for the PR-1 ``string_stats`` unguarded-merge race."""
    det = _active
    if det is None:
        raise RuntimeError("lockcheck not installed")
    if not isinstance(lock, _InstrumentedBase):
        raise TypeError("guard_mutations needs an instrumented lock "
                        "(create it after install())")
    return GuardedMapping(det, lock, initial or {})
