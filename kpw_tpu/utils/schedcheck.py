"""Deterministic concurrency-schedule explorer runtime: the dynamic half
of the cross-process protocol tooling (the static half is the
``protocol-exhaustiveness`` / ``resource-pairing`` lint passes; the
single-interpreter analog is ``utils/lockcheck.py``).

PR 11/12 created bug classes no lock-order graph can see: the shared-
memory ring slot double-free that needed two exact interleavings of a
stale ``free`` ack against a supervisor respawn, the heartbeat torn read
that condemned a healthy child, and the object-store uploader-thread
spawn race.  Each was caught by a reviewer imagining the schedule.  This
module makes the schedules mechanical:

* **Seeded preemption points.**  Production code marks its racy edges
  with :func:`point` (free of cost when nothing is installed — one
  global ``is None`` check).  :func:`install` arms them: each point
  consults a deterministic per-``(seed, label, occurrence)`` coin and
  either passes through or parks the calling thread for a bounded delay,
  systematically perturbing the interleaving.  ``install`` also patches
  ``threading.Thread.start`` so every KPW-named thread's spawn edge is a
  preemption point (the uploader race lives exactly there), and the same
  seed replays the same perturbation schedule — a failing schedule is
  re-run by re-running its seed (``tools/schedx`` commits the seed
  sets).
* **Invariant probes registered alongside the code they guard.**  The
  ring free pool (``note_slot_taken``/``note_slot_recycled`` — a slot
  recycled while already free is the PR-11 double-free, whichever of the
  stale-ack/respawn interleavings produced it), the heartbeat cells
  (``note_hb_sample`` — ``pending`` observed with a cleared
  ``started_at`` is the torn read that ages into a false condemnation),
  the background uploader singleton (``note_uploader_spawn`` — a second
  live drainer on one adapter reorders dirty part re-uploads), and the
  death-notice pid check (``note_death_notice`` — acting on a stale
  notice condemns the replacement child).  A violated probe raises AND
  records on the active checker (a raise inside a worker thread kills
  the thread, not the test), and every report carries the seed plus BOTH
  participating stacks — the observing one and the first-actor one
  recorded when the guarded state was created.
* **Virtual-delay option.**  ``install(virtual=True)`` replaces wall
  sleeps at preemption points with bounded yield loops, so wide seed
  walks explore quickly; the committed regression seeds use wall delays
  (deterministic on a loaded box: a parked thread stays parked while the
  racing thread's whole critical region completes).

Opt-in exactly like lockcheck: the ``schedcheck_checker`` pytest fixture
or ``KPW_SCHEDCHECK=1`` (whole-suite autouse; the chaos/procworkers/
objectstore suites run their unchanged assertions under the live probes
and must record zero violations).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

# injected delays are INSTRUMENTATION, not production blocking calls:
# they must run even while the perturbed thread holds a production lock,
# so they go through the true stdlib sleep, not lockcheck's guarded
# patch (lockcheck captured it at ITS import and never patches itself)
from .lockcheck import _REAL_SLEEP

_REAL_THREAD_START = threading.Thread.start

# clock-discipline: every timestamp in this module is monotonic — the
# probes reason about liveness windows, never wall time


class ScheduleViolation(RuntimeError):
    """Base of every probe violation: message carries the replay seed
    and both participating stacks."""


class DoubleRecycleError(ScheduleViolation):
    """A ring slot entered the free pool while already free — two units
    would be staged into the same shared memory (the PR-11 stale-free /
    respawn double-free, either interleaving)."""


class HeartbeatTornReadError(ScheduleViolation):
    """A heartbeat sample showed ``pending`` with a cleared
    ``started_at`` — the torn read a watchdog ages into condemning a
    healthy child."""


class UploaderDuplicateError(ScheduleViolation):
    """A second background part-uploader was spawned for one adapter —
    two drainers can reorder a dirty re-upload behind its stale
    original."""


class StaleDeathNoticeError(ScheduleViolation):
    """A death notice was acted on for a process that did not send it —
    a delayed notice from a previous occupant condemns the healthy
    replacement."""


class QuotaLedgerTornError(ScheduleViolation):
    """A multi-tenant quota ledger's per-tenant counters diverged from
    its global total — a torn multi-route update (one side of the
    charge/credit pair landed without the other, i.e. an update escaped
    the ledger lock) would let one tenant's accounting leak into a
    sibling's quota headroom."""


class RevokedCommitError(ScheduleViolation):
    """The commit fence accepted a commit for a partition AFTER its
    ownership handed off to a different member — a revoked run was acked
    past the generation bump, i.e. a zombie clobbered the new owner's
    offset state.  The fenced broker makes this impossible (ownership is
    checked under the metadata lock at commit time); the un-fenced shape
    (a monotonic-only ``commit``) lets a delayed stale commit land after
    the handoff completes."""


def _stack(skip: int = 2, limit: int = 14) -> str:
    while skip > 0:
        try:
            frame = sys._getframe(skip)
            break
        except ValueError:  # shallow caller (direct probe use in tests)
            skip -= 1
    else:
        frame = sys._getframe(0)
    return "".join(traceback.format_stack(frame, limit=limit))


class SchedCheck:
    """One install's shared state: the seeded perturbation schedule, the
    probe state tables, and the violation log."""

    def __init__(self, seed: int = 0, delay_prob: float = 0.5,
                 max_delay_s: float = 0.02, virtual: bool = False,
                 labels: tuple[str, ...] | None = None) -> None:
        self.seed = int(seed)
        self.delay_prob = float(delay_prob)
        self.max_delay_s = float(max_delay_s)
        self.virtual = bool(virtual)
        self.labels = labels  # None = perturb every point
        self._mu = threading.RLock()
        self._occurrence: dict[str, int] = {}
        self.points_hit = 0
        self.delays_injected = 0
        self.violations: list[BaseException] = []
        # probe state -------------------------------------------------------
        # ring free pools: pool key -> {slot idx -> recycling stack}
        self._free_slots: dict[int, dict[int, str]] = {}
        # uploader singletons: adapter key -> spawning stack
        self._uploaders: dict[int, str] = {}
        # heartbeat writers: worker idx -> last hb_publish stack
        self._hb_writers: dict[int, str] = {}
        # quota ledgers: ledger key -> last consistent-update stack
        self._ledger_writers: dict[int, str] = {}
        # partition ownership: (broker key, group, topic, partition) ->
        # (owner member id, handoff stack) — written when a handoff
        # COMPLETES (never during a drain window, so the old owner's
        # drain commits pass)
        self._part_owners: dict[tuple, tuple[str, str]] = {}

    # -- perturbation ---------------------------------------------------------
    def _coin(self, label: str) -> tuple[bool, float]:
        """Deterministic per-(seed, label, occurrence) decision.  Each
        label keeps its own occurrence counter, so two threads running
        DISTINCT point labels consume independent streams — the replay
        does not depend on which thread reached the shared RNG first.
        The RNG is seeded from a STRING (random.seed hashes str via
        sha512, stable everywhere) — seeding from a tuple would go
        through hash(), which PYTHONHASHSEED randomizes per process and
        the replay seed would stop replaying across runs."""
        import random

        with self._mu:
            n = self._occurrence.get(label, 0)
            self._occurrence[label] = n + 1
            self.points_hit += 1
        rng = random.Random(f"{self.seed}:{label}:{n}")
        if rng.random() >= self.delay_prob:
            return False, 0.0
        return True, rng.uniform(0.5, 1.0) * self.max_delay_s

    def _point(self, label: str) -> None:
        if self.labels is not None and label not in self.labels:
            return
        delay, seconds = self._coin(label)
        if not delay:
            return
        with self._mu:
            self.delays_injected += 1
        if self.virtual:
            # virtual-delay mode: bounded yield quanta instead of wall
            # time, so wide seed walks stay fast
            for _ in range(int(seconds * 5000) + 1):
                _REAL_SLEEP(0)
        else:
            _REAL_SLEEP(seconds)

    # -- violation plumbing ---------------------------------------------------
    def _record(self, exc: BaseException) -> BaseException:
        with self._mu:
            self.violations.append(exc)
        return exc

    def _report(self, what: str, first_stack: str | None) -> str:
        return (f"{what}\n[replay: schedcheck seed {self.seed}]\n"
                f"--- this observation ---\n{_stack(2)}"
                f"--- first participant ---\n"
                f"{first_stack or '<stack unavailable>'}")

    # -- probe: ring slot free pool ------------------------------------------
    def note_pool_reset(self, pool_key: int, slots: int) -> None:
        """A fresh ring free pool: every slot starts free (no stack — a
        double recycle against the initial state names only one side)."""
        with self._mu:
            self._free_slots[pool_key] = {i: "<initial free pool>"
                                          for i in range(slots)}

    def note_slot_taken(self, pool_key: int, slot_idx: int) -> None:
        with self._mu:
            self._free_slots.setdefault(pool_key, {}).pop(slot_idx, None)

    def note_slot_recycled(self, pool_key: int, slot_idx: int) -> None:
        """Raises when ``slot_idx`` is already in the free pool: two
        recyclers raced (stale free ack vs. respawn reclaim) and two
        future units would share one slot's memory."""
        with self._mu:
            pool = self._free_slots.setdefault(pool_key, {})
            prior = pool.get(slot_idx)
            if prior is None:
                pool[slot_idx] = _stack(2)
                return
        raise self._record(DoubleRecycleError(self._report(
            f"ring slot {slot_idx} recycled while already free "
            f"(double-free: two units would be staged into the same "
            f"shared memory)", prior)))

    # -- probe: heartbeat cells ----------------------------------------------
    def note_hb_write(self, widx: int) -> None:
        with self._mu:
            self._hb_writers[widx] = _stack(2)

    def note_hb_sample(self, widx: int, pending: bool,
                       started_at: float) -> None:
        """Guards the stall-age COMPUTATION: ``pending`` about to be aged
        from a cleared (or absurd) ``started_at`` is the torn-read shape
        — a watchdog computing ``monotonic() - 0.0`` sees an enormous
        stall and condemns a healthy child.  A transient raw sample of
        (pending, 0.0) out of ``hb_read`` is benign BY DESIGN (the
        reader's own field reads can tear); the invariant is that no
        consumer ever turns one into an age."""
        if pending and (started_at == 0.0
                        or time.monotonic() - started_at > 3600.0):
            with self._mu:
                writer = self._hb_writers.get(widx)
            raise self._record(HeartbeatTornReadError(self._report(
                f"heartbeat cell {widx}: stall age computed from a "
                f"cleared/garbage started_at ({started_at!r}) — a torn "
                f"read is about to condemn a healthy child", writer)))

    # -- probe: uploader singleton -------------------------------------------
    def note_uploader_spawn(self, fs_key: int) -> None:
        with self._mu:
            prior = self._uploaders.get(fs_key)
            if prior is None:
                self._uploaders[fs_key] = _stack(2)
                prior = None
        if prior is not None:
            raise self._record(UploaderDuplicateError(self._report(
                "second background part-uploader spawned for one "
                "object-store adapter (two drainers reorder dirty part "
                "re-uploads)", prior)))

    # -- probe: multi-tenant quota ledger ------------------------------------
    def note_quota_ledger(self, ledger_key: int, per_tenant_sum: int,
                          global_total: int) -> None:
        """Guards the shared-session quota ledger's pairing invariant:
        at every charge/credit the sum of the per-tenant counters must
        equal the global total (both are updated under one lock, with a
        preemption point between them — an update that escapes the lock
        tears here).  The caller computes both sums INSIDE its critical
        section, so a violation is a real torn update, never reader-side
        tearing."""
        if per_tenant_sum != global_total:
            with self._mu:
                first = self._ledger_writers.get(ledger_key)
            raise self._record(QuotaLedgerTornError(self._report(
                f"quota ledger {ledger_key:#x}: per-tenant counters sum to "
                f"{per_tenant_sum} but the global total reads "
                f"{global_total} — a multi-route update tore", first)))
        with self._mu:
            self._ledger_writers[ledger_key] = _stack(2)

    # -- probe: revocation-vs-in-flight-publish fence ------------------------
    def note_partition_owner(self, broker_key: int, part_key: tuple,
                             member: str) -> None:
        """A partition handoff COMPLETED: ``member`` is now the
        authoritative owner of ``part_key`` (= (group, topic,
        partition)).  The broker notes this only when the transfer is
        final — instant reassignments and drain-window completions —
        never at drain BEGIN, so the old owner's in-window flush commits
        don't trip the probe."""
        with self._mu:
            self._part_owners[(broker_key,) + part_key] = (member, _stack(2))

    def note_commit_accepted(self, broker_key: int, part_key: tuple,
                             member: str) -> None:
        """Guards the fence itself: a commit the broker ACCEPTED from a
        member that is not the recorded owner means a revoked run was
        acked after the generation bump — the exactly-once handoff is
        broken.  The fenced commit path cannot reach here in that state
        (ownership is re-checked under the same lock); the ``--revert``
        monotonic-only shape lands here with the zombie's identity."""
        with self._mu:
            rec = self._part_owners.get((broker_key,) + part_key)
        if rec is not None and rec[0] != member:
            raise self._record(RevokedCommitError(self._report(
                f"commit for {part_key} accepted from member {member!r} "
                f"after ownership handed off to {rec[0]!r} — a revoked "
                f"run was acked past the generation bump", rec[1])))

    # -- probe: death-notice pid check ---------------------------------------
    def note_death_notice(self, slot_pid: int | None, msg_pid: int,
                          acted: bool) -> None:
        if acted and slot_pid != msg_pid:
            raise self._record(StaleDeathNoticeError(self._report(
                f"death notice from pid {msg_pid} acted on a slot now "
                f"occupied by pid {slot_pid} (stale notice condemns the "
                f"replacement child)", None)))

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "seed": self.seed,
                "points_hit": self.points_hit,
                "delays_injected": self.delays_injected,
                "violations": [repr(v) for v in self.violations],
            }


# -- module-level seams (cheap when inactive) ---------------------------------

_active: SchedCheck | None = None


def point(label: str) -> None:
    """A seeded preemption point.  Costs one global ``is None`` check
    when no checker is installed."""
    c = _active
    if c is not None:
        c._point(label)


def note_pool_reset(pool_key: int, slots: int) -> None:
    c = _active
    if c is not None:
        c.note_pool_reset(pool_key, slots)


def note_slot_taken(pool_key: int, slot_idx: int) -> None:
    c = _active
    if c is not None:
        c.note_slot_taken(pool_key, slot_idx)


def note_slot_recycled(pool_key: int, slot_idx: int) -> None:
    c = _active
    if c is not None:
        c.note_slot_recycled(pool_key, slot_idx)


def note_hb_write(widx: int) -> None:
    c = _active
    if c is not None:
        c.note_hb_write(widx)


def note_hb_sample(widx: int, pending: bool, started_at: float) -> None:
    c = _active
    if c is not None:
        c.note_hb_sample(widx, pending, started_at)


def note_uploader_spawn(fs_key: int) -> None:
    c = _active
    if c is not None:
        c.note_uploader_spawn(fs_key)


def note_partition_owner(broker_key: int, part_key: tuple,
                         member: str) -> None:
    c = _active
    if c is not None:
        c.note_partition_owner(broker_key, part_key, member)


def note_commit_accepted(broker_key: int, part_key: tuple,
                         member: str) -> None:
    c = _active
    if c is not None:
        c.note_commit_accepted(broker_key, part_key, member)


def note_death_notice(slot_pid: int | None, msg_pid: int,
                      acted: bool) -> None:
    c = _active
    if c is not None:
        c.note_death_notice(slot_pid, msg_pid, acted)


def note_quota_ledger(ledger_key: int, per_tenant_sum: int,
                      global_total: int) -> None:
    c = _active
    if c is not None:
        c.note_quota_ledger(ledger_key, per_tenant_sum, global_total)


def _patched_thread_start(self: threading.Thread) -> None:
    """Spawn edges of KPW-named threads are preemption points too — the
    uploader spawn race lives exactly in the window between a thread
    object's creation and its start."""
    c = _active
    if c is not None and self.name.upper().startswith("KPW"):
        c._point(f"thread.start:{self.name}")
    _REAL_THREAD_START(self)


def install(seed: int = 0, delay_prob: float = 0.5,
            max_delay_s: float = 0.02, virtual: bool = False,
            labels: tuple[str, ...] | None = None) -> SchedCheck:
    """Arm the preemption points and probes.  ``labels`` restricts the
    perturbation to a targeted point set (probes always stay live);
    ``virtual`` trades wall delays for yield loops."""
    global _active
    if _active is not None:
        raise RuntimeError("schedcheck already installed")
    checker = SchedCheck(seed=seed, delay_prob=delay_prob,
                         max_delay_s=max_delay_s, virtual=virtual,
                         labels=labels)
    _active = checker
    threading.Thread.start = _patched_thread_start
    return checker


def uninstall() -> None:
    global _active
    threading.Thread.start = _REAL_THREAD_START
    _active = None


def active() -> SchedCheck | None:
    return _active


def env_requested() -> bool:
    return os.environ.get("KPW_SCHEDCHECK") == "1"
