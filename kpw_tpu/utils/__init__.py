"""Cross-cutting utilities: tracing/profiling (SURVEY.md §5)."""

from .tracing import StageTimer, get_tracer, set_tracer, stage  # noqa: F401
