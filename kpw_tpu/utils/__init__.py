"""Cross-cutting utilities: tracing/profiling (SURVEY.md §5)."""

from .tracing import (  # noqa: F401
    STAGE_NAMES,
    SpanRecorder,
    StageTimer,
    get_span_recorder,
    get_tracer,
    set_span_recorder,
    set_tracer,
    stage,
)
