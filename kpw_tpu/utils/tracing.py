"""Per-stage tracing/profiling for the encode pipeline.

The reference has no tracing — only lifecycle logging (SURVEY.md §5,
KafkaProtoParquetWriter.java:172-197).  The TPU rebuild needs real stage
attribution because the pipeline is host ingest / device encode / host
flush: a slowdown can hide in device dispatch, host assembly, or IO.

Three layers, all zero-cost when disabled:

- :class:`StageTimer` — cumulative wall-clock + call counts + min/max per
  stage, queryable programmatically (the metrics analog of the reference's
  written/flushed meters, KPW.java:144-151, but for time).
- :class:`SpanRecorder` — a bounded, thread-safe ring buffer of individual
  spans (name, thread, start, duration, optional attrs like row-group
  ordinal or file path), exportable as Chrome/Perfetto ``trace_event``
  JSON so dispatch-vs-assembly-vs-IO overlap is visually inspectable on
  a timeline instead of inferred from cumulative sums.
- ``jax.profiler.TraceAnnotation`` — when a JAX profiler trace is being
  captured, the same ``stage(...)`` spans show up on the TensorBoard/Perfetto
  timeline against the device activity.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

# Canonical stage-name registry: every name ``stage(...)`` is called with
# anywhere in the codebase.  Docs cite these names; tools/check_docs.py
# verifies each cited name exists here so a rename cannot silently orphan
# a doc claim.  Grouped by pipeline leg:
#   consumer.* — the smart-commit fetcher thread (ingest/consumer.py)
#   worker.*   — the per-worker poll loop (runtime/writer.py)
#   rowgroup.* — the row-group pipeline stages (core/writer.py)
#   encode.*   — the encoder's internal phases (ops/backend.py)
#   compactor.* — the small-file compaction service (io/compact.py)
#   upload.*   — the object-store part uploader (io/objectstore.py)
#   tenant.*   — the multi-tenant routing legs (runtime/multiwriter.py)
STAGE_NAMES = (
    "consumer.fetch",
    "consumer.track",
    "worker.shred",
    "worker.append",
    "worker.publish",
    "worker.proc.dispatch",
    "worker.proc.ack",
    "rowgroup.encode",
    "rowgroup.launch",
    "rowgroup.assemble",
    "rowgroup.io_write",
    "encode.launch",
    "encode.bodies",
    "encode.assemble",
    "assemble.native",
    "encode.bloom",
    "encode.page_index",
    "compactor.merge",
    "compactor.round",
    "upload.part",
    "tenant.quota.wait",
    "tenant.route.start",
    "tenant.route.close",
    "tenant.schema.audit",
)


class StageTimer:
    """Thread-safe cumulative timer keyed by stage name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._total: dict[str, float] = {}
        self._count: dict[str, int] = {}
        self._min: dict[str, float] = {}
        self._max: dict[str, float] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._total[name] = self._total.get(name, 0.0) + seconds
            self._count[name] = self._count.get(name, 0) + 1
            if seconds < self._min.get(name, float("inf")):
                self._min[name] = seconds
            if seconds > self._max.get(name, float("-inf")):
                self._max[name] = seconds

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                name: {"seconds": self._total[name],
                       "calls": self._count[name],
                       "min": self._min[name],
                       "max": self._max[name]}
                for name in sorted(self._total)
            }

    def reset(self) -> None:
        with self._lock:
            self._total.clear()
            self._count.clear()
            self._min.clear()
            self._max.clear()


class SpanRecorder:
    """Bounded thread-safe ring buffer of per-event spans.

    Each span is (name, thread_name, thread_id, start_s, duration_s,
    attrs) with ``start_s`` relative to the recorder's creation.  The
    buffer is a ``deque(maxlen=capacity)``: at capacity the OLDEST spans
    are evicted, so a long run keeps the most recent window — the part a
    live investigation actually wants — at O(capacity) memory.  Append is
    one lock round per span; spans here are stage-granular (row groups,
    fetch batches), not per record, so the hot path never sees more than
    a few thousand appends per second."""

    def __init__(self, capacity: int = 65536, pid: int | None = None) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        # real process identity: every exported event carries the pid that
        # recorded it, so a merged multi-process trace keeps its rows
        # separable (and a single-process trace is honest about which
        # process it came from)
        self.pid = os.getpid() if pid is None else pid
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._dropped = 0
        # wall-clock anchor + monotonic epoch: spans are timed with
        # perf_counter (monotonic, ns resolution) but anchored to an
        # absolute wall time so multiple recorders/processes can be lined up
        self.epoch_wall = time.time()
        self._epoch = time.perf_counter()

    def now(self) -> float:
        """Seconds since the recorder's epoch (span clock)."""
        return time.perf_counter() - self._epoch

    def record(self, name: str, thread_name: str, thread_id: int,
               start_s: float, duration_s: float,
               attrs: dict | None = None) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(
                (name, thread_name, thread_id, start_s, duration_s, attrs))

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring bound (oldest-first)."""
        with self._lock:
            return self._dropped

    def snapshot(self) -> list[tuple]:
        """Consistent copy of the buffered spans, oldest first."""
        with self._lock:
            return list(self._spans)

    def drain(self) -> list[tuple]:
        """Pop every buffered span (oldest first), leaving the buffer
        empty.  The cross-process shipping primitive: a child drains its
        ring at rotation/seal boundaries and at exit, sends the batch to
        the parent over the ack channel, and keeps recording — the
        bounded buffer never has to hold a whole run's spans."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            return out

    def to_chrome_trace(self) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON (the ``chrome://tracing``
        / https://ui.perfetto.dev object format): one complete event
        (``ph: "X"``) per span, microsecond ``ts``/``dur``, ``tid`` =
        recording thread.  Thread names ride ``thread_name`` metadata
        events so the timeline rows are labeled kpw-rg-encode /
        kpw-rg-assemble / kpw-rg-io / worker threads."""
        events = _span_events(self.snapshot(), self.pid, 0.0)
        events.append({
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": f"kpw pid {self.pid}"},
        })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "recorder_epoch_unix_s": self.epoch_wall,
                "spans_dropped": self.dropped,
                "span_capacity": self.capacity,
            },
        }

    def write_chrome_trace(self, path: str) -> None:
        """Serialize :meth:`to_chrome_trace` to ``path`` (open the file in
        chrome://tracing or ui.perfetto.dev)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    def export_payload(self, process_name: str | None = None) -> dict:
        """Drain the buffer into the picklable cross-process shipping
        shape :meth:`MultiProcessTrace.absorb` takes: spans + this
        recorder's pid and wall-clock epoch (the alignment anchor)."""
        return {
            "pid": self.pid,
            "epoch_wall": self.epoch_wall,
            "process_name": process_name or f"kpw pid {self.pid}",
            "spans": self.drain(),
            "dropped": self.dropped,
        }


def _span_events(spans, pid: int, shift_s: float) -> list[dict]:
    """Span tuples -> Chrome ``trace_event`` complete events (+ one
    ``thread_name`` metadata event per thread), all stamped ``pid`` with
    start times shifted by ``shift_s`` (the epoch-alignment delta)."""
    events: list[dict] = []
    thread_names: dict[int, str] = {}
    for name, tname, tid, start_s, dur_s, attrs in spans:
        thread_names.setdefault(tid, tname)
        ev = {
            "name": name,
            "ph": "X",
            "ts": round((start_s + shift_s) * 1e6, 3),
            "dur": round(dur_s * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "cat": name.split(".", 1)[0],
        }
        if attrs:
            ev["args"] = attrs
        events.append(ev)
    for tid, tname in thread_names.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    return events


class MultiProcessTrace:
    """Parent-side merger: one Chrome/Perfetto timeline spanning every
    process the writer tree owns.

    The parent's own :class:`SpanRecorder` is the alignment anchor; each
    child ships ``{pid, epoch_wall, spans, ...}`` payloads
    (:meth:`SpanRecorder.export_payload`, drained over the ack side
    channel at rotation/seal boundaries and at exit).  Child span clocks
    are relative to the CHILD's epoch, so the merge shifts them by
    ``child.epoch_wall - parent.epoch_wall`` — both processes anchored
    their monotonic span clock to wall time at recorder creation, which
    is exactly the cross-process hook ``epoch_wall`` was left for.
    Per-child span storage is bounded by the parent recorder's capacity
    (oldest evicted), so a chatty child cannot grow the parent without
    bound."""

    def __init__(self, recorder: SpanRecorder) -> None:
        self._recorder = recorder
        self._lock = threading.Lock()
        # pid -> {"epoch_wall", "process_name", "spans": deque, "dropped"}
        self._children: dict[int, dict] = {}

    def absorb(self, payload: dict) -> None:
        """Merge one child payload; safe from any thread, never raises
        on a malformed payload (observability must not take down the ack
        collector)."""
        try:
            pid = int(payload["pid"])
            epoch_wall = float(payload["epoch_wall"])
            spans = payload.get("spans") or []
            with self._lock:
                entry = self._children.get(pid)
                if entry is None:
                    entry = {
                        "epoch_wall": epoch_wall,
                        "process_name": str(
                            payload.get("process_name") or f"pid {pid}"),
                        "spans": deque(maxlen=self._recorder.capacity),
                        "dropped": 0,
                    }
                    self._children[pid] = entry
                entry["dropped"] = max(entry["dropped"],
                                       int(payload.get("dropped") or 0))
                entry["spans"].extend(tuple(s) for s in spans)
        except (KeyError, TypeError, ValueError):
            logging.getLogger(__name__).warning(
                "dropping malformed child span payload", exc_info=True)

    def pids(self) -> list[int]:
        with self._lock:
            return sorted([self._recorder.pid, *self._children])

    def to_chrome_trace(self) -> dict:
        trace = self._recorder.to_chrome_trace()
        events = trace["traceEvents"]
        with self._lock:
            children = {pid: (e["epoch_wall"], e["process_name"],
                              list(e["spans"]), e["dropped"])
                        for pid, e in self._children.items()}
        child_dropped = 0
        for pid, (epoch_wall, pname, spans, dropped) in children.items():
            shift = epoch_wall - self._recorder.epoch_wall
            events.extend(_span_events(spans, pid, shift))
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": pname},
            })
            child_dropped += dropped
        trace["otherData"]["processes"] = self.pids()
        trace["otherData"]["child_spans_dropped"] = child_dropped
        return trace

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


_tracer: StageTimer | None = None
_recorder: SpanRecorder | None = None


def set_tracer(tracer: StageTimer | None) -> None:
    """Install (or remove) the process-wide stage timer."""
    global _tracer
    _tracer = tracer


def get_tracer() -> StageTimer | None:
    return _tracer


def set_span_recorder(recorder: SpanRecorder | None) -> None:
    """Install (or remove) the process-wide span ring buffer.  Orthogonal
    to :func:`set_tracer`: either, both, or neither may be installed."""
    global _recorder
    _recorder = recorder


def get_span_recorder() -> SpanRecorder | None:
    return _recorder


@contextmanager
def stage(name: str, **attrs):
    """Span a pipeline stage: feeds the installed StageTimer and/or
    SpanRecorder and annotates the JAX profiler timeline.  A true no-op
    (just a yield) when neither is installed, so the hot path pays nothing
    by default.  ``attrs`` (row-group ordinal, file path, batch rows, ...)
    are only consumed when a SpanRecorder is installed."""
    tracer = _tracer
    recorder = _recorder
    if tracer is None and recorder is None:
        yield
        return
    annotation = None
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
        annotation.__enter__()
    except Exception:
        annotation = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if annotation is not None:
            annotation.__exit__(None, None, None)
        dt = time.perf_counter() - t0
        if tracer is not None:
            tracer.record(name, dt)
        if recorder is not None:
            t = threading.current_thread()
            recorder.record(name, t.name, t.ident or 0,
                            t0 - recorder._epoch, dt, attrs or None)
