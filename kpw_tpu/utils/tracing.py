"""Per-stage tracing/profiling for the encode pipeline.

The reference has no tracing — only lifecycle logging (SURVEY.md §5,
KafkaProtoParquetWriter.java:172-197).  The TPU rebuild needs real stage
attribution because the pipeline is host ingest / device encode / host
flush: a slowdown can hide in device dispatch, host assembly, or IO.

Two layers, both zero-cost when disabled:

- :class:`StageTimer` — cumulative wall-clock + call counts per stage,
  queryable programmatically (the metrics analog of the reference's
  written/flushed meters, KPW.java:144-151, but for time).
- ``jax.profiler.TraceAnnotation`` — when a JAX profiler trace is being
  captured, the same ``stage(...)`` spans show up on the TensorBoard/Perfetto
  timeline against the device activity.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class StageTimer:
    """Thread-safe cumulative timer keyed by stage name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._total: dict[str, float] = {}
        self._count: dict[str, int] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._total[name] = self._total.get(name, 0.0) + seconds
            self._count[name] = self._count.get(name, 0) + 1

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                name: {"seconds": self._total[name], "calls": self._count[name]}
                for name in sorted(self._total)
            }

    def reset(self) -> None:
        with self._lock:
            self._total.clear()
            self._count.clear()


_tracer: StageTimer | None = None


def set_tracer(tracer: StageTimer | None) -> None:
    """Install (or remove) the process-wide stage timer."""
    global _tracer
    _tracer = tracer


def get_tracer() -> StageTimer | None:
    return _tracer


@contextmanager
def stage(name: str):
    """Span a pipeline stage: feeds the installed StageTimer and annotates
    the JAX profiler timeline.  A true no-op (just a yield) when no tracer is
    installed, so the hot path pays nothing by default."""
    tracer = _tracer
    if tracer is None:
        yield
        return
    annotation = None
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
        annotation.__enter__()
    except Exception:
        annotation = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if annotation is not None:
            annotation.__exit__(None, None, None)
        tracer.record(name, time.perf_counter() - t0)
