"""Multi-tenant bulkheads (ISSUE 15): N routes over one broker session,
isolated by per-tenant quotas (queue share + open-file budget, enforced
as backpressure-on-the-offender), per-tenant fault domains (a sink
fault, a poison stream, or an incompatible schema is contained to its
route), per-tenant observability (stats/ack-lag/canonical meters in both
exporters), and schema evolution handled the way parquet readers expect
(additive merged-schema reads; incompatible changes dead-letter with a
typed reason; the cross-file schema audit flags a planted mixed tree).

The whole module runs under the LIVE lockcheck + schedcheck probes
(module-autouse fixtures, the procworkers-suite pattern): the shared
quota ledger's torn-update invariant probe and the lock-order graph are
armed on every drill below, and any violation fails the test here.
"""

import errno
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from kpw_tpu import (
    Builder,
    FakeBroker,
    MemoryFileSystem,
    MetricRegistry,
    MultiWriter,
    TenantQuotaLedger,
    registry_to_json,
    registry_to_prometheus,
)
from kpw_tpu.io import FaultInjectingFileSystem, FaultSchedule
from kpw_tpu.io.fs import publish_file
from kpw_tpu.io.verify import audit_schema_consistency, file_schema
from kpw_tpu.models.proto_bridge import ProtoColumnarizer
from kpw_tpu.runtime import metrics as M
from kpw_tpu.runtime.parquet_file import ParquetFile
from kpw_tpu.utils import schedcheck
from kpw_tpu.utils.schedcheck import QuotaLedgerTornError

from proto_helpers import _F, _field, build_classes, sample_message_class

PARTS = 2


@pytest.fixture(autouse=True)
def _probes(schedcheck_checker, lockcheck_detector):
    """Module autouse: every drill runs with the schedule explorer's
    invariant probes (incl. the quota-ledger torn-update probe) AND the
    runtime lock-order detector live — assertions below run unchanged,
    any probe/lock violation fails here."""
    yield
    assert not schedcheck_checker.violations, [
        repr(v) for v in schedcheck_checker.violations]
    assert not lockcheck_detector.violations, [
        repr(v) for v in lockcheck_detector.violations]


def sample_v2_class():
    """Additive evolution of the sample schema: one new optional field."""
    return build_classes("sample_v2", {
        "SampleMessage": [
            _field("query", 1, _F.TYPE_STRING, _F.LABEL_REQUIRED),
            _field("timestamp", 2, _F.TYPE_INT64, _F.LABEL_REQUIRED),
            _field("page_number", 3, _F.TYPE_INT32),
            _field("result_per_page", 4, _F.TYPE_INT32),
            _field("extra_score", 5, _F.TYPE_INT32),
        ]
    })["SampleMessage"]


def sample_incompatible_class():
    """Incompatible evolution: ``timestamp`` flips int64 -> string (one
    dotted leaf path, two physical types — the merged-read breaker)."""
    return build_classes("sample_bad", {
        "SampleMessage": [
            _field("query", 1, _F.TYPE_STRING, _F.LABEL_REQUIRED),
            _field("timestamp", 2, _F.TYPE_STRING, _F.LABEL_REQUIRED),
        ]
    })["SampleMessage"]


def produce(broker, topic, cls, n, start=0, pad=40, page_mod=None):
    for i in range(start, start + n):
        m = cls(query=f"q-{i}-{'x' * pad}", timestamp=i)
        if page_mod is not None:
            m.page_number = i % page_mod
        broker.produce(topic, m.SerializeToString(), partition=i % PARTS)


def base_builder(broker, fs, reg=None):
    b = (Builder().broker(broker).filesystem(fs)
         .instance_name("tenants").thread_count(1).batch_size(256)
         .max_file_size(128 * 1024).block_size(32 * 1024)
         .max_file_open_duration_seconds(0.4)
         .supervise(True, max_restarts=4, restart_backoff_seconds=0.02))
    if reg is not None:
        b.metric_registry(reg)
    return b


def drain(mw, broker, expected, deadline_s=90, sample=None):
    """Run until every (topic, rows) pair in ``expected`` is committed
    and the aggregate ack lag is 0.  ``sample(mw)`` is called each tick
    (the SLA/occupancy probes some drills record)."""
    group = next(iter(mw.routes.values()))._b._group_id
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if sample is not None:
            sample(mw)
        done = all(
            sum(broker.committed(group, topic, p)
                for p in range(PARTS)) >= rows
            for topic, rows in expected.items())
        if done and mw.ack_lag()["unacked_records"] == 0:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"never drained: lag={mw.ack_lag()}, committed="
        f"{{t: [broker.committed(group, t, p) for p in range(PARTS)] "
        f"for t in expected}}")


def seed_tree(fs, target, cls, rows, name="seed.parquet", start=0,
              extra=None):
    """Publish one parquet file of ``cls`` rows into ``target`` directly
    (no writer) — the pre-existing-tree fixture for the schema drills."""
    props = Builder().proto_class(cls).writer_properties()
    msgs = []
    for i in range(start, start + rows):
        m = cls(query=f"s-{i}", timestamp=i)
        if extra is not None:
            setattr(m, extra, i)
        msgs.append(m)
    tmp = f"{target}/tmp/{name}.tmp"
    fs.mkdirs(f"{target}/tmp")
    pf = ParquetFile(fs, tmp, ProtoColumnarizer(cls), props, batch_size=256)
    pf.append_records(msgs)
    pf.close()
    publish_file(fs, tmp, f"{target}/{name}", durable=False)
    return f"{target}/{name}"


# -- shared session, per-tenant trees, observability --------------------------

def test_routes_share_session_publish_per_tenant_trees_and_meters():
    """Three tenants (two protos) over ONE broker session: each drains
    into its own tree, the session's per-tenant fetch split is
    observable, per-tenant stats carry ack/status/quota, and the
    canonical tenant meters render in BOTH generic exporters."""
    cls = sample_message_class()
    broker = FakeBroker()
    for t in ("ta", "tb", "tc"):
        broker.create_topic(t, PARTS)
        produce(broker, t, cls, 2000)
    fs = MemoryFileSystem()
    reg = MetricRegistry()
    b = (base_builder(broker, fs, reg)
         .route("ta", cls, "/mt/ta", queue_quota=50_000, ack_sla_seconds=30)
         .route("tb", cls, "/mt/tb")
         .route("tc", cls, "/mt/tc"))
    mw = b.build()
    assert isinstance(mw, MultiWriter)
    with mw:
        drain(mw, broker, {"ta": 2000, "tb": 2000, "tc": 2000})
        st = mw.stats()
        assert st["healthy"]
        for t in ("ta", "tb", "tc"):
            ten = st["tenants"][t]
            assert ten["state"] == "running"
            assert ten["ack"]["unacked_records"] == 0
            assert ten["workers_dead"] == 0
            assert not ten["sla_violated"]
            # every tenant's traffic went through the ONE shared session
            assert st["session"]["records_by_tenant"][t] >= 2000
        assert st["tenants"]["ta"]["quota"]["queue_quota"] == 50_000
        # full single-writer stats reachable per route
        assert mw.route_stats("tb")["ack"]["unacked_records"] == 0
    for t in ("ta", "tb", "tc"):
        files = [f for f in fs.list_files(f"/mt/{t}", extension=".parquet")
                 if "/tmp/" not in f]
        assert files, f"tenant {t} published nothing"
        rows = sum(len(pq.read_table(fs.open_read(f))) for f in files)
        assert rows >= 2000
    # canonical tenant meters/gauges in both exporters, no per-metric wiring
    prom = registry_to_prometheus(reg)
    js = registry_to_json(reg)
    for name in (M.TENANT_QUEUE_STALLS_METER, M.TENANT_QUEUE_STALL_MS_METER,
                 M.TENANT_FILES_EVICTED_METER, M.DEADLETTER_METER,
                 M.TENANT_ROUTES_GAUGE, M.TENANT_ROUTES_DEGRADED_GAUGE):
        assert name in js
        assert name.replace(".", "_") in prom


# -- quotas: backpressure on the offender -------------------------------------

def test_noisy_neighbor_quota_throttles_offender_not_victims():
    """The burst tenant's small queue share parks ITS OWN fetch gate
    (stall episodes bind on the offender); the victim's gate never
    fires, both drain, nothing is dropped."""
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic("burst", PARTS)
    broker.create_topic("victim", PARTS)
    produce(broker, "burst", cls, 12_000)
    produce(broker, "victim", cls, 2000)
    fs = MemoryFileSystem()
    mw = (base_builder(broker, fs)
          .route("burst", cls, "/nn/burst", queue_quota=600)
          .route("victim", cls, "/nn/victim", queue_quota=50_000,
                 ack_sla_seconds=30)
          .build())
    with mw:
        drain(mw, broker, {"burst": 12_000, "victim": 2000})
        led = mw.stats()["quota_ledger"]["tenants"]
        assert led["burst"]["quota_stalls"] > 0, \
            "the burst tenant's gate never bound — the quota is vacuous"
        assert led["victim"]["quota_stalls"] == 0
        assert led["burst"]["queued_records"] == 0  # credits matched charges
        assert led["victim"]["queued_records"] == 0
        assert not mw.stats()["tenants"]["victim"]["sla_violated"]


def test_quota_gate_blocks_until_credit_and_counts_stall():
    """Ledger unit: a tenant at its share parks in wait_turn until a
    drain credit frees headroom; the stall episode and seconds are
    counted on the offender only."""
    import threading

    led = TenantQuotaLedger()
    led.register("a", queue_quota=2)
    led.register("b", queue_quota=2)
    led.on_enqueued("a", 2)
    released = threading.Event()

    def gate():
        led.wait_turn("a", tick_s=0.01)
        released.set()

    t = threading.Thread(target=gate, daemon=True)
    t.start()
    assert not released.wait(0.15), "gate passed while at quota"
    assert led.wait_turn("b") == 0.0  # sibling never parks
    led.on_drained("a", 1)
    assert released.wait(2.0), "credit did not release the gate"
    t.join(2.0)
    snap = led.tenant_snapshot("a")
    assert snap["quota_stalls"] == 1
    assert snap["quota_stall_s"] > 0.0
    assert led.tenant_snapshot("b")["quota_stalls"] == 0


def test_quota_ledger_torn_update_probe():
    """The schedx-style invariant probe guards the ledger against torn
    multi-route updates: a consistent charge passes, a diverged
    per-tenant-sum vs global-total raises AND records with the replay
    seed (negative control — the recorded violation is then cleared so
    the module-autouse zero-violations assertion stays meaningful)."""
    act = schedcheck.active()
    assert act is not None
    schedcheck.note_quota_ledger(0xbeef, 7, 7)  # consistent: passes
    with pytest.raises(QuotaLedgerTornError) as ei:
        schedcheck.note_quota_ledger(0xbeef, 3, 4)
    assert "torn" in str(ei.value)
    assert any(isinstance(v, QuotaLedgerTornError) for v in act.violations)
    act.violations.clear()  # negative control: not a real violation


def test_open_file_budget_evicts_lru_within_the_offending_route():
    """The PR-8 LRU bound generalized: a partitioned route at its
    open-file budget closes-and-publishes its own LRU file before
    opening another — open files stay at/under the budget, the tenant
    eviction meter binds, everything still drains and acks."""
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic("pt", PARTS)
    produce(broker, "pt", cls, 4000, page_mod=6)
    fs = MemoryFileSystem()
    reg = MetricRegistry()
    mw = (base_builder(broker, fs, reg)
          .route("pt", cls, "/fb/pt", open_file_budget=2,
                 partition_by={"spec": "page_number",
                               "max_open_partitions": 8})
          .build())
    seen_open = []
    with mw:
        drain(mw, broker, {"pt": 4000},
              sample=lambda m: seen_open.append(
                  m.stats()["tenants"]["pt"]["quota"]["open_files"]))
    assert max(seen_open) <= 2, f"budget exceeded: {max(seen_open)}"
    assert reg.get(M.TENANT_FILES_EVICTED_METER).count > 0
    # six partitions' rows all landed despite the 2-file budget
    got = set()
    for f in fs.list_files("/fb/pt", extension=".parquet"):
        if "/tmp/" in f:
            continue
        got.update(r["timestamp"]
                   for r in pq.read_table(fs.open_read(f)).to_pylist())
    assert got.issuperset(range(4000))


# -- fault domains: containment ----------------------------------------------

def test_sink_fault_pauses_offending_route_alone_then_recovers():
    """A fatal sink condition (ENOSPC) on ONE tenant's filesystem pauses
    that route alone (degraded-mode bulkhead): the sibling keeps
    publishing and fully drains DURING the outage with zero worker
    deaths, and after heal() the faulted route resumes and drains too."""
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic("sick", PARTS)
    broker.create_topic("well", PARTS)
    produce(broker, "sick", cls, 3000)
    produce(broker, "well", cls, 3000)
    sched = FaultSchedule(seed=3).recover_after("write", nth=6,
                                                err=errno.ENOSPC)
    sick_fs = FaultInjectingFileSystem(MemoryFileSystem(), sched)
    well_fs = MemoryFileSystem()
    mw = (base_builder(broker, MemoryFileSystem())
          .route("sick", cls, "/fd/sick", filesystem=sick_fs,
                 degraded_mode={"flag": True,
                                "probe_interval_seconds": 0.05,
                                "probe_backoff_max_seconds": 0.2})
          .route("well", cls, "/fd/well", filesystem=well_fs,
                 ack_sla_seconds=30)
          .build())
    group = None
    try:
        mw.start()
        group = mw.route("well")._b._group_id
        # wait for the sick route to PAUSE (not die)
        deadline = time.time() + 30
        while time.time() < deadline:
            if mw.stats()["tenants"]["sick"]["state"] == "paused":
                break
            time.sleep(0.02)
        st = mw.stats()
        assert st["tenants"]["sick"]["state"] == "paused", \
            st["tenants"]["sick"]
        # sibling drains FULLY while the offender is paused
        deadline = time.time() + 60
        while time.time() < deadline:
            if (sum(broker.committed(group, "well", p)
                    for p in range(PARTS)) >= 3000
                    and mw.route("well").ack_lag()["unacked_records"] == 0):
                break
            time.sleep(0.02)
        st = mw.stats()
        assert sum(broker.committed(group, "well", p)
                   for p in range(PARTS)) >= 3000
        assert st["tenants"]["well"]["workers_dead"] == 0
        assert st["tenants"]["well"]["restarts_total"] == 0
        assert st["tenants"]["well"]["healthy"]
        # heal the sink: the paused route resumes and drains alone
        sched.heal()
        drain(mw, broker, {"sick": 3000, "well": 3000})
        st = mw.stats()
        assert st["tenants"]["sick"]["state"] == "running"
        assert st["tenants"]["sick"]["workers_dead"] == 0
    finally:
        mw.close()


def test_poison_stream_dead_letters_alone():
    """Garbage payloads on one tenant's topic dead-letter (typed frames
    in ITS tree, then ack) without touching the sibling: zero sibling
    deaths, sibling rows all published, per-tenant dead-letter counts
    exact, canonical meter aggregates."""
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic("poison", PARTS)
    broker.create_topic("clean", PARTS)
    n_poison = 0
    for i in range(2000):
        if i % 100 == 7:
            broker.produce("poison", b"\xff\xfe garbage " + bytes([i % 256]),
                           partition=i % PARTS)
            n_poison += 1
        else:
            broker.produce("poison",
                           cls(query=f"q-{i}",
                               timestamp=i).SerializeToString(),
                           partition=i % PARTS)
    produce(broker, "clean", cls, 2000)
    fs = MemoryFileSystem()
    reg = MetricRegistry()
    mw = (base_builder(broker, fs, reg)
          .route("poison", cls, "/ps/poison", on_parse_error="dead_letter")
          .route("clean", cls, "/ps/clean")
          .build())
    with mw:
        drain(mw, broker, {"poison": 2000, "clean": 2000})
        st = mw.stats()
        assert st["tenants"]["poison"]["deadletter_records"] == n_poison
        assert st["tenants"]["clean"]["deadletter_records"] == 0
        assert st["tenants"]["clean"]["workers_dead"] == 0
        assert st["tenants"]["clean"]["restarts_total"] == 0
    assert reg.get(M.DEADLETTER_METER).count == n_poison
    assert fs.list_files("/ps/poison/deadletter")
    clean_rows = set()
    for f in fs.list_files("/ps/clean", extension=".parquet"):
        if "/tmp/" not in f:
            clean_rows.update(
                r["timestamp"]
                for r in pq.read_table(fs.open_read(f)).to_pylist())
    assert clean_rows == set(range(2000))


# -- schema evolution ---------------------------------------------------------

def test_schema_additive_evolution_reads_consistently_merged():
    """V1 files then V2 (one added optional field) in ONE tree: the
    merged-schema read (pyarrow promotion) stays consistent — old rows
    surface the new column as null, new rows carry it — and the
    cross-file audit reports the column as additive, not a conflict."""
    v1, v2 = sample_message_class(), sample_v2_class()
    broker = FakeBroker()
    broker.create_topic("evo", PARTS)
    fs = MemoryFileSystem()
    seed_tree(fs, "/evo/tree", v1, 500)  # the V1 era
    for i in range(500, 1000):  # the V2 era streams through a route
        m = v2(query=f"q-{i}", timestamp=i)
        m.extra_score = i * 2
        broker.produce("evo", m.SerializeToString(), partition=i % PARTS)
    mw = (base_builder(broker, fs)
          .route("evo", v2, "/evo/tree")
          .build())
    with mw:
        drain(mw, broker, {"evo": 500})
        assert mw.stats()["tenants"]["evo"]["state"] == "running"
    files = [f for f in fs.list_files("/evo/tree", extension=".parquet")
             if "/tmp/" not in f]
    assert len(files) >= 2
    tables = [pq.read_table(fs.open_read(f)) for f in files]
    merged = pa.concat_tables(tables, promote_options="permissive")
    assert "extra_score" in merged.schema.names
    by_ts = {r["timestamp"]: r for r in merged.to_pylist()}
    assert set(by_ts) == set(range(1000))
    assert by_ts[100]["extra_score"] is None       # V1 row: null
    assert by_ts[700]["extra_score"] == 1400       # V2 row: value
    audit = audit_schema_consistency(fs, "/evo/tree")
    assert audit["consistent"], audit["conflicts"]
    assert "extra_score" in audit["additive_columns"]


def test_schema_incompatible_route_dead_letters_with_typed_reason():
    """A route whose proto conflicts with its published tree (int64 ->
    string on one leaf) flips to dead_lettering at start(): every record
    lands in ITS dead-letter file with the typed reason surfaced, the
    tree gains no mixed-schema file, acks still commit (the stream keeps
    draining), and the sibling route is untouched."""
    v1, bad = sample_message_class(), sample_incompatible_class()
    broker = FakeBroker()
    broker.create_topic("tbad", PARTS)
    broker.create_topic("tok", PARTS)
    for i in range(300):
        broker.produce("tbad",
                       bad(query=f"q-{i}",
                           timestamp=str(i)).SerializeToString(),
                       partition=i % PARTS)
    produce(broker, "tok", v1, 1000)
    fs = MemoryFileSystem()
    seed_tree(fs, "/si/tree", v1, 200)
    files_before = set(fs.list_files("/si/tree", extension=".parquet"))
    mw = (base_builder(broker, fs)
          .route("tbad", bad, "/si/tree")
          .route("tok", v1, "/si/ok")
          .build())
    with mw:
        status = mw.route_status("tbad")
        assert status["state"] == "dead_lettering"
        assert status["reason_type"] == "SchemaIncompatibleError"
        assert "timestamp" in status["reason"]
        assert mw.route_status("tok")["state"] == "running"
        drain(mw, broker, {"tbad": 300, "tok": 1000})
        st = mw.stats()
        assert st["tenants"]["tbad"]["deadletter_records"] == 300
        assert st["tenants"]["tok"]["deadletter_records"] == 0
        assert st["tenants"]["tok"]["workers_dead"] == 0
    # the tree gained NO mixed-schema file; the audit stays clean
    files_after = set(fs.list_files("/si/tree", extension=".parquet"))
    assert {f for f in files_after if "/tmp/" not in f} == \
        {f for f in files_before if "/tmp/" not in f}
    assert audit_schema_consistency(fs, "/si/tree")["consistent"]
    assert fs.list_files("/si/tree/deadletter")


def test_cross_file_schema_audit_flags_planted_mixed_tree():
    """The PR-9 verifier's schema half: a partition tree holding the
    same leaf under two physical types is flagged with the column name
    and carrier files; a clean tree (and a merely-additive one) is not."""
    v1, bad = sample_message_class(), sample_incompatible_class()
    fs = MemoryFileSystem()
    seed_tree(fs, "/audit/tree", v1, 50, name="a.parquet")
    seed_tree(fs, "/audit/tree", v1, 50, name="b.parquet", start=50)
    clean = audit_schema_consistency(fs, "/audit/tree")
    assert clean["consistent"] and clean["files"] == 2
    # plant the conflicting file (timestamp: int64 in a/b, string here)
    props = Builder().proto_class(bad).writer_properties()
    tmp = "/audit/tree/tmp/x.tmp"
    fs.mkdirs("/audit/tree/tmp")
    pf = ParquetFile(fs, tmp, ProtoColumnarizer(bad), props, batch_size=64)
    pf.append_records([bad(query=f"q-{i}", timestamp=str(i))
                       for i in range(20)])
    pf.close()
    publish_file(fs, tmp, "/audit/tree/mixed.parquet", durable=False)
    audit = audit_schema_consistency(fs, "/audit/tree")
    assert not audit["consistent"]
    assert audit["files"] == 3
    cols = {c["column"] for c in audit["conflicts"]}
    assert "timestamp" in cols
    conflict = next(c for c in audit["conflicts"]
                    if c["column"] == "timestamp")
    assert any("mixed.parquet" in f
               for files in conflict["types"].values() for f in files)
    # file_schema surfaces the leaf map the audit is built from
    leaves = file_schema(fs, "/audit/tree/mixed.parquet")
    assert "timestamp" in leaves and "query" in leaves


# -- shared compaction service ------------------------------------------------

def test_shared_compaction_service_compacts_every_route():
    """ONE service thread drives both routes' compactors: small files in
    BOTH tenants' trees merge (inputs tombstoned, outputs verified), and
    the per-tenant compaction stats ride the MultiWriter snapshot."""
    cls = sample_message_class()
    broker = FakeBroker()
    for t in ("ca", "cb"):
        broker.create_topic(t, PARTS)
        produce(broker, t, cls, 5000, pad=80)
    fs = MemoryFileSystem()
    mw = (base_builder(broker, fs)
          .max_file_size(100 * 1024)
          .route("ca", cls, "/cp/ca",
                 compaction={"target_size": 512 * 1024,
                             "scan_interval_seconds": 0.2})
          .route("cb", cls, "/cp/cb",
                 compaction={"target_size": 512 * 1024,
                             "scan_interval_seconds": 0.2})
          .build())
    with mw:
        drain(mw, broker, {"ca": 5000, "cb": 5000})
        deadline = time.time() + 30
        merged = {}
        while time.time() < deadline:
            snap = mw.stats()["compaction"]["by_tenant"]
            merged = {t: snap[t]["merged"] for t in ("ca", "cb")}
            if all(v > 0 for v in merged.values()):
                break
            time.sleep(0.05)
        assert all(v > 0 for v in merged.values()), \
            f"shared service left a route uncompacted: {merged}"
    for t in ("ca", "cb"):
        # every row still readable exactly once per published tree
        got = {}
        for f in fs.list_files(f"/cp/{t}", extension=".parquet"):
            if "/tmp/" in f or "/compacted/" in f:
                continue
            for r in pq.read_table(fs.open_read(f)).to_pylist():
                got[r["timestamp"]] = got.get(r["timestamp"], 0) + 1
        assert set(got) == set(range(5000))
        assert all(c == 1 for c in got.values())
