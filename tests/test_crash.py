"""Process-level crash harness: SIGKILL a real writer process mid-run and
prove, via the INDEPENDENT structural verifier, that the at-least-once
contract survived the process boundary.

PR 3's chaos tests injected faults inside one process; everything here
crosses it.  A child writer process (tests/crash_child.py) streams records
over a LocalFileSystem with the durability discipline on, fsync'ing every
offset commit to an on-disk log before it becomes visible.  The parent
SIGKILLs the child at a seeded point mid-run, plants the torn-final /
stale-tmp debris a power cut would leave, restarts a fresh process over
the same directory, and then asserts mechanically from the bytes on disk:

* every logged (acked) offset's record lives in a structurally-VERIFIED
  published file (``kpw_tpu.io.verify`` — magic, footer, page walk, CRCs),
* no unverifiable file remains published (torn finals were quarantined,
  not deleted and not left published),
* abandoned tmp files were swept,
* the healed run drained to ack-lag 0.

The short smoke runs in tier-1; the multi-kill torture is ``slow``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from crash_child import (
    COMMIT_LOG,
    RECOVER_STATS,
    check_crash_invariant,
    published_files,
    read_commit_frontiers,
)

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "crash_child.py")


def _spawn(target_dir: str, rows: int, mode: str) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen([sys.executable, CHILD, target_dir,
                             str(rows), mode],
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _kill_after_publishes(proc: subprocess.Popen, target_dir: str,
                          n_files: int, timeout_s: float = 120) -> None:
    """SIGKILL the child once >= n_files are published AND at least one
    offset commit hit the durable log — the seeded kill point: mid-run,
    after real acks exist to check, before the stream drains."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            pytest.fail("victim exited before the kill window "
                        f"(rc={proc.returncode}) — raise rows")
        if (len(published_files(target_dir)) >= n_files
                and read_commit_frontiers(target_dir)):
            break
        time.sleep(0.02)
    else:
        proc.kill()
        pytest.fail("victim never published within the kill window")
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL


def _plant_debris(target_dir: str) -> tuple[str, str]:
    """The states a power cut can leave that a plain process SIGKILL
    cannot (the page cache survives process death): a TORN published
    final (its tail never reached the disk) and a stale tmp.  Returns
    (torn_final_name, stale_tmp_name)."""
    files = published_files(target_dir)
    assert files, "need at least one published file to tear"
    whole = open(files[0], "rb").read()
    torn_name = "19990101-000000000_crash_0.parquet"
    with open(os.path.join(target_dir, torn_name), "wb") as f:
        f.write(whole[: max(8, len(whole) // 3)])
        f.flush()
        os.fsync(f.fileno())
    tmp_dir = os.path.join(target_dir, "tmp")
    os.makedirs(tmp_dir, exist_ok=True)
    stale_tmp = "crash_0_424242.tmp"
    with open(os.path.join(tmp_dir, stale_tmp), "wb") as f:
        f.write(b"half a row group")
    return torn_name, stale_tmp


def _recover_and_check(tmp_path, rows: int, torn_name: str,
                       stale_tmp: str) -> dict:
    target = str(tmp_path)
    rc = _spawn(target, rows, "recover").wait(timeout=300)
    assert rc == 0, f"recover run failed rc={rc}"

    verdict = check_crash_invariant(target)
    # the tentpole invariant: every acked offset in a verified published
    # file; nothing unverifiable left published; tmps swept
    assert verdict["acked_but_missing"] == [], verdict
    assert verdict["unverifiable_published"] == [], verdict
    assert verdict["acked_offsets_checked"] > 0
    assert verdict["tmp_files_left"] == []
    assert verdict["invariant_holds"] is True
    # the torn final was quarantined — moved, never deleted, not published
    assert torn_name in verdict["quarantined_files"]
    assert not os.path.exists(os.path.join(target, torn_name))
    # the stale tmp was swept by recovery, not published
    assert not os.path.exists(os.path.join(target, "tmp", stale_tmp))
    # page CRCs were actually exercised (page_checksums on in the child)
    assert verdict["pages_crc_checked"] > 0

    stats = json.load(open(os.path.join(target, RECOVER_STATS)))
    assert stats["drained"] is True
    assert stats["ack"]["unacked_records"] == 0
    assert stats["recovery"]["quarantined"] >= 1
    assert stats["recovery"]["tmp_swept"] >= 1
    quarantined_paths = [q["path"] for q in
                         stats["recovery"]["manifest"]["quarantined_files"]]
    assert any(torn_name in p for p in quarantined_paths)
    # the healed run republished everything: every produced record present
    assert verdict["distinct_records"] == rows
    return verdict


def test_crash_smoke_kill9_at_least_once(tmp_path):
    """Tier-1: one SIGKILL after the first publish, planted power-cut
    debris, one recovery run — invariant checked from disk."""
    rows = 4000
    target = str(tmp_path)
    victim = _spawn(target, rows, "victim")
    _kill_after_publishes(victim, target, n_files=1)
    torn, stale = _plant_debris(target)
    _recover_and_check(tmp_path, rows, torn, stale)


def test_crash_mid_compaction_no_row_lost_no_duplicate(tmp_path):
    """Kill -9 mid-compaction, reconstructed as the exact on-disk states
    the compactor's write-ahead plan can be interrupted in (ISSUE 8
    satellite): a HALF-WRITTEN merged tmp from one crashed merge, plus a
    second merge crashed AFTER its publish with its inputs un-retired
    (duplicate-published finals).  Restart = compactor ``recover()`` +
    a real writer start() with ``verify_on_startup`` over the same dir.
    Assert from disk: zero rows lost, and no duplicate-published final
    survives startup verify."""
    import pyarrow.parquet as pq

    from kpw_tpu import Builder, Compactor, FakeBroker, FaultSchedule
    from kpw_tpu import FaultInjectingFileSystem, LocalFileSystem
    from kpw_tpu.io.verify import verify_dir

    from proto_helpers import sample_message_class
    from test_compact import _plant_partitioned_small_files, _props

    cls = sample_message_class()
    fs = LocalFileSystem()
    target = str(tmp_path)
    total = _plant_partitioned_small_files(fs, cls, per_dir=2,
                                           dirs=("k=0", "k=1"),
                                           root=target)

    # crash #1's debris: a half-written merged tmp (the kill landed
    # mid-rewrite; nothing was published, the inputs are intact)
    os.makedirs(f"{target}/tmp", exist_ok=True)
    with open(f"{target}/tmp/crashc_compact_99.tmp", "wb") as f:
        f.write(b"half a merged row group")
    # crash #2: a merge dies AFTER its durable publish, before the
    # retire (its _execute's retire renames fail) — the un-retired
    # inputs are duplicate-published finals until recovery
    sched = FaultSchedule(seed=2).fail_nth("rename", 3, count=2)
    crashing = Compactor(FaultInjectingFileSystem(fs, sched), target, cls,
                         _props(), target_size=1 << 20,
                         instance_name="crashc")
    summary = crashing.compact_once()
    assert summary["merged"] >= 1
    # the half-state exists right now: duplicates on disk
    dup_reports = verify_dir(fs, target)
    seen: dict[int, int] = {}
    for r in dup_reports:
        if not r.ok:
            continue
        for row in pq.read_table(r.path).to_pylist():
            seen[row["timestamp"]] = seen.get(row["timestamp"], 0) + 1
    assert any(v > 1 for v in seen.values()), "expected mid-crash dupes"

    # restart: recover() finishes/rolls back the plans, then a REAL
    # writer startup-verifies the directory (tombstones excluded)
    fresh = Compactor(fs, target, cls, _props(), target_size=1 << 20,
                      instance_name="crashc")
    rec = fresh.recover()
    assert rec["plans"] >= 1
    assert rec["tmp_swept"] >= 1  # the half-written merged tmp is gone

    broker = FakeBroker()
    broker.create_topic("crash", 1)
    w = (Builder().broker(broker).topic("crash").proto_class(cls)
         .target_dir(target).filesystem(fs).instance_name("crashc")
         .group_id("crash-g")
         .durability(False, verify_on_startup=True)
         .clean_abandoned_tmp(True).build())
    w.start()
    stats = w.stats()
    w.close()
    assert stats["recovery"]["quarantined"] == 0  # nothing left to condemn

    reports = verify_dir(fs, target)
    assert all(r.ok for r in reports)
    got: dict[int, int] = {}
    for r in reports:
        for row in pq.read_table(r.path).to_pylist():
            got[row["timestamp"]] = got.get(row["timestamp"], 0) + 1
    assert len(got) == total, "rows lost across the crash windows"
    assert all(v == 1 for v in got.values()), \
        "duplicate-published final survived startup verify"


@pytest.mark.slow
def test_crash_torture_double_kill(tmp_path):
    """Slow torture: kill a victim, start another victim over the same
    directory and kill IT too (crash during recovery), then heal — the
    invariant must hold across stacked crashes, with the commit log
    accumulating acks from both dead runs."""
    rows = 20_000
    target = str(tmp_path)
    victim = _spawn(target, rows, "victim")
    _kill_after_publishes(victim, target, n_files=2)
    frontier_1 = read_commit_frontiers(target)

    victim2 = _spawn(target, rows, "victim")
    _kill_after_publishes(victim2, target, n_files=4)
    frontier_2 = read_commit_frontiers(target)
    # the second run made progress past the first run's acks
    assert sum(frontier_2.values()) >= sum(frontier_1.values())

    torn, stale = _plant_debris(target)
    verdict = _recover_and_check(tmp_path, rows, torn, stale)
    assert verdict["acked_offsets_checked"] >= sum(frontier_2.values())
