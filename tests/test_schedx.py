"""Tests for the deterministic concurrency-schedule explorer
(kpw_tpu/utils/schedcheck.py + tools/schedx): the current tree runs
CLEAN across the committed seed set, the negative controls re-find the
PR-11/12 historical races from committed seeds with each fix reverted
test-locally, and every violation report carries a replayable seed plus
both participating stacks."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kpw_tpu.utils import schedcheck  # noqa: E402
from tools.schedx import SCENARIOS, load_seeds  # noqa: E402

SEEDS = load_seeds()


def test_committed_seed_file_matches_scenario_registry():
    """seeds.json and the SCENARIOS registry must agree exactly: a stale
    extra seed entry would inflate the doc-reconciled seed counts while
    never being explored; a missing one would skip a scenario."""
    assert set(SEEDS) == set(SCENARIOS)


# -- probe units (no threads) -------------------------------------------------

def test_probes_noop_when_uninstalled():
    assert schedcheck.active() is None
    schedcheck.point("anything")
    schedcheck.note_slot_recycled(1, 2)
    schedcheck.note_hb_sample(0, True, 0.0)
    schedcheck.note_uploader_spawn(9)
    schedcheck.note_death_notice(1, 2, True)  # all no-ops, no state


def test_double_recycle_probe_fires_with_both_stacks():
    c = schedcheck.install(seed=7)
    try:
        c.note_pool_reset(1, 4)
        c.note_slot_taken(1, 2)
        c.note_slot_recycled(1, 2)
        with pytest.raises(schedcheck.DoubleRecycleError) as ei:
            c.note_slot_recycled(1, 2)
        msg = str(ei.value)
        assert "seed 7" in msg
        assert "this observation" in msg and "first participant" in msg
        # both sections carry real stack frames, not placeholders
        assert msg.count("test_schedx.py") >= 2
        assert c.violations and c.violations[0] is ei.value
    finally:
        schedcheck.uninstall()


def test_hb_probe_guards_the_age_computation():
    c = schedcheck.install(seed=0)
    try:
        import time

        c.note_hb_write(3)
        c.note_hb_sample(3, True, time.monotonic())  # live stamp: fine
        with pytest.raises(schedcheck.HeartbeatTornReadError):
            c.note_hb_sample(3, True, 0.0)
    finally:
        schedcheck.uninstall()


def test_seeded_coins_are_deterministic_per_label():
    a = schedcheck.SchedCheck(seed=5)
    b = schedcheck.SchedCheck(seed=5)
    seq_a = [a._coin("x") for _ in range(8)] + [a._coin("y")]
    seq_b = [b._coin("x") for _ in range(8)] + [b._coin("y")]
    assert seq_a == seq_b
    c = schedcheck.SchedCheck(seed=6)
    assert [c._coin("x") for _ in range(8)] != seq_a[:8]


# -- the committed seed set runs clean on the current tree --------------------

@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_current_tree_clean_across_committed_seeds(scenario):
    """The acceptance gate: 0 violations on the current tree across the
    committed seed set — a new finding here is a real schedule bug (the
    report carries its replay seed)."""
    for seed in SEEDS[scenario]["seeds"]:
        checker = SCENARIOS[scenario](seed)
        assert not checker.violations, (
            scenario, seed, [str(v) for v in checker.violations])


# -- negative controls: reverted fixes must be re-found -----------------------

def _refound(scenario: str, exc_type) -> list:
    """Seeds (of the committed refind set) that re-find the historical
    race under the reverted fix; one retry per seed absorbs a box-load
    spike descheduling the racing party past even the widened margins."""
    hits = []
    for seed in SEEDS[scenario]["refind_seeds"]:
        for _attempt in range(2):
            checker = SCENARIOS[scenario](seed, revert=True)
            if checker.violations:
                assert isinstance(checker.violations[0], exc_type), \
                    checker.violations[0]
                hits.append((seed, checker.violations[0]))
                break
    return hits


def test_refinds_pr11_ring_double_free_with_fix_reverted():
    """Negative control #1: with drain_unfreed_slots reverted to its
    pre-fix shape (returns un-freed slots without marking them), the
    committed seeds re-find the stale-free/respawn double recycle."""
    hits = _refound("ring-free-respawn", schedcheck.DoubleRecycleError)
    assert len(hits) >= 2, "reverted double-free fix was not re-found"
    seed, v = hits[0]
    assert f"seed {seed}" in str(v)
    assert "this observation" in str(v) and "first participant" in str(v)


def test_refinds_pr11_heartbeat_torn_read_with_fix_reverted():
    """Negative control #2: with hb_publish's write ordering AND the
    stall() started_at guard reverted, the committed seeds re-find the
    pending-without-start torn read."""
    hits = _refound("heartbeat-torn-read", schedcheck.HeartbeatTornReadError)
    assert len(hits) >= 2, "reverted torn-read fix was not re-found"
    _seed, v = hits[0]
    assert "condemn a healthy child" in str(v)


def test_refinds_pr12_uploader_spawn_race_with_fix_reverted():
    hits = _refound("uploader-spawn-race", schedcheck.UploaderDuplicateError)
    assert len(hits) >= 2, "reverted uploader spawn fix was not re-found"


def test_refinds_pr11_stale_death_notice_with_fix_reverted():
    hits = _refound("stale-death-notice", schedcheck.StaleDeathNoticeError)
    assert len(hits) >= 2, "reverted death-notice pid check was not re-found"


def test_refinds_pr19_revoke_backout_vs_free_with_fix_reverted():
    """Negative control for the cross-process rebalance protocol: with
    backout_units reverted to a shape that ignores the commit-to-send /
    freed handshake, a revoked unit the child already freed is backed
    out anyway and the same ring slot recycles twice."""
    hits = _refound("proc-revoke-vs-free", schedcheck.DoubleRecycleError)
    assert len(hits) >= 2, "reverted revoke back-out fix was not re-found"


# -- CLI ----------------------------------------------------------------------

@pytest.mark.slow
def test_cli_smoke_exits_zero_on_clean_tree():
    """Duplicates ci.sh gate 8 exactly (a fresh-subprocess run of the
    committed smoke subset), so it is excluded from tier-1 — the in-
    process clean-sweep test above already covers the full seed set."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "tools.schedx", "--smoke"], cwd=REPO,
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all explored schedules clean" in proc.stdout


def test_cli_lists_scenarios():
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "tools.schedx", "--list"], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for name in SCENARIOS:
        assert name in proc.stdout
