"""Worker for test_multihost.py: one JAX process of a multi-process run.

Each process owns 4 virtual CPU devices; together they form an 8-device
global mesh whose collectives cross process boundaries (Gloo over
localhost) — the in-image stand-in for multi-host DCN (parallel/mesh.py:
"JAX process boundaries play the role of the reference's scale-out
consumer-group instances", KafkaProtoParquetWriter.java:72-76).

Runs the full sharded encode step over the global mesh and asserts this
process observes the GLOBAL dictionary (replicated output): the merged
sorted unique set of rows held by every process.
"""

import sys


def main() -> int:
    pid, n_proc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=n_proc, process_id=pid)
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kpw_tpu.parallel.sharded import sharded_encode_step

    n_shards = len(jax.devices())
    assert n_shards == 8 and len(jax.local_devices()) == 4
    mesh = Mesh(np.array(jax.devices()), ("shard",))
    C, per = 4, 512
    N = n_shards * per
    rng = np.random.default_rng(42)  # same seed in every process: full view
    vals = rng.integers(0, 300, (C, N)).astype(np.uint32)
    counts = np.full(n_shards, per, np.int32)

    row_sh = NamedSharding(mesh, P(None, "shard"))
    cnt_sh = NamedSharding(mesh, P("shard"))
    local = jax.make_array_from_process_local_data
    cols = N // n_proc
    lo = local(row_sh, vals[:, pid * cols:(pid + 1) * cols])
    hi = local(row_sh, np.zeros((C, cols), np.uint32))
    shards_per = n_shards // n_proc
    cnt = local(cnt_sh, counts[pid * shards_per:(pid + 1) * shards_per])

    packed, mhi, mlo, gk, rows, ovf = sharded_encode_step(
        hi, lo, cnt, mesh=mesh, cap=1024, width=16)
    gk = np.asarray(jax.device_get(gk))
    mlo_np = np.asarray(jax.device_get(mlo))
    assert int(np.asarray(jax.device_get(rows))) == N
    assert int(np.asarray(jax.device_get(ovf))) == 0
    for c in range(C):
        want = np.unique(vals[c])
        got = mlo_np[c][: int(gk[c])]
        assert np.array_equal(got, want), (c, got[:5], want[:5])
    print(f"MULTIHOST-OK proc={pid} k={[int(x) for x in gk]}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
