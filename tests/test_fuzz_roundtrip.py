"""Randomized cross-backend conformance sweep: for deterministic seeds,
generate a random flat schema (dtype mix, cardinalities, optionality),
random writer properties (codec, page size, dictionary/delta settings), and
assert (a) CPU, native, and TPU encoders produce byte-identical files and
(b) pyarrow reads back the exact content.  This is the property-style
complement of the targeted identity tests (SURVEY.md §4 rebuild mapping)."""

import io

import numpy as np
import pyarrow.parquet as pq
import pytest

from kpw_tpu.core import (Codec, ParquetFileWriter, Repetition, Schema,
                          WriterProperties, columns_from_arrays, leaf)
from kpw_tpu.core.pages import CpuChunkEncoder
from kpw_tpu.native.encoder import NativeChunkEncoder
from kpw_tpu.ops import TpuChunkEncoder


def _random_column(rng, n):
    kind = rng.integers(0, 7)
    if kind == 0:
        return "int64", rng.integers(0, int(rng.choice([4, 300, 1 << 50])),
                                     n).astype(np.int64)
    if kind == 1:
        return "int32", rng.integers(-(1 << 20), 1 << 20, n).astype(np.int32)
    if kind == 2:
        pool = rng.normal(size=int(rng.choice([8, 4000])))
        return "double", rng.choice(pool, n)
    if kind == 3:
        pool = rng.normal(size=16).astype(np.float32)
        return "float", rng.choice(pool, n).astype(np.float32)
    if kind == 4:
        return "boolean", rng.integers(0, 2, n).astype(bool)
    if kind == 5:  # low-cardinality strings
        k = int(rng.choice([3, 120]))
        return "string", [f"s{int(v)}".encode() for v in rng.integers(0, k, n)]
    # high-cardinality strings of varied length
    return "string", [f"{int(v):0{int(rng.integers(4, 28))}x}".encode()
                      for v in rng.integers(0, 1 << 40, n)]


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_cross_backend_identity_and_roundtrip(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([37, 1000, 6000]))
    ncols = int(rng.integers(2, 6))
    fields = []
    arrays = {}
    for c in range(ncols):
        tname, vals = _random_column(rng, n)
        name = f"c{c}"
        optional = bool(rng.integers(0, 2)) and tname != "boolean"
        if optional:
            valid = rng.integers(0, 2, n).astype(bool)
            fields.append(leaf(name, tname, Repetition.OPTIONAL))
            arrays[name] = (vals, valid)
        else:
            fields.append(leaf(name, tname))
            arrays[name] = vals
    schema = Schema(fields)
    props = WriterProperties(
        codec=int(rng.choice([Codec.UNCOMPRESSED, Codec.SNAPPY, Codec.ZSTD,
                              Codec.GZIP])),
        data_page_size=int(rng.choice([1024, 64 * 1024, 1 << 20])),
        enable_dictionary=bool(rng.integers(0, 2)),
        delta_fallback=bool(rng.integers(0, 2)),
    )

    def write(encoder_cls):
        encoder = encoder_cls(props.encoder_options())
        if encoder_cls is TpuChunkEncoder:
            encoder.min_device_rows = 1
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props, encoder=encoder)
        w.write_batch(columns_from_arrays(schema, arrays))
        w.close()
        return buf.getvalue()

    cpu = write(CpuChunkEncoder)
    assert write(NativeChunkEncoder) == cpu
    assert write(TpuChunkEncoder) == cpu

    table = pq.read_table(io.BytesIO(cpu))
    assert table.num_rows == n
    for c in range(ncols):
        name = f"c{c}"
        got = table[name].to_pylist()
        data = arrays[name]
        if isinstance(data, tuple):
            vals, valid = data
            want = [None if not ok else v
                    for v, ok in zip(_aslist(vals), valid)]
        else:
            want = _aslist(data)
        assert _norm(got) == _norm(want), name


def _aslist(vals):
    if isinstance(vals, list):
        return [v.decode() for v in vals]
    return list(vals)


def _norm(xs):
    out = []
    for x in xs:
        if isinstance(x, float):
            out.append(None if x != x else round(x, 9))
        elif isinstance(x, np.floating):
            out.append(None if x != x else round(float(x), 9))
        elif isinstance(x, (np.integer, np.bool_)):
            out.append(x.item())
        else:
            out.append(x)
    return out
