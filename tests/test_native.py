"""Native C++ codec library tests: correctness vs independent implementations
(pyarrow/libsnappy decode our snappy; zstandard decodes our zstd)."""

import ctypes
import os

import numpy as np
import pytest

from kpw_tpu import native
from kpw_tpu.core import compression as comp


@pytest.fixture(scope="module")
def lib():
    os.environ["KPW_TPU_NATIVE_REQUIRE"] = "1"
    try:
        out = native.lib()
    finally:
        os.environ.pop("KPW_TPU_NATIVE_REQUIRE", None)
    assert out is not None, "native library must build in this environment"
    return out


def _corpus():
    rng = np.random.default_rng(0)
    return [
        b"",
        b"a",
        b"abcabcabcabcabcabcabcabc" * 100,
        bytes(rng.integers(0, 256, 100_000, dtype=np.uint8)),  # incompressible
        bytes(rng.integers(0, 4, 100_000, dtype=np.uint8)),  # low entropy
        b"\x00" * 1_000_000,
        bytes(rng.integers(0, 256, 200_000, dtype=np.uint8)) * 3,  # cross-64KiB repeats
        ("the quick brown fox " * 10_000).encode(),
    ]


def test_snappy_self_roundtrip(lib):
    for data in _corpus():
        c = lib.snappy_compress(data)
        assert lib.snappy_decompress(c) == data


def test_snappy_cross_validated_by_system_libsnappy(lib):
    """Our from-scratch compressor's output must be decodable by the system
    snappy (and vice versa)."""
    ct = comp._load_snappy_ctypes()
    if not ct:
        pytest.skip("system libsnappy unavailable")
    for data in _corpus():
        ours = lib.snappy_compress(data)
        # system decode of our stream
        out_len = ctypes.c_size_t(0)
        assert ct.snappy_uncompressed_length(ours, len(ours), ctypes.byref(out_len)) == 0
        buf = ctypes.create_string_buffer(max(out_len.value, 1))
        assert ct.snappy_uncompress(ours, len(ours), buf, ctypes.byref(out_len)) == 0
        assert buf.raw[: out_len.value] == data
        # our decode of system stream
        max_len = ct.snappy_max_compressed_length(len(data))
        cbuf = ctypes.create_string_buffer(max(max_len, 1))
        clen = ctypes.c_size_t(max_len)
        assert ct.snappy_compress(data, len(data), cbuf, ctypes.byref(clen)) == 0
        assert lib.snappy_decompress(cbuf.raw[: clen.value]) == data


def test_snappy_compresses(lib):
    data = b"abab" * 50_000
    assert len(lib.snappy_compress(data)) < len(data) // 10


def test_zstd_cross_validated(lib):
    if not lib.has_zstd:
        pytest.skip("built without zstd")
    import zstandard

    for data in _corpus():
        ours = lib.zstd_compress(data)
        assert zstandard.ZstdDecompressor().decompress(ours) == data
        theirs = zstandard.ZstdCompressor(level=3).compress(data)
        assert lib.zstd_decompress(theirs) == data


def test_crc32c_known_vectors(lib):
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert lib.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert lib.crc32c(b"123456789") == 0xE3069283


def test_byte_array_plain_matches_python(lib):
    from kpw_tpu.core.encodings import byte_array_plain_encode

    values = [b"alpha", b"", b"x" * 300, b"beta"]
    data = b"".join(values)
    offsets = np.cumsum([0] + [len(v) for v in values])
    assert lib.byte_array_plain(data, offsets) == byte_array_plain_encode(values)


def test_byte_array_gather(lib):
    dict_vals = [b"aa", b"bbbb", b"c"]
    dict_data = b"".join(dict_vals)
    dict_offsets = np.cumsum([0] + [len(v) for v in dict_vals])
    idx = np.array([2, 0, 1, 1, 0], np.int32)
    want = b"".join(
        len(dict_vals[i]).to_bytes(4, "little") + dict_vals[i] for i in idx
    )
    assert lib.byte_array_gather(dict_data, dict_offsets, idx) == want


def test_parquet_file_with_native_snappy(lib, tmp_path):
    """End to end: page compressed by the native lib, read by pyarrow."""
    import pyarrow.parquet as pq

    from kpw_tpu.core import Codec, ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf

    schema = Schema([leaf("a", "int64"), leaf("s", "string")])
    vals = np.arange(50_000)
    strs = [f"row-{i % 100}".encode() for i in range(50_000)]
    path = tmp_path / "native.parquet"
    with open(path, "wb") as f:
        w = ParquetFileWriter(f, schema, WriterProperties(codec=Codec.SNAPPY))
        w.write_batch(columns_from_arrays(schema, {"a": vals, "s": strs}))
        w.close()
    t = pq.read_table(path)
    np.testing.assert_array_equal(t["a"].to_numpy(), vals)
    assert t["s"].to_pylist()[:3] == ["row-0", "row-1", "row-2"]


# ---------------------------------------------------------------------------
# native encode primitives (src/encode.cc) vs the numpy oracle
# ---------------------------------------------------------------------------

def test_native_rle_hybrid_matches_oracle(lib):
    from kpw_tpu.core import encodings as enc

    rng = np.random.default_rng(1)
    cases = [
        (np.zeros(0, np.uint32), 5),                      # empty
        (np.zeros(100, np.uint32), 0),                    # width 0
        (rng.integers(0, 2, 1000).astype(np.uint32), 1),  # booleans
        (rng.integers(0, 300, 10_000).astype(np.uint32), 9),   # no long runs
        (np.repeat(rng.integers(0, 16, 200), rng.integers(1, 50, 200)).astype(np.uint32), 4),  # run-heavy
        (np.concatenate([np.full(1000, 7), rng.integers(0, 8, 77)]).astype(np.uint32), 3),  # run then noise tail
        (rng.integers(0, 1 << 20, 5003).astype(np.uint32), 20),  # wide (>16) width
        (np.repeat([5, 5, 9], [4, 3, 12]).astype(np.uint32), 4),  # short runs only
    ]
    for values, width in cases:
        got = lib.rle_hybrid(values, width)
        want = enc.rle_hybrid_encode(values, width)
        assert got == want, f"width={width} n={len(values)}"
        if len(values):
            back = enc.rle_hybrid_decode(got, width, len(values))
            np.testing.assert_array_equal(back, values.astype(np.uint64))


def test_native_dict_build_matches_oracle(lib):
    from kpw_tpu.core import encodings as enc
    from kpw_tpu.core.schema import PhysicalType

    rng = np.random.default_rng(2)
    cols = [
        (rng.integers(0, 8, 10_000).astype(np.int64), PhysicalType.INT64),
        (rng.integers(-300, 300, 10_000).astype(np.int32), PhysicalType.INT32),  # negatives: bit-pattern order
        ((rng.integers(0, 3000, 10_000) / 100.0), PhysicalType.DOUBLE),
        (rng.integers(0, 1 << 40, 10_000).astype(np.int64), PhysicalType.INT64),  # high-card hash path
        (rng.integers(0, 100, 10_000).astype(np.float32), PhysicalType.FLOAT),
        (np.array([1.0, -1.0, 0.0, -0.0, np.nan, 1.0, np.nan]), PhysicalType.DOUBLE),  # nan/-0.0 bit patterns
    ]
    for values, pt in cols:
        key = values.view(np.uint32 if values.dtype.itemsize == 4 else np.uint64)
        d, idx = lib.dict_build(key)
        want_d, want_idx = enc.dictionary_build(values, pt)
        np.testing.assert_array_equal(d.view(values.dtype), want_d)
        np.testing.assert_array_equal(idx, want_idx)


def test_native_dict_build_max_k_abort(lib):
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1 << 40, 10_000).astype(np.uint64)  # ~all unique
    assert lib.dict_build(vals, max_k=100) is None
    low = rng.integers(0, 50, 10_000).astype(np.uint64)
    assert lib.dict_build(low, max_k=100) is not None
    # bounded-range path also aborts
    wide = rng.integers(0, 5000, 10_000).astype(np.uint64)
    assert lib.dict_build(wide, max_k=10) is None


def _random_table(rng, rows):
    return {
        "lo": rng.integers(0, 10, rows).astype(np.int64),
        "neg": rng.integers(-1000, 1000, rows).astype(np.int32),
        "f": (rng.integers(0, 500, rows) / 10.0),
        "hi": rng.integers(0, 1 << 50, rows).astype(np.int64),  # dict rejected
        "s": [f"tag-{i % 37}".encode() for i in range(rows)],   # python fallback
    }


def test_native_encoder_byte_identical_to_cpu():
    """File-level byte equality: NativeChunkEncoder vs the numpy oracle,
    covering dict, plain fallback (high cardinality), strings, floats."""
    import io

    from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf
    from kpw_tpu.core.pages import CpuChunkEncoder
    from kpw_tpu.native.encoder import NativeChunkEncoder

    rng = np.random.default_rng(4)
    arrays = _random_table(rng, 20_000)
    schema = Schema([
        leaf("lo", "int64"), leaf("neg", "int32"), leaf("f", "double"),
        leaf("hi", "int64"), leaf("s", "string"),
    ])
    props = WriterProperties()

    def run(encoder):
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props, encoder=encoder)
        w.write_batch(columns_from_arrays(schema, arrays))
        w.close()
        return buf.getvalue()

    opts = props.encoder_options()
    assert run(NativeChunkEncoder(opts)) == run(CpuChunkEncoder(opts))


def test_native_encoder_byte_identical_nullable_delta():
    """Nullable columns (def levels through native _levels_body) and the
    delta fallback config."""
    import io

    from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf
    from kpw_tpu.core.pages import CpuChunkEncoder
    from kpw_tpu.core.schema import Repetition
    from kpw_tpu.native.encoder import NativeChunkEncoder

    rng = np.random.default_rng(5)
    rows = 10_000
    vals = rng.integers(0, 1 << 45, rows).astype(np.int64)
    valid = rng.random(rows) >= 0.2
    schema = Schema([leaf("v", "int64", repetition=Repetition.OPTIONAL)])
    props = WriterProperties(delta_fallback=True)

    def run(encoder):
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props, encoder=encoder)
        w.write_batch(columns_from_arrays(schema, {"v": (vals, valid)}))
        w.close()
        return buf.getvalue()

    opts = props.encoder_options()
    assert run(NativeChunkEncoder(opts)) == run(CpuChunkEncoder(opts))


def test_backend_selection_cpu_platform():
    """On the CPU platform the auto selector must pick the native path."""
    from kpw_tpu.core.pages import EncoderOptions
    from kpw_tpu.native.encoder import NativeChunkEncoder
    from kpw_tpu.ops.backend import TpuChunkEncoder
    from kpw_tpu.runtime import select

    assert select.choose_backend() == "native"
    opts = EncoderOptions()
    assert isinstance(select.make_encoder(opts, "auto"), NativeChunkEncoder)
    assert isinstance(select.make_encoder(opts, "tpu"), TpuChunkEncoder)
    assert type(select.make_encoder(opts, "cpu")).__name__ == "CpuChunkEncoder"


def test_native_dict_build_full_span_keys(lib):
    """int64 keys 0 and -1 span the whole uint64 space: the bounded-range
    guard must not wrap (regression: heap overflow/segfault)."""
    from kpw_tpu.core import encodings as enc
    from kpw_tpu.core.schema import PhysicalType

    values = np.array([0, -1, 0, -1, 5, -1, 0], np.int64)
    d, idx = lib.dict_build(values.view(np.uint64))
    want_d, want_idx = enc.dictionary_build(values, PhysicalType.INT64)
    np.testing.assert_array_equal(d.view(np.int64), want_d)
    np.testing.assert_array_equal(idx, want_idx)


def test_native_dict_build_bytes_matches_oracle(lib):
    from kpw_tpu.core import encodings as enc
    from kpw_tpu.core.schema import PhysicalType

    rng = np.random.default_rng(7)
    cases = [
        [f"cat_{i:03d}".encode() for i in rng.integers(0, 100, 5000)],
        [b"", b"a", b"", b"ab", b"a", b"b" * 300, b""],  # empties + long
        [b"x\x00", b"x", b"x\x00\x00"],  # trailing NULs (oracle hash path)
        [bytes([b]) for b in rng.integers(0, 256, 4000)],  # all byte values
    ]
    for values in cases:
        data = b"".join(values)
        offsets = np.zeros(len(values) + 1, np.int64)
        np.cumsum([len(v) for v in values], out=offsets[1:])
        uniq_pos, idx = lib.dict_build_bytes(data, offsets)
        got_table = [values[p] for p in uniq_pos]
        want_table, want_idx = enc.dictionary_build(values, PhysicalType.BYTE_ARRAY)
        assert got_table == list(want_table)
        np.testing.assert_array_equal(idx, want_idx)


def test_native_dict_build_bytes_max_k_abort(lib):
    values = [f"u{i}".encode() for i in range(1000)]  # all unique
    data = b"".join(values)
    offsets = np.zeros(len(values) + 1, np.int64)
    np.cumsum([len(v) for v in values], out=offsets[1:])
    assert lib.dict_build_bytes(data, offsets, max_k=50) is None


def test_native_encoder_string_dictionary_identity():
    """String-heavy table: native byte-array dictionary vs the oracle at
    file level, including a high-cardinality column (rejected dict)."""
    import io

    from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf
    from kpw_tpu.core.pages import CpuChunkEncoder
    from kpw_tpu.native.encoder import NativeChunkEncoder

    rng = np.random.default_rng(8)
    rows = 15_000
    arrays = {
        "s_lo": [f"cat_{k:02d}".encode() for k in rng.integers(0, 60, rows)],
        "s_hi": [f"{v:028x}".encode() for v in rng.integers(0, 1 << 62, rows)],
        "s_nul": [(b"v\x00" if k else b"v") for k in rng.integers(0, 2, rows)],
    }
    schema = Schema([leaf("s_lo", "string"), leaf("s_hi", "string"),
                     leaf("s_nul", "string")])
    props = WriterProperties()

    def run(encoder):
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props, encoder=encoder)
        w.write_batch(columns_from_arrays(schema, arrays))
        w.close()
        return buf.getvalue()

    opts = props.encoder_options()
    assert run(NativeChunkEncoder(opts)) == run(CpuChunkEncoder(opts))


def test_native_delta_binary_packed_matches_oracle(lib):
    from kpw_tpu.core import encodings as enc

    rng = np.random.default_rng(9)
    cases64 = [
        np.array([], np.int64),
        np.array([42], np.int64),
        rng.integers(-(1 << 62), 1 << 62, 1000).astype(np.int64),  # wide deltas
        (1_700_000_000_000 + np.cumsum(rng.integers(0, 50, 777))).astype(np.int64),
        np.full(300, -5, np.int64),  # zero deltas
        np.array([0, (1 << 63) - 1, -(1 << 63), 17], np.int64),  # wraparound
    ]
    for v in cases64:
        assert lib.delta_binary_packed(v, 64) == enc.delta_binary_packed_encode(v, 64)
    cases32 = [
        rng.integers(-(1 << 30), 1 << 30, 1000).astype(np.int32),
        np.array([0, (1 << 31) - 1, -(1 << 31)], np.int32),
        np.arange(129, dtype=np.int32),  # exactly one block + 1
    ]
    for v in cases32:
        assert lib.delta_binary_packed(v, 32) == enc.delta_binary_packed_encode(v, 32)


def test_native_encoder_delta_identity():
    """delta_fallback config: native DELTA_BINARY_PACKED and
    DELTA_LENGTH_BYTE_ARRAY vs the oracle at file level."""
    import io

    from kpw_tpu.core import Codec, ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf
    from kpw_tpu.core.pages import CpuChunkEncoder
    from kpw_tpu.native.encoder import NativeChunkEncoder

    rng = np.random.default_rng(10)
    rows = 12_000
    arrays = {
        "ts": (1_700_000_000 + np.cumsum(rng.integers(0, 9, rows))).astype(np.int64),
        "i32": rng.integers(-(1 << 29), 1 << 29, rows).astype(np.int32),
        "u": [f"{v:024x}".encode() for v in rng.integers(0, 1 << 60, rows)],
    }
    schema = Schema([leaf("ts", "int64"), leaf("i32", "int32"), leaf("u", "string")])
    props = WriterProperties(codec=Codec.ZSTD, enable_dictionary=False,
                             delta_fallback=True)

    def run(encoder):
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props, encoder=encoder)
        w.write_batch(columns_from_arrays(schema, arrays))
        w.close()
        return buf.getvalue()

    opts = props.encoder_options()
    assert run(NativeChunkEncoder(opts)) == run(CpuChunkEncoder(opts))


def test_native_bytes_min_max(lib):
    from kpw_tpu.core.bytecol import ByteColumn

    rng = np.random.default_rng(11)
    values = [f"{v:08x}".encode() for v in rng.integers(0, 1 << 30, 3000)]
    values += [b"", b"\xff" * 40]
    col = ByteColumn.from_list(values)
    mn, mx = lib.bytes_min_max(col.data, col.offsets)
    assert col[mn] == min(values)
    assert col[mx] == max(values)


def test_native_encoder_threaded_identity():
    """encoder_threads > 1 must produce byte-identical files (offsets are
    shifted after parallel encode), across multiple row groups."""
    import io

    from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf
    from kpw_tpu.native.encoder import NativeChunkEncoder

    rng = np.random.default_rng(12)
    rows = 9000
    arrays = {
        "a": rng.integers(0, 50, rows).astype(np.int64),
        "b": rng.integers(0, 1 << 45, rows).astype(np.int64),
        "s": [f"v{k}".encode() for k in rng.integers(0, 80, rows)],
        "d": (rng.integers(0, 900, rows) / 7.0),
    }
    schema = Schema([leaf("a", "int64"), leaf("b", "int64"),
                     leaf("s", "string"), leaf("d", "double")])

    def run(threads):
        props = WriterProperties(encoder_threads=threads,
                                 row_group_size=120_000)
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props,
                              encoder=NativeChunkEncoder(props.encoder_options()))
        for _ in range(3):  # several batches -> multiple row groups
            w.write_batch(columns_from_arrays(schema, arrays))
        w.close()
        return buf.getvalue()

    seq = run(1)
    par = run(4)
    assert seq == par
    import pyarrow.parquet as pq

    md = pq.read_metadata(io.BytesIO(par))
    assert md.num_rows == rows * 3 and md.num_row_groups >= 2


def test_native_int_stats_matches_object_oracle(lib):
    """The fused min/max/gcd stats pass (kpw_int_stats_*, the affine
    dictionary planner's one host scan) against an overflow-proof
    object-dtype oracle: extremes of every supported dtype, even/odd
    strides (the divisionless divisibility check has separate power-of-two
    and odd-part legs), constant columns (gcd 0), and a randomized fuzz
    over scales up to 2^40."""
    rng = np.random.default_rng(57)
    cases = [
        (rng.integers(0, 5000, 4096) * 25 + 7).astype(np.int64),
        rng.integers(-(2**62), 2**62, 4096).astype(np.int64),
        np.array([-2**62, 2**62 - 1], np.int64),
        rng.integers(0, 2**63 + 5, 4096, dtype=np.uint64),  # >2^63 min/max
        rng.integers(0, 2**62, 4096, dtype=np.uint64) * np.uint64(3),
        rng.integers(-50, 50, 4096).astype(np.int32),
        rng.integers(0, 2**32 - 1, 4096, dtype=np.uint32),
        np.full(100, 42, np.int64),
        (rng.integers(0, 100, 4096) * 1024).astype(np.int64),  # 2^s stride
        (rng.integers(0, 100, 4096) * 768).astype(np.int64),   # 256 * 3
        np.array([0, 2**63], np.uint64),
    ]
    for t in range(100):
        n = int(rng.integers(1, 200))
        scale = int(rng.integers(1, 1 << int(rng.integers(1, 40))))
        base = int(rng.integers(-2**40, 2**40))
        cases.append((rng.integers(0, 1000, n) * scale + base).astype(np.int64))
    for arr in cases:
        st = lib.int_stats(arr)
        assert st is not None
        mn = int(arr.min())
        g_want = int(np.gcd.reduce(arr.astype(object) - mn))
        assert st[0] == mn and st[1] == int(arr.max()), (st, arr.dtype)
        assert st[2] == g_want, (st[2], g_want, arr.dtype)
    assert lib.int_stats(np.zeros(0, np.int64)) is None  # empty: caller falls back
    assert lib.int_stats(np.zeros(4, np.int16)) is None  # unsupported dtype
