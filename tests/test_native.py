"""Native C++ codec library tests: correctness vs independent implementations
(pyarrow/libsnappy decode our snappy; zstandard decodes our zstd)."""

import ctypes
import os

import numpy as np
import pytest

from kpw_tpu import native
from kpw_tpu.core import compression as comp


@pytest.fixture(scope="module")
def lib():
    os.environ["KPW_TPU_NATIVE_REQUIRE"] = "1"
    try:
        out = native.lib()
    finally:
        os.environ.pop("KPW_TPU_NATIVE_REQUIRE", None)
    assert out is not None, "native library must build in this environment"
    return out


def _corpus():
    rng = np.random.default_rng(0)
    return [
        b"",
        b"a",
        b"abcabcabcabcabcabcabcabc" * 100,
        bytes(rng.integers(0, 256, 100_000, dtype=np.uint8)),  # incompressible
        bytes(rng.integers(0, 4, 100_000, dtype=np.uint8)),  # low entropy
        b"\x00" * 1_000_000,
        bytes(rng.integers(0, 256, 200_000, dtype=np.uint8)) * 3,  # cross-64KiB repeats
        ("the quick brown fox " * 10_000).encode(),
    ]


def test_snappy_self_roundtrip(lib):
    for data in _corpus():
        c = lib.snappy_compress(data)
        assert lib.snappy_decompress(c) == data


def test_snappy_cross_validated_by_system_libsnappy(lib):
    """Our from-scratch compressor's output must be decodable by the system
    snappy (and vice versa)."""
    ct = comp._load_snappy_ctypes()
    if not ct:
        pytest.skip("system libsnappy unavailable")
    for data in _corpus():
        ours = lib.snappy_compress(data)
        # system decode of our stream
        out_len = ctypes.c_size_t(0)
        assert ct.snappy_uncompressed_length(ours, len(ours), ctypes.byref(out_len)) == 0
        buf = ctypes.create_string_buffer(max(out_len.value, 1))
        assert ct.snappy_uncompress(ours, len(ours), buf, ctypes.byref(out_len)) == 0
        assert buf.raw[: out_len.value] == data
        # our decode of system stream
        max_len = ct.snappy_max_compressed_length(len(data))
        cbuf = ctypes.create_string_buffer(max(max_len, 1))
        clen = ctypes.c_size_t(max_len)
        assert ct.snappy_compress(data, len(data), cbuf, ctypes.byref(clen)) == 0
        assert lib.snappy_decompress(cbuf.raw[: clen.value]) == data


def test_snappy_compresses(lib):
    data = b"abab" * 50_000
    assert len(lib.snappy_compress(data)) < len(data) // 10


def test_zstd_cross_validated(lib):
    if not lib.has_zstd:
        pytest.skip("built without zstd")
    import zstandard

    for data in _corpus():
        ours = lib.zstd_compress(data)
        assert zstandard.ZstdDecompressor().decompress(ours) == data
        theirs = zstandard.ZstdCompressor(level=3).compress(data)
        assert lib.zstd_decompress(theirs) == data


def test_crc32c_known_vectors(lib):
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert lib.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert lib.crc32c(b"123456789") == 0xE3069283


def test_byte_array_plain_matches_python(lib):
    from kpw_tpu.core.encodings import byte_array_plain_encode

    values = [b"alpha", b"", b"x" * 300, b"beta"]
    data = b"".join(values)
    offsets = np.cumsum([0] + [len(v) for v in values])
    assert lib.byte_array_plain(data, offsets) == byte_array_plain_encode(values)


def test_byte_array_gather(lib):
    dict_vals = [b"aa", b"bbbb", b"c"]
    dict_data = b"".join(dict_vals)
    dict_offsets = np.cumsum([0] + [len(v) for v in dict_vals])
    idx = np.array([2, 0, 1, 1, 0], np.int32)
    want = b"".join(
        len(dict_vals[i]).to_bytes(4, "little") + dict_vals[i] for i in idx
    )
    assert lib.byte_array_gather(dict_data, dict_offsets, idx) == want


def test_parquet_file_with_native_snappy(lib, tmp_path):
    """End to end: page compressed by the native lib, read by pyarrow."""
    import pyarrow.parquet as pq

    from kpw_tpu.core import Codec, ParquetFileWriter, Schema, WriterProperties
    from kpw_tpu.core import columns_from_arrays, leaf

    schema = Schema([leaf("a", "int64"), leaf("s", "string")])
    vals = np.arange(50_000)
    strs = [f"row-{i % 100}".encode() for i in range(50_000)]
    path = tmp_path / "native.parquet"
    with open(path, "wb") as f:
        w = ParquetFileWriter(f, schema, WriterProperties(codec=Codec.SNAPPY))
        w.write_batch(columns_from_arrays(schema, {"a": vals, "s": strs}))
        w.close()
    t = pq.read_table(path)
    np.testing.assert_array_equal(t["a"].to_numpy(), vals)
    assert t["s"].to_pylist()[:3] == ["row-0", "row-1", "row-2"]
