"""Child process + parent-side helpers for the kill -9 crash harness.

The process-level leg of the durability story (tests/test_crash.py and
``bench.py --crash``): a REAL writer process is SIGKILLed mid-run and the
at-least-once invariant is then checked from the bytes the dead process
left on disk.  The child runs a full writer over a LocalFileSystem with
the durability discipline on; its broker is a :class:`DurableCommitBroker`
whose offset commits are fsync'd to an on-disk commit log BEFORE they
become visible — so the log that survives the kill is exactly the set of
acks the invariant must account for (the writer acks only after publish,
so every logged offset's record must live in a published file).

Run as a script (the parent spawns it with subprocess):

    python crash_child.py <target_dir> <rows> victim   # killed by parent
    python crash_child.py <target_dir> <rows> recover  # heals + drains

``victim`` produces ``rows`` records and streams until the parent
SIGKILLs it (it exits 0 if it somehow finishes first — the parent treats
that as a missed kill window and asserts on it).  ``recover`` re-produces
the SAME records (redelivery-by-restart: none of the dead run's unacked
records were lost, and duplicates are allowed), starts over the same
directory with ``verify_on_startup`` + tmp sweep, drains to ack-lag 0,
and dumps its stats to ``recover_stats.json``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PARTS = 2
PAD = 150
INSTANCE = "crash"
GROUP = "crash-g"
COMMIT_LOG = "commits.log"
RECOVER_STATS = "recover_stats.json"


def make_broker_class():
    from kpw_tpu import FakeBroker

    class DurableCommitBroker(FakeBroker):
        """FakeBroker whose commits are fsync'd to ``log_path`` before
        they become visible.  Durability order matters: log-then-commit
        means a kill between the two leaves a logged offset that was
        never re-readable from the broker — but the writer only commits
        AFTER publish, so the logged offset's record is published either
        way and the invariant check stays sound (strictly harder, never
        weaker)."""

        def __init__(self, log_path: str) -> None:
            super().__init__()
            self._log_fd = os.open(log_path,
                                   os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                                   0o644)

        def commit(self, group, topic, partition, offset,
                   generation=None, member_id=None) -> None:
            os.write(self._log_fd, f"{partition} {offset}\n".encode())
            os.fsync(self._log_fd)
            super().commit(group, topic, partition, offset,
                           generation=generation, member_id=member_id)

    return DurableCommitBroker


def identity(partition: int, offset: int) -> int:
    """(partition, offset) -> record timestamp under round-robin produce."""
    return offset * PARTS + partition


def produce_all(broker, cls, rows: int) -> None:
    filler = "x" * PAD
    for i in range(rows):
        broker.produce("crash", cls(query=f"q-{i}-{filler}",
                                    timestamp=i).SerializeToString(),
                       partition=i % PARTS)


def build_writer(target_dir: str, broker, durability: bool = True):
    from kpw_tpu import Builder, LocalFileSystem, RetryPolicy

    from proto_helpers import sample_message_class

    b = (Builder().broker(broker).topic("crash")
         .proto_class(sample_message_class()).target_dir(target_dir)
         .filesystem(LocalFileSystem())
         .instance_name(INSTANCE).group_id(GROUP)
         .batch_size(128).page_checksums(True)
         .retry_policy(RetryPolicy(base_sleep=0.005, max_sleep=0.05))
         .clean_abandoned_tmp(True)
         .max_file_size(128 * 1024).block_size(16 * 1024)
         .max_file_open_duration_seconds(0.5))
    if durability:
        b.durability(True, verify_on_publish=False, verify_on_startup=True)
    return b.build()


# -- parent-side helpers (imported by test_crash.py and bench.py) -----------

def read_commit_frontiers(target_dir: str,
                          log_name: str = COMMIT_LOG) -> dict[int, int]:
    """Parse the durable commit log into {partition: max committed
    frontier} — the set of acks the invariant must account for."""
    path = os.path.join(target_dir, log_name)
    frontiers: dict[int, int] = {}
    if not os.path.exists(path):
        return frontiers
    for line in open(path):
        try:
            p, off = line.split()
            p, off = int(p), int(off)
        except ValueError:
            continue  # torn tail line: the kill landed mid-write
        frontiers[p] = max(frontiers.get(p, 0), off)
    return frontiers


def published_files(target_dir: str) -> list[str]:
    """Published .parquet paths — tmp/, quarantine/ and compacted/
    (retired compaction-input tombstones) excluded."""
    target = target_dir.rstrip("/")
    out = []
    for root, _dirs, files in os.walk(target):
        if (root.startswith(os.path.join(target, "tmp"))
                or root.startswith(os.path.join(target, "quarantine"))
                or root.startswith(os.path.join(target, "compacted"))):
            continue
        out.extend(os.path.join(root, f) for f in files
                   if f.endswith(".parquet"))
    return sorted(out)


def check_crash_invariant(target_dir: str) -> dict:
    """The mechanical post-crash verdict, computed from disk alone:
    every logged (acked) offset's record lives in a structurally-VERIFIED
    published file, no unverifiable file remains published, no tmp file
    survived recovery.  Returns a dict of evidence (raises nothing — the
    caller asserts on the fields)."""
    import pyarrow.parquet as pq

    from kpw_tpu.io.fs import LocalFileSystem
    from kpw_tpu.io.verify import verify_dir

    reports = verify_dir(LocalFileSystem(), target_dir)
    bad = [r for r in reports if not r.ok]
    got: dict[int, int] = {}
    for r in reports:
        if not r.ok:
            continue  # unverified files must not vouch for acked offsets
        for row in pq.read_table(r.path).to_pylist():
            got[row["timestamp"]] = got.get(row["timestamp"], 0) + 1
    frontiers = read_commit_frontiers(target_dir)
    missing = []
    acked = 0
    for p, frontier in frontiers.items():
        for off in range(frontier):
            acked += 1
            if got.get(identity(p, off), 0) < 1:
                missing.append((p, off))
    tmp_dir = os.path.join(target_dir, "tmp")
    tmps = (os.listdir(tmp_dir) if os.path.isdir(tmp_dir) else [])
    qdir = os.path.join(target_dir, "quarantine")
    quarantined = sorted(os.listdir(qdir)) if os.path.isdir(qdir) else []
    return {
        "published_files": len(reports),
        "verified_ok": len(reports) - len(bad),
        "unverifiable_published": [r.path for r in bad],
        "acked_offsets_checked": acked,
        "acked_but_missing": missing,
        "published_records": sum(got.values()),
        "distinct_records": len(got),
        "pages_crc_checked": sum(r.pages_crc_checked for r in reports),
        "tmp_files_left": tmps,
        "quarantined_files": quarantined,
        "invariant_holds": (not missing and not bad and acked > 0),
    }


# -- child entry points ------------------------------------------------------

def run_victim(target_dir: str, rows: int) -> int:
    from proto_helpers import sample_message_class

    cls = sample_message_class()
    broker = make_broker_class()(os.path.join(target_dir, COMMIT_LOG))
    broker.create_topic("crash", PARTS)
    produce_all(broker, cls, rows)
    w = build_writer(target_dir, broker)
    w.start()
    deadline = time.time() + 300
    while time.time() < deadline:  # run until SIGKILLed (or drained)
        if (sum(broker.committed(GROUP, "crash", p) for p in range(PARTS))
                >= rows and w.ack_lag()["unacked_records"] == 0):
            break
        time.sleep(0.01)
    w.close()
    return 0


def run_recover(target_dir: str, rows: int) -> int:
    from proto_helpers import sample_message_class

    cls = sample_message_class()
    # redelivery-by-restart: the healed instance re-serves the FULL topic
    # (its own commit log goes to a separate file so the parent's run-1
    # frontier read stays pristine)
    broker = make_broker_class()(
        os.path.join(target_dir, "commits_recover.log"))
    broker.create_topic("crash", PARTS)
    produce_all(broker, cls, rows)
    w = build_writer(target_dir, broker)
    w.start()
    deadline = time.time() + 300
    drained = False
    while time.time() < deadline:
        if (sum(broker.committed(GROUP, "crash", p) for p in range(PARTS))
                >= rows and w.ack_lag()["unacked_records"] == 0):
            drained = True
            break
        time.sleep(0.01)
    stats = w.stats()
    w.close()
    stats["drained"] = drained
    with open(os.path.join(target_dir, RECOVER_STATS), "w") as f:
        json.dump(stats, f, indent=1, default=repr)
    return 0 if drained else 3


def main(argv: list[str]) -> int:
    target_dir, rows, mode = argv[0], int(argv[1]), argv[2]
    os.makedirs(target_dir, exist_ok=True)
    if mode == "victim":
        return run_victim(target_dir, rows)
    if mode == "recover":
        return run_recover(target_dir, rows)
    print(f"unknown mode {mode!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
