"""Stage tracing (SURVEY.md §5: the rebuild's tracing/profiling subsystem)."""

import io

import numpy as np

from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties, columns_from_arrays, leaf
from kpw_tpu.ops import TpuChunkEncoder
from kpw_tpu.utils import StageTimer, set_tracer, stage


def test_stage_noop_without_tracer():
    set_tracer(None)
    with stage("anything"):
        pass  # must not raise or record


def test_stage_timing_pipeline():
    timer = StageTimer()
    set_tracer(timer)
    try:
        rng = np.random.default_rng(0)
        schema = Schema([leaf("a", "int64")])
        props = WriterProperties()
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props,
                              encoder=TpuChunkEncoder(props.encoder_options(), min_device_rows=1))
        w.write_batch(columns_from_arrays(
            schema, {"a": rng.integers(0, 50, 5000).astype(np.int64)}))
        w.close()
    finally:
        set_tracer(None)
    s = timer.summary()
    assert {"rowgroup.encode", "rowgroup.io_write",
            "encode.launch", "encode.assemble"} <= set(s)
    assert all(v["calls"] >= 1 and v["seconds"] >= 0 for v in s.values())
