"""Stage tracing (SURVEY.md §5: the rebuild's tracing/profiling subsystem)."""

import io
import json
import os
import threading
import time

import numpy as np

from kpw_tpu.core import ParquetFileWriter, Schema, WriterProperties, columns_from_arrays, leaf
from kpw_tpu.ops import TpuChunkEncoder
from kpw_tpu.utils import (
    STAGE_NAMES,
    SpanRecorder,
    StageTimer,
    set_span_recorder,
    set_tracer,
    stage,
)


def test_stage_noop_without_tracer():
    set_tracer(None)
    set_span_recorder(None)
    with stage("anything"):
        pass  # must not raise or record


def test_stage_timing_pipeline():
    timer = StageTimer()
    set_tracer(timer)
    try:
        rng = np.random.default_rng(0)
        schema = Schema([leaf("a", "int64")])
        props = WriterProperties()
        buf = io.BytesIO()
        w = ParquetFileWriter(buf, schema, props,
                              encoder=TpuChunkEncoder(props.encoder_options(), min_device_rows=1))
        w.write_batch(columns_from_arrays(
            schema, {"a": rng.integers(0, 50, 5000).astype(np.int64)}))
        w.close()
    finally:
        set_tracer(None)
    s = timer.summary()
    assert {"rowgroup.encode", "rowgroup.io_write",
            "encode.launch", "encode.assemble"} <= set(s)
    assert all(v["calls"] >= 1 and v["seconds"] >= 0 for v in s.values())
    # every stage name observed anywhere must be in the canonical registry
    assert set(s) <= set(STAGE_NAMES)


def test_stage_timer_min_max():
    t = StageTimer()
    t.record("x", 0.25)
    t.record("x", 0.05)
    t.record("x", 0.10)
    s = t.summary()["x"]
    assert s["calls"] == 3
    assert s["min"] == 0.05 and s["max"] == 0.25
    assert abs(s["seconds"] - 0.40) < 1e-12
    t.reset()
    assert t.summary() == {}


def test_stage_timer_threaded_exact_counts():
    """Concurrent recorders through the stage() seam: exact call counts,
    consistent totals/min/max under contention."""
    timer = StageTimer()
    recorder = SpanRecorder(capacity=10_000)
    set_tracer(timer)
    set_span_recorder(recorder)
    n_threads, n_calls = 8, 200

    def work(i: int) -> None:
        for k in range(n_calls):
            with stage("mt.shared"):
                pass
            with stage(f"mt.only{i}"):
                pass

    try:
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        set_tracer(None)
        set_span_recorder(None)
    s = timer.summary()
    assert s["mt.shared"]["calls"] == n_threads * n_calls
    for i in range(n_threads):
        assert s[f"mt.only{i}"]["calls"] == n_calls
    for v in s.values():
        assert 0 <= v["min"] <= v["max"] <= v["seconds"] + 1e-12
    # the span ring saw every call too (capacity was not exceeded)
    assert len(recorder) == 2 * n_threads * n_calls
    assert recorder.dropped == 0


def test_disabled_tracing_records_nothing():
    """The disabled hot path must leave the ring buffer empty: a recorder
    that exists but is not installed sees zero entries."""
    recorder = SpanRecorder()
    set_tracer(None)
    set_span_recorder(None)
    for _ in range(50):
        with stage("never.recorded", attr=1):
            pass
    assert len(recorder) == 0
    assert recorder.dropped == 0


def test_span_ring_bound_evicts_oldest():
    r = SpanRecorder(capacity=4)
    set_span_recorder(r)
    try:
        for i in range(10):
            with stage("ring.span", i=i):
                pass
    finally:
        set_span_recorder(None)
    assert len(r) == 4
    assert r.dropped == 6
    # the surviving spans are the MOST RECENT four
    kept = [s[5]["i"] for s in r.snapshot()]
    assert kept == [6, 7, 8, 9]


def test_chrome_trace_roundtrip():
    """Export -> json round trip with well-formed ph/ts/dur fields, thread
    labeling metadata, and attrs riding args."""
    r = SpanRecorder(capacity=64)
    set_span_recorder(r)
    try:
        with stage("trace.outer", rowgroup=3, rows=100):
            time.sleep(0.002)
        with stage("trace.inner"):
            pass
    finally:
        set_span_recorder(None)
    doc = json.loads(json.dumps(r.to_chrome_trace()))
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"trace.outer", "trace.inner"}
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["pid"] == os.getpid() and isinstance(e["tid"], int)
    outer = next(e for e in xs if e["name"] == "trace.outer")
    assert outer["args"] == {"rowgroup": 3, "rows": 100}
    assert outer["dur"] >= 2000  # slept 2 ms; dur is microseconds
    meta = [e for e in events if e["ph"] == "M"]
    assert meta and all(e["name"] in ("thread_name", "process_name")
                        for e in meta)
    assert any(e["name"] == "process_name" for e in meta)
    assert doc["otherData"]["spans_dropped"] == 0
