"""Scripted in-process stand-in for the ``kafka`` (kafka-python) package.

Installed into ``sys.modules`` by tests so ``kpw_tpu.ingest.kafka_client``
exercises its real seek/pause/resume/rebalance/commit logic against a
deterministic broker — the closest this image can get to the reference's
embedded-Kafka strategy (KafkaProtoParquetWriterTest.java:58-83).

Faithful bits of the kafka-python surface used by the adapter:
- ``KafkaConsumer(bootstrap_servers=..., **config)``, ``subscribe([topic],
  listener=...)``, ``poll(timeout_ms, max_records, update_offsets)``,
  ``assignment()``, ``position(tp)``, ``seek``, ``pause``, ``resume``,
  ``commit({tp: OffsetAndMetadata})``, ``committed(tp)``, ``close()``;
- group membership only makes progress inside ``poll()`` (the reason the
  adapter pumps unassigned members from ``generation()``);
- rebalance listeners fire inside ``poll()``;
- committing a partition the consumer does not currently own raises
  ``errors.CommitFailedError`` (the rebalance-window failure the adapter
  must survive).
"""

from __future__ import annotations

import threading
from collections import namedtuple

TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
ConsumerRecord = namedtuple(
    "ConsumerRecord", ["topic", "partition", "offset", "key", "value",
                       "timestamp"])


class ConsumerRebalanceListener:
    def on_partitions_revoked(self, revoked):
        pass

    def on_partitions_assigned(self, assigned):
        pass


class _Structs:
    class OffsetAndMetadata(namedtuple("OffsetAndMetadata",
                                       ["offset", "metadata", "leader_epoch"])):
        pass


structs = _Structs


class _Errors:
    class CommitFailedError(Exception):
        pass


errors = _Errors


class FakeCluster:
    """One broker shared by every consumer in the test (module-global so the
    adapter's plain ``KafkaConsumer(...)`` constructor finds it)."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.logs: dict[tuple[str, int], list[ConsumerRecord]] = {}
        self.partitions: dict[str, int] = {}
        self.committed: dict[tuple[str, str, int], int] = {}
        # (group, topic) -> membership generation bookkeeping
        self.members: dict[tuple[str, str], list["KafkaConsumer"]] = {}
        self.generation: dict[tuple[str, str], int] = {}

    def create_topic(self, topic: str, partitions: int) -> None:
        with self.lock:
            self.partitions[topic] = partitions
            for p in range(partitions):
                self.logs.setdefault((topic, p), [])

    def produce(self, topic: str, partition: int, value: bytes,
                key: bytes | None = None) -> None:
        with self.lock:
            log = self.logs[(topic, partition)]
            log.append(ConsumerRecord(topic, partition, len(log), key, value,
                                      1_700_000_000_000))

    # -- group protocol ----------------------------------------------------
    def join(self, consumer: "KafkaConsumer", topic: str) -> None:
        with self.lock:
            key = (consumer.group_id, topic)
            self.members.setdefault(key, []).append(consumer)
            self.generation[key] = self.generation.get(key, 0) + 1

    def leave(self, consumer: "KafkaConsumer", topic: str) -> None:
        with self.lock:
            key = (consumer.group_id, topic)
            if consumer in self.members.get(key, []):
                self.members[key].remove(consumer)
                self.generation[key] = self.generation.get(key, 0) + 1

    def assignment_for(self, consumer: "KafkaConsumer", topic: str):
        """Range assignment over the sorted membership."""
        with self.lock:
            key = (consumer.group_id, topic)
            members = sorted(self.members.get(key, []), key=id)
            if consumer not in members:
                return []
            n_parts = self.partitions.get(topic, 0)
            idx = members.index(consumer)
            per, extra = divmod(n_parts, len(members))
            start = idx * per + min(idx, extra)
            count = per + (1 if idx < extra else 0)
            return [TopicPartition(topic, p)
                    for p in range(start, start + count)]


CLUSTER = FakeCluster()


def reset_cluster() -> None:
    global CLUSTER
    CLUSTER = FakeCluster()


class KafkaConsumer:
    def __init__(self, bootstrap_servers=None, group_id=None,
                 enable_auto_commit=True, **config) -> None:
        assert enable_auto_commit is False, \
            "smart-commit invariant: auto commit must be forced off"
        self.group_id = group_id
        self.config = config
        self._topic: str | None = None
        self._listener: ConsumerRebalanceListener | None = None
        self._assignment: list[TopicPartition] = []
        self._seen_generation = -1
        self._positions: dict[TopicPartition, int] = {}
        self._paused: set[TopicPartition] = set()
        self._closed = False
        self.poll_calls = 0

    # -- membership --------------------------------------------------------
    def subscribe(self, topics, listener=None) -> None:
        (self._topic,) = topics
        self._listener = listener
        CLUSTER.join(self, self._topic)

    def _maybe_rebalance(self) -> None:
        """Group progress happens only here (inside poll), like the real
        client."""
        key = (self.group_id, self._topic)
        gen = CLUSTER.generation.get(key, 0)
        if gen == self._seen_generation:
            return
        new = CLUSTER.assignment_for(self, self._topic)
        if self._listener is not None and self._assignment:
            self._listener.on_partitions_revoked(list(self._assignment))
        self._assignment = new
        self._seen_generation = gen
        for tp in new:
            if tp not in self._positions:
                self._positions[tp] = CLUSTER.committed.get(
                    (self.group_id, tp.topic, tp.partition), 0)
        if self._listener is not None:
            self._listener.on_partitions_assigned(list(new))

    # -- consumption -------------------------------------------------------
    def poll(self, timeout_ms=0, max_records=500, update_offsets=True):
        if self._closed:
            raise RuntimeError("consumer closed")
        self.poll_calls += 1
        self._maybe_rebalance()
        out: dict[TopicPartition, list[ConsumerRecord]] = {}
        budget = max_records
        for tp in self._assignment:
            if budget <= 0:
                break
            if tp in self._paused:
                continue
            pos = self._positions.get(tp, 0)
            with CLUSTER.lock:
                recs = CLUSTER.logs.get((tp.topic, tp.partition), [])[
                    pos: pos + budget]
            if recs:
                out[tp] = list(recs)
                budget -= len(recs)
                if update_offsets:
                    self._positions[tp] = recs[-1].offset + 1
        return out

    def assignment(self):
        return set(self._assignment)

    def position(self, tp):
        if tp not in self._assignment:
            raise errors.CommitFailedError(f"not assigned: {tp}")
        return self._positions.get(tp, 0)

    def seek(self, tp, offset):
        self._positions[tp] = offset

    def pause(self, *tps):
        self._paused.update(tps)

    def resume(self, *tps):
        self._paused.difference_update(tps)

    def paused(self):
        return set(self._paused)

    # -- offsets -----------------------------------------------------------
    def commit(self, offsets) -> None:
        self._maybe_rebalance()  # a stale snapshot surfaces here, like real
        for tp, om in offsets.items():
            if tp not in self._assignment:
                raise errors.CommitFailedError(
                    f"{tp} not assigned to this consumer (generation moved)")
            with CLUSTER.lock:
                key = (self.group_id, tp.topic, tp.partition)
                CLUSTER.committed[key] = om.offset

    def committed(self, tp):
        with CLUSTER.lock:
            got = CLUSTER.committed.get((self.group_id, tp.topic, tp.partition))
        if got is None:
            return None
        return structs.OffsetAndMetadata(got, None, -1)

    def close(self) -> None:
        if self._topic is not None:
            CLUSTER.leave(self, self._topic)
        self._closed = True
