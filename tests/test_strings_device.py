"""Device-side BYTE_ARRAY dictionary probe (ops/strings.py): output must be
byte-identical to the CPU oracle across the tricky shapes — zero-padding
vs short strings, shared prefixes with divergent suffixes, empties, and
the cfg1 pool shape the bench probe measures."""

import numpy as np
import pytest

from kpw_tpu.core.bytecol import ByteColumn
from kpw_tpu.core.encodings import dictionary_build
from kpw_tpu.core.schema import PhysicalType
from kpw_tpu.ops.strings import device_string_dictionary, prefix_keys


def _check(values: list[bytes], max_k=None):
    col = ByteColumn.from_list(values)
    want = dictionary_build(values, PhysicalType.BYTE_ARRAY)
    got = device_string_dictionary(col, max_k=max_k)
    assert got is not None
    d, idx = got
    assert d == list(want[0])
    np.testing.assert_array_equal(idx, want[1])
    # reconstruct
    assert [d[i] for i in idx] == values


def test_cfg1_pool_shape():
    rng = np.random.default_rng(0)
    pool = [b"cat_%03d" % j for j in range(100)]
    _check([pool[k] for k in rng.integers(0, 100, 4096)])


def test_short_strings_and_zero_padding():
    # b"a" vs b"a\x00" vs b"a\x00\x00": same zero-padded prefix, distinct
    # lengths -> distinct keys; order: "a" < "a\x00" < "a\x00\x00"
    _check([b"a", b"a\x00", b"a\x00\x00", b"", b"a", b"b"] * 10)


def test_long_shared_prefix_tiebreak():
    # len >= 8 with identical first 7 bytes: one key group, host suffix sort
    vals = [b"prefix_AAA", b"prefix_BBB", b"prefix_", b"prefix_A",
            b"prefix_AAA", b"prefix_ABC", b"prefixZ"] * 7
    _check(vals)


def test_long_vs_exact7_order():
    # a 7-byte string sorts before every 8+ extension of it
    _check([b"abcdefg", b"abcdefgh", b"abcdefg!", b"abcdefg"] * 5)


def test_mixed_random_lengths():
    rng = np.random.default_rng(3)
    vals = [bytes(rng.integers(97, 123, rng.integers(0, 14)).astype(np.uint8))
            for _ in range(3000)]
    _check(vals)


def test_all_empty_strings():
    _check([b""] * 20)


def test_max_k_abort():
    vals = [b"v%06d" % i for i in range(100)]
    col = ByteColumn.from_list(vals)
    assert device_string_dictionary(col, max_k=10) is None


def test_prefix_keys_order_matches_bytes_order():
    rng = np.random.default_rng(5)
    vals = sorted(set(
        bytes(rng.integers(0, 256, rng.integers(0, 7)).astype(np.uint8))
        for _ in range(500)))
    keys = prefix_keys(ByteColumn.from_list(vals))
    assert (np.diff(keys.astype(np.int64)) > 0).all()
