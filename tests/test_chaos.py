"""Chaos tests: the at-least-once invariant under injected failure.

The reference's correctness protocol (write tmp -> close -> atomic rename ->
ack, KafkaProtoParquetWriter.java:325-351) promises that a record's offset
is acked only after the record is durably published.  These tests drive the
FULL writer through a seeded fault schedule — transient IO errors
mid-row-group, torn writes, rename failures on the publish step, broker
fetch/commit errors, forced rebalances, and fatal faults that kill workers —
and then assert the invariant *mechanically*:

* every acked offset's record appears in a published (renamed) file,
* no tmp file is ever counted as published,
* ack-lag drains to exactly 0 after faults stop.

A short seeded smoke variant runs in tier-1; the full torture run is marked
``slow``.
"""

import collections
import errno
import time

import pyarrow.parquet as pq
import pytest

from kpw_tpu import (
    Builder,
    FakeBroker,
    FaultInjectingBroker,
    FaultInjectingFileSystem,
    FaultSchedule,
    MemoryFileSystem,
    MetricRegistry,
    RetryPolicy,
    WriterFailedError,
)
from kpw_tpu.io.verify import verify_file

from proto_helpers import sample_message_class

TOPIC = "chaos"


@pytest.fixture(autouse=True)
def _lockcheck(lockcheck_detector):
    # the whole chaos suite runs under the runtime lock-order detector
    # (kpw_tpu/utils/lockcheck.py): every writer/consumer/broker lock the
    # tests create joins the live ordering graph, and a cycle or a
    # sleep-under-lock raises in the offending thread.  The tests'
    # assertions are unchanged; teardown additionally proves the run
    # recorded no violations (no new ordering cycles under fault
    # injection — ISSUE 7 acceptance).
    yield lockcheck_detector
    assert not lockcheck_detector.violations, [
        repr(v) for v in lockcheck_detector.violations]


@pytest.fixture(autouse=True)
def _schedcheck(schedcheck_checker):
    # the chaos suite also runs under the schedule explorer's invariant
    # probes (kpw_tpu/utils/schedcheck.py) with tiny seeded jitter at
    # the instrumented preemption points — same pattern as lockcheck:
    # assertions unchanged, zero violations required (ISSUE 13)
    yield schedcheck_checker
    assert not schedcheck_checker.violations, [
        repr(v) for v in schedcheck_checker.violations]


def produce_indexed(broker, cls, rows, parts, pad=0):
    """Produce ``rows`` records round-robin over ``parts`` partitions;
    returns {(partition, offset): timestamp} — the identity map the
    invariant check resolves acked offsets through.  ``pad`` fattens each
    record so chaos runs produce enough row-group write ops for the
    schedule's fault ordinals to actually fire."""
    identity = {}
    filler = "x" * pad
    for i in range(rows):
        m = cls(query=f"q-{i}-{filler}", timestamp=i)
        p, off = broker.produce(TOPIC, m.SerializeToString(),
                                partition=i % parts)
        identity[(p, off)] = i
    return identity


def published_timestamps(fs, target="/out"):
    """Multiset of record timestamps across PUBLISHED files only, plus the
    file list; asserts no tmp leaks into the published set — a .parquet
    living under the tmp dir (or a .tmp-suffixed listing survivor) is a
    publish-protocol violation, counted rather than silently filtered.
    Every published file must ALSO pass the independent structural
    verifier (magic, footer, page walk, CRCs) before its records may
    vouch for acked offsets: the invariant is "offsets present in VALID
    parquet", not merely "offsets present"."""
    all_parquet = fs.list_files(target, extension=".parquet")
    violations = [f for f in all_parquet
                  if f"{target}/tmp/" in f or f.endswith(".tmp")]
    assert violations == [], f"tmp counted as published: {violations}"
    got = collections.Counter()
    for f in all_parquet:
        rep = verify_file(fs, f)
        assert rep.ok, (
            f"published file fails structural verification: {f}: "
            f"{rep.errors}")
        for r in pq.read_table(fs.open_read(f)).to_pylist():
            got[r["timestamp"]] += 1
    return got, all_parquet


def assert_at_least_once_invariant(w, broker, fs, identity, parts,
                                   group="g"):
    """The mechanical invariant: acked offsets ⊆ published records, zero
    published tmp files, ack-lag drained to 0.  "Drained" is an
    eventually-property: a duplicate copy (rebalance re-fetch or
    supervised redelivery — at-least-once allows both) can still be
    mid-file after every ORIGINAL offset committed, so run_chaos's
    two-condition drain poll can break while lag is about to rise one
    last time.  Wait for lag to read 0 stably (longer than the 0.5 s
    time-rotation tail that publishes a straggler duplicate's file)
    before the strict zero assert."""
    deadline = time.time() + 15
    stable_since = None
    while time.time() < deadline:
        if w.ack_lag()["unacked_records"] == 0:
            if stable_since is None:
                stable_since = time.time()
            elif time.time() - stable_since >= 0.75:
                break
        else:
            stable_since = None
        time.sleep(0.05)
    got, files = published_timestamps(fs)
    total_committed = 0
    for p in range(parts):
        committed = broker.committed(group, TOPIC, p)
        total_committed += committed
        for off in range(committed):
            ts = identity[(p, off)]
            assert got[ts] >= 1, (
                f"offset {p}/{off} acked but record {ts} not published")
    lag = w.ack_lag()
    assert lag["unacked_records"] == 0 and lag["oldest_unacked_age_s"] == 0.0
    return got, files, total_committed


def run_chaos(rows, parts, threads, build_schedule, max_restarts=6,
              deadline_s=60, registry=None, expected_deaths=0):
    """Produce -> run the writer under the schedule -> stop faults ->
    drain -> return everything the invariant check needs."""
    broker = FakeBroker()
    broker.create_topic(TOPIC, parts)
    cls = sample_message_class()
    identity = produce_indexed(broker, cls, rows, parts, pad=150)

    sched = FaultSchedule(seed=7)
    rebalance_at = build_schedule(sched)
    inner = MemoryFileSystem()
    fs = FaultInjectingFileSystem(inner, sched)
    fb = FaultInjectingBroker(broker, sched,
                              rebalance_on_fetch=rebalance_at or ())

    b = (Builder().broker(fb).topic(TOPIC).proto_class(cls)
         .target_dir("/out").filesystem(fs).instance_name("chaos")
         .group_id("g").thread_count(threads).batch_size(64)
         .retry_policy(RetryPolicy(base_sleep=0.005, max_sleep=0.05))
         .supervise(True, max_restarts=max_restarts,
                    restart_backoff_seconds=0.01)
         # small row groups + files: many write/rename ops per run, so the
         # schedule's ordinals land mid-row-group and mid-publish
         .max_file_size(128 * 1024).block_size(16 * 1024)
         .max_file_open_duration_seconds(0.5))
    if registry is not None:
        b.metric_registry(registry)
    w = b.build()
    w.start()
    deadline = time.time() + deadline_s
    # phase 1: run under fire until everything has at least been written
    # AND the scheduled worker kills actually landed (the write-op faults
    # fire in the IO leg, which lags the written counter — disarming on
    # written-alone would skip the late ordinals)
    while time.time() < deadline:
        if (w.total_written_records >= rows
                and w._failed.count >= expected_deaths):
            break
        time.sleep(0.01)
    # phase 2: faults stop; the system must fully drain
    sched.stop()
    while time.time() < deadline:
        if (sum(broker.committed("g", TOPIC, p) for p in range(parts)) >= rows
                and w.ack_lag()["unacked_records"] == 0):
            break
        time.sleep(0.02)
    return w, broker, fs, sched, identity


def test_chaos_smoke_at_least_once():
    """Tier-1 seeded smoke: transient write/rename/fetch faults, one torn
    write, one forced rebalance, and one fatal ENOSPC worker kill — the
    invariant must hold and the supervisor must have restarted the
    worker."""
    rows, parts = 3000, 2
    reg = MetricRegistry()

    def schedule(s):
        # fatal rule FIRST: rules match in registration order, so a later
        # overlapping transient rule can never mask the kill
        s.fail_nth("write", 14, err=errno.ENOSPC)         # fatal: worker kill
        s.fail_nth("write", 5, count=2)                   # mid-row-group EIO
        s.fail_nth("write", 9, partial=0.5)               # torn write
        s.fail_nth("rename", 1)                           # publish fault
        s.fail_nth("fetch", 3, count=2)                   # poll errors
        s.fail_nth("commit", 1)                           # ack-path fault
        return (6,)                                       # rebalance mid-run

    w, broker, fs, sched, identity = run_chaos(rows, parts, 1, schedule,
                                               registry=reg,
                                               expected_deaths=1)
    try:
        got, files, committed = assert_at_least_once_invariant(
            w, broker, fs, identity, parts)
        assert committed >= rows  # everything eventually acked
        # nothing lost: every produced record is present (>=1 occurrences)
        assert set(got) == set(range(rows))
        stats = w.stats()
        assert stats["supervision"]["restarts_total"] >= 1  # the kill healed
        assert stats["meters"]["parquet.writer.failed"]["count"] >= 1
        assert stats["meters"]["parquet.writer.retries"]["count"] >= 1
        assert stats["healthy"] is True
        assert reg.get("parquet.writer.worker.restarts").count >= 1
        assert sched.fired()  # the schedule actually fired
    finally:
        w.close()


@pytest.mark.slow
def test_chaos_torture_at_least_once():
    """Full torture: two workers, heavier randomized (seeded) fault load —
    many transient IO faults, repeated rename failures, torn writes,
    broker errors, two worker kills, two rebalances, latency injection."""
    rows, parts = 40_000, 4

    def schedule(s):
        # fatal rules first (registration order = match priority)
        s.fail_nth("write", 70, err=errno.ENOSPC)         # worker kill 1
        s.fail_nth("write", 150, err=errno.ENOSPC)        # worker kill 2
        s.fail_random("write", 12, 400)                   # scattered EIO
        s.fail_nth("write", 31, partial=0.3)              # torn writes
        s.fail_nth("write", 57, partial=0.7)
        s.fail_nth("rename", 2, count=2)
        s.fail_nth("rename", 7)
        s.fail_random("fetch", 5, 200)
        s.fail_nth("commit", 2, count=2)
        s.delay_nth("write", 40, 0.05, count=3)           # latency injection
        s.delay_nth("fetch", 11, 0.05)
        return (10, 60)                                   # two rebalances

    w, broker, fs, sched, identity = run_chaos(rows, parts, 2, schedule,
                                               deadline_s=120,
                                               expected_deaths=2)
    try:
        got, files, committed = assert_at_least_once_invariant(
            w, broker, fs, identity, parts)
        assert committed >= rows
        assert set(got) == set(range(rows))
        stats = w.stats()
        assert stats["supervision"]["restarts_total"] >= 1
        assert len(files) >= 4  # rotation kept happening under fire
        # the schedule did real damage: faults fired across multiple ops
        ops_fired = {e["op"] for e in sched.fired()}
        assert {"write", "rename", "fetch"} <= ops_fired
    finally:
        w.close()


def test_worker_death_visible_without_supervision():
    """Satellite: a dead worker must be observable even when supervision
    was never enabled — healthy() flips false, the failed meter marks,
    stats carry the exit reason — and close() still succeeds (reference
    parity: no restart, no terminal error)."""
    broker = FakeBroker()
    broker.create_topic(TOPIC, 1)
    cls = sample_message_class()
    produce_indexed(broker, cls, 500, 1)
    sched = FaultSchedule(seed=1).fail_nth("write", 2, err=errno.EROFS)
    fs = FaultInjectingFileSystem(MemoryFileSystem(), sched)
    reg = MetricRegistry()
    w = (Builder().broker(broker).topic(TOPIC).proto_class(cls)
         .target_dir("/out").filesystem(fs).instance_name("nosup")
         .group_id("g").batch_size(32).metric_registry(reg)
         .retry_policy(RetryPolicy(base_sleep=0.005, max_sleep=0.02))
         .max_file_open_duration_seconds(0.2)
         .build())
    w.start()
    deadline = time.time() + 10
    while reg.get("parquet.writer.failed").count < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert reg.get("parquet.writer.failed").count == 1
    assert w.healthy() is False
    s = w.stats()
    assert s["supervision"]["enabled"] is False
    assert s["supervision"]["workers_dead"] == 1
    assert s["supervision"]["workers_alive"] == 0
    assert "EROFS" in s["workers"][0]["exit_reason"] \
        or "30" in s["workers"][0]["exit_reason"]  # errno.EROFS == 30
    assert reg.get("parquet.writer.workers.alive").value == 0.0
    w.close()  # must NOT raise without supervision


def test_restart_budget_exhausted_raises_on_close():
    """Satellite: with supervision on and a persistently failing sink, the
    restart budget runs out, healthy() goes false, and close() raises a
    terminal WriterFailedError instead of silently succeeding."""
    broker = FakeBroker()
    broker.create_topic(TOPIC, 1)
    cls = sample_message_class()
    produce_indexed(broker, cls, 100, 1)
    sched = FaultSchedule(seed=2).fail_forever_from("write", 1,
                                                    err=errno.ENOSPC)
    fs = FaultInjectingFileSystem(MemoryFileSystem(), sched)
    w = (Builder().broker(broker).topic(TOPIC).proto_class(cls)
         .target_dir("/out").filesystem(fs).instance_name("term")
         .group_id("g").batch_size(32)
         .retry_policy(RetryPolicy(base_sleep=0.005, max_sleep=0.01))
         .supervise(True, max_restarts=2, restart_backoff_seconds=0.01)
         .max_file_open_duration_seconds(0.2)
         .build())
    w.start()
    deadline = time.time() + 15
    while w._terminal is None and time.time() < deadline:
        time.sleep(0.02)
    assert w.healthy() is False
    s = w.stats()
    assert s["supervision"]["terminal_failure"] is not None
    assert s["supervision"]["restart_counts"] == [2]
    with pytest.raises(WriterFailedError, match="restart budget"):
        w.close()
    # nothing was ever acked: the records are intact for the next instance
    assert broker.committed("g", TOPIC, 0) == 0


def test_recovery_sweep_meters_swept_tmp():
    """Satellite: the startup recovery sweep counts what it GC'd — the
    swept-tmp meter and the stats recovery block agree with the planted
    leftovers."""
    broker = FakeBroker()
    broker.create_topic(TOPIC, 1)
    cls = sample_message_class()
    produce_indexed(broker, cls, 50, 1)
    fs = MemoryFileSystem()
    fs.mkdirs("/out/tmp")
    for p in ("/out/tmp/sweep_0_11.tmp", "/out/tmp/sweep_0_22.tmp",
              "/out/tmp/other_0_33.tmp"):
        with fs.open_write(p) as f:
            f.write(b"leftover")
    reg = MetricRegistry()
    w = (Builder().broker(broker).topic(TOPIC).proto_class(cls)
         .target_dir("/out").filesystem(fs).instance_name("sweep")
         .group_id("g").metric_registry(reg)
         .clean_abandoned_tmp(True)
         .max_file_open_duration_seconds(0.2)
         .build())
    with w:
        deadline = time.time() + 10
        while w.total_flushed_records < 50 and time.time() < deadline:
            time.sleep(0.01)
    assert reg.get("parquet.writer.tmp.swept").count == 2
    assert w.stats()["recovery"]["tmp_swept"] == 2
    # the foreign instance's tmp survived
    assert fs.exists("/out/tmp/other_0_33.tmp")
