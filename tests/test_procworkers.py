"""Process-parallel workers (runtime/procworkers.py): the shared-memory
batch handoff is byte-identical to thread-mode consumption, the full
poll → shred → encode → publish → ack leg works across the process
boundary, and the PR-3/4 at-least-once invariant survives a kill -9 of a
worker *process* — acked offsets ⊆ structurally-verified published
files, ack-lag drains to exactly 0, zero rows lost.

Every writer here runs real spawned subprocesses against a real on-disk
LocalFileSystem (the only sink that crosses a process boundary), so the
suite keeps row counts small; the kill test is the seeded smoke shape of
tests/test_chaos.py re-proven in process mode."""

import collections
import glob
import os
import signal
import time

import numpy as np
import pyarrow.parquet as pq
import pytest

from kpw_tpu import Builder, FakeBroker, LocalFileSystem, MetricRegistry
from kpw_tpu.ingest.broker import RecordBatch
from kpw_tpu.io.verify import verify_file
from kpw_tpu.runtime.procworkers import ShmBatchRing
from proto_helpers import sample_message_class

TOPIC = "procs"


@pytest.fixture(autouse=True)
def _schedcheck(schedcheck_checker):
    """Module autouse: every process-mode test runs with the schedule
    explorer's invariant probes live in the parent (ring double-recycle,
    heartbeat torn-read, death-notice pid check) and tiny seeded jitter
    at the dispatcher/collector preemption points — assertions below run
    unchanged, and any probe violation fails the test here."""
    yield schedcheck_checker
    assert not schedcheck_checker.violations, [
        repr(v) for v in schedcheck_checker.violations]


def produce_indexed(broker, cls, rows, parts, pad=0):
    identity = {}
    filler = "x" * pad
    for i in range(rows):
        m = cls(query=f"q-{i}-{filler}", timestamp=i)
        p, off = broker.produce(TOPIC, m.SerializeToString(),
                                partition=i % parts)
        identity[(p, off)] = i
    return identity


def build_proc_writer(broker, cls, target, procs=2, **kw):
    b = (Builder().broker(broker).topic(TOPIC).proto_class(cls)
         .target_dir(target).filesystem(LocalFileSystem())
         .instance_name("procw").group_id("g")
         .process_workers(procs, **kw.pop("proc_kw", {}))
         .max_file_size(256 * 1024)
         .max_file_open_duration_seconds(0.3))
    for name, val in kw.items():
        getattr(b, name)(val)
    return b


def drain(w, broker, rows, parts, deadline_s=90):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if (sum(broker.committed("g", TOPIC, p) for p in range(parts))
                >= rows and w.ack_lag()["unacked_records"] == 0):
            return True
        time.sleep(0.05)
    return False


def published_timestamps(target):
    """Timestamp multiset over published files only — every file must
    pass the independent structural verifier first (the invariant is
    'offsets present in VALID parquet')."""
    fs = LocalFileSystem()
    got = collections.Counter()
    files = [f for f in glob.glob(f"{target}/**/*.parquet", recursive=True)
             if f"{target}/tmp/" not in f]
    for f in files:
        rep = verify_file(fs, f)
        assert rep.ok, (f, rep.errors)
        for r in pq.read_table(f).to_pylist():
            got[r["timestamp"]] += 1
    return got, files


# -- the handoff itself -------------------------------------------------------

def test_shm_ring_roundtrip_byte_identical():
    """A batch staged into a ring slot reads back bit-for-bit: payload
    window, rebased offsets, and run metadata all survive the crossing —
    the handoff is lossless by construction."""
    ring = ShmBatchRing(4, 1 << 16)
    try:
        payloads = [f"record-{i}".encode() * (i % 5 + 1) for i in range(64)]
        lens = np.fromiter(map(len, payloads), np.int64, count=64)
        offs = np.zeros(65, np.int64)
        np.cumsum(lens, out=offs[1:])
        blob = b"".join(payloads)
        rb = RecordBatch(TOPIC, 3, 1000, blob, offs)
        # stage a nonzero-base slice window too (a fetch-slice shape)
        win = rb.slice(10, 40)
        n = ring.write_slot(2, win.partition, win.start_offset,
                            win.offsets, win.payload)
        assert n == 40
        part, start, count, r_offs, r_payload, ingest_us = ring.read_slot(2)
        assert (part, start, count) == (3, 1010, 40)
        assert ingest_us == 0  # write_slot (no parts) carries no stamp
        assert r_offs[0] == 0
        base = int(win.offsets[0])
        assert bytes(r_payload) == blob[base: int(win.offsets[-1])]
        np.testing.assert_array_equal(np.asarray(r_offs),
                                      np.asarray(win.offsets) - base)
        for i in range(count):
            assert bytes(r_payload[int(r_offs[i]): int(r_offs[i + 1])]) \
                == win.payload_at(i)
        # release the slot views before close: the mmap cannot unmap
        # under exported pointers
        del r_offs, r_payload
    finally:
        ring.close()
        ring.unlink()


def test_proc_handoff_shreds_byte_identical_to_thread_mode():
    """The acceptance pin: a batch consumed THROUGH the ring (the child's
    zero-copy view path) shreds to the exact same columnar bytes as the
    thread-mode direct path over the same RecordBatch."""
    from kpw_tpu.models.proto_bridge import ProtoColumnarizer

    cls = sample_message_class()
    col = ProtoColumnarizer(cls)
    payloads = [cls(query=f"q-{i}", timestamp=i,
                    page_number=i % 7).SerializeToString()
                for i in range(500)]
    lens = np.fromiter(map(len, payloads), np.int64, count=len(payloads))
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    blob = b"".join(payloads)

    direct = col.columnarize_buffer(blob, offs)  # thread-mode consumption

    ring = ShmBatchRing(2, 1 << 20)
    try:
        ring.write_slot(0, 0, 0, offs, blob)
        _, _, _, r_offs, r_payload, _ = ring.read_slot(0)
        via_ring = col.columnarize_buffer(r_payload, r_offs)
        assert via_ring.num_rows == direct.num_rows
        from kpw_tpu.core.bytecol import ByteColumn

        for a, b in zip(direct.chunks, via_ring.chunks):
            va, vb = a.values, b.values
            if isinstance(va, ByteColumn):
                assert bytes(va.data) == bytes(vb.data)
                np.testing.assert_array_equal(va.offsets, vb.offsets)
            else:
                np.testing.assert_array_equal(va, vb)
            if a.def_levels is None:
                assert b.def_levels is None
            else:
                np.testing.assert_array_equal(a.def_levels, b.def_levels)
        del r_offs, r_payload  # release slot views before the unmap
    finally:
        ring.close()
        ring.unlink()


def test_ring_rejects_oversized_batch():
    ring = ShmBatchRing(2, 8192)
    try:
        big = b"z" * 9000
        offs = np.array([0, len(big)], np.int64)
        with pytest.raises(ValueError, match="slot capacity"):
            ring.write_slot(0, 0, 0, offs, big)
    finally:
        ring.close()
        ring.unlink()


# -- build() validation -------------------------------------------------------

def test_process_mode_build_validation():
    from kpw_tpu import MemoryFileSystem

    cls = sample_message_class()
    broker = FakeBroker()

    def base():
        return (Builder().broker(broker).topic(TOPIC).proto_class(cls)
                .target_dir("/out"))

    with pytest.raises(ValueError, match="LocalFileSystem"):
        base().filesystem(MemoryFileSystem()).process_workers(2).build()
    with pytest.raises(ValueError, match="partition_by"):
        (base().filesystem(LocalFileSystem()).process_workers(2)
         .partition_by("query").build())
    with pytest.raises(ValueError, match="backends"):
        (base().filesystem(LocalFileSystem()).process_workers(2)
         .encoder_backend("mesh").build())
    # a transforming parser would be silently ignored by the children
    with pytest.raises(ValueError, match="custom parser"):
        (base().filesystem(LocalFileSystem()).process_workers(2)
         .parser(lambda b: cls.FromString(b)).build())

    class NotAProto:
        @staticmethod
        def FromString(raw):
            return raw

    with pytest.raises(ValueError, match="DESCRIPTOR"):
        (Builder().broker(broker).topic(TOPIC).proto_class(NotAProto)
         .target_dir("/out").filesystem(LocalFileSystem())
         .process_workers(2).build())


# -- end to end ---------------------------------------------------------------

def test_process_mode_end_to_end(tmp_path):
    """2 worker processes drain a seeded replay to ack-lag exactly 0:
    every produced row lands in a structurally-verified published file,
    every offset commits, and the process-mode observability block
    (per-child rss, ring occupancy, registered `worker.proc.*` gauges)
    is live."""
    rows, parts = 3000, 2
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic(TOPIC, parts)
    produce_indexed(broker, cls, rows, parts)
    reg = MetricRegistry()
    target = str(tmp_path / "out")
    w = build_proc_writer(broker, cls, target,
                          metric_registry=reg).build()
    w.start()
    try:
        assert drain(w, broker, rows, parts), w.ack_lag()
        got, files = published_timestamps(target)
        assert set(got) == set(range(rows))  # nothing lost
        assert w.total_written_records >= rows
        assert w.healthy() is True
        s = w.stats()
        procs = s["procs"]
        assert procs["workers"] == 2
        assert procs["ring"]["free"] == procs["ring"]["slots"]
        assert procs["dispatched_units"] >= 1
        assert procs["acked_units"] == procs["dispatched_units"]
        for child in procs["children"]:
            assert child["alive"] is True
            assert child["rss_bytes"] > 0
        assert reg.get("worker.proc.alive").value == 2.0
        assert reg.get("worker.proc.ring.slots").value == \
            procs["ring"]["slots"]
        assert reg.get("worker.proc.inflight.records").value == 0.0
        assert reg.get("worker.proc.rss.bytes").value > 0
        # both worker indices actually published (real parallelism)
        writers = {f.rsplit("_", 1)[-1].split(".")[0].split("-")[0]
                   for f in files}
        assert len(writers) == 2, files
    finally:
        w.close()


def test_process_worker_kill9_at_least_once(tmp_path):
    """The PR-3/4 invariant re-proven across the process boundary: a
    seeded replay with one worker process SIGKILLed mid-run must end
    with acked offsets ⊆ verified published files, ack-lag drained to
    exactly 0, and 0 rows lost; the supervisor restarts the slot and the
    redelivered runs flow through the ring again."""
    rows, parts = 8000, 2
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic(TOPIC, parts)
    identity = produce_indexed(broker, cls, rows, parts, pad=100)
    target = str(tmp_path / "out")
    w = build_proc_writer(broker, cls, target).supervise(
        True, max_restarts=3, restart_backoff_seconds=0.05).build()
    w.start()
    try:
        # let the stream get going, then kill -9 one child process
        deadline = time.time() + 45
        while (time.time() < deadline
               and w.total_written_records < rows // 4):
            time.sleep(0.01)
        victim = w._workers[0].pid
        assert victim is not None
        os.kill(victim, signal.SIGKILL)
        assert drain(w, broker, rows, parts), w.ack_lag()
        got, _files = published_timestamps(target)
        # acked ⊆ published (resolve every committed offset through identity)
        for p in range(parts):
            committed = broker.committed("g", TOPIC, p)
            for off in range(committed):
                ts = identity[(p, off)]
                assert got[ts] >= 1, (
                    f"offset {p}/{off} acked but record {ts} missing")
        assert set(got) == set(range(rows))  # zero rows lost
        lag = w.ack_lag()
        assert lag["unacked_records"] == 0
        s = w.stats()
        assert s["supervision"]["restarts_total"] >= 1
        assert s["meters"]["parquet.writer.failed"]["count"] >= 1
        assert s["consumer"]["redelivered_records"] >= 0
        assert w.healthy() is True
    finally:
        w.close()


def test_watchdog_condemn_kills_and_restarts_child(tmp_path):
    """Process-mode watchdog promotion: condemning a (simulated) hung
    child SIGKILLs the process — the slot is actually reclaimed, unlike
    a parked thread — and the supervisor restarts it with held runs
    redelivered; the stream still drains to zero loss."""
    rows, parts = 4000, 2
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic(TOPIC, parts)
    produce_indexed(broker, cls, rows, parts, pad=80)
    target = str(tmp_path / "out")
    w = (build_proc_writer(broker, cls, target)
         .supervise(True, max_restarts=3, restart_backoff_seconds=0.05)
         .watchdog(True, io_stall_deadline_seconds=30.0,
                   abandon_stalled=True)
         .build())
    w.start()
    try:
        deadline = time.time() + 45
        while (time.time() < deadline
               and w.total_written_records < rows // 8):
            time.sleep(0.01)
        slot = w._workers[0]
        victim_pid = slot.pid
        # simulate the watchdog crossing the deadline on this slot
        w._on_watchdog_stall(0, slot, 99.0, "publish")
        assert slot.condemned and slot.failed
        assert drain(w, broker, rows, parts), w.ack_lag()
        # the condemned process is really gone and the slot was respawned
        assert not slot.alive()
        fresh = w._workers[0]
        assert fresh is not slot and fresh.pid != victim_pid
        got, _ = published_timestamps(target)
        assert set(got) == set(range(rows))
        s = w.stats()
        assert s["supervision"]["restarts_total"] >= 1
        assert s["meters"]["parquet.writer.stalled"]["count"] >= 1
    finally:
        w.close()


def test_dispatcher_splits_oversized_batches(tmp_path):
    """Batches wider than one ring slot split into multiple units and
    still drain losslessly (tiny 8 KiB slots force splitting)."""
    rows, parts = 1200, 1
    cls = sample_message_class()
    broker = FakeBroker()
    broker.create_topic(TOPIC, parts)
    produce_indexed(broker, cls, rows, parts, pad=200)
    target = str(tmp_path / "out")
    w = build_proc_writer(
        broker, cls, target, procs=1,
        proc_kw={"ring_slots": 4, "slot_bytes": 8192}).build()
    w.start()
    try:
        assert drain(w, broker, rows, parts), w.ack_lag()
        got, _ = published_timestamps(target)
        assert set(got) == set(range(rows))
        # ~240 B/record against 8 KiB slots: the fetch batches HAD to split
        assert w.stats()["procs"]["dispatched_units"] > 4
    finally:
        w.close()
