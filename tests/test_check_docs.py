"""tools/check_docs.py cited-artifact-key reconciliation (VERDICT r5 ask
#2): a doc sentence claiming a key is recorded in the sweep artifact must
fail when the key does not exist there, stay silent for keys that do,
skip explicit pending-next-sweep promises, and never treat a code
identifier in neutral prose as a claim."""

import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "check_docs", os.path.join(HERE, os.pardir, "tools", "check_docs.py"))
check_docs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_docs)


RECORD = {"configs": {"config2": {"vs_dist": {"median": 2.0},
                                  "projected_system": {"median": {}}}}}


def _failures(text: str) -> list:
    docs = {f: "" for f in check_docs.KEY_DOCS}
    docs["PARITY.md"] = text
    return check_docs.check_cited_keys(RECORD, docs)


def test_flags_absent_cited_key():
    out = _failures("the win is recorded as `encode_side_vs_baseline` "
                    "in the artifact.")
    assert len(out) == 1 and "encode_side_vs_baseline" in out[0]


def test_present_key_passes():
    assert _failures("recorded as `vs_dist` in the artifact.") == []


def test_pending_claim_is_exempt():
    assert _failures("will be recorded as the `writer_route` block, "
                     "pending the next sweep.") == []


def test_neutral_code_identifier_not_a_claim():
    assert _failures("tune `encoder_threads` to size the pool.") == []


# --- cited stage/metric-name reconciliation (observability PR) -------------

NAMES = {"rowgroup.encode", "rowgroup.assemble",
         "parquet.writer.file.size", "parquet.writer.ack.lag.records"}


def _name_failures(text: str) -> list:
    docs = {f: "" for f in check_docs.NAME_DOCS}
    docs["PARITY.md"] = text
    return check_docs.check_cited_names(docs, names=NAMES)


def test_unknown_stage_name_flagged():
    out = _name_failures("host work hides in the `rowgroup.asemble` stage.")
    assert len(out) == 1 and "rowgroup.asemble" in out[0]


def test_unknown_metric_name_flagged():
    out = _name_failures("watch `parquet.writer.ack.lag.seconds` climb.")
    assert len(out) == 1 and "parquet.writer.ack.lag.seconds" in out[0]


def test_known_names_pass():
    assert _name_failures(
        "`rowgroup.encode` feeds `parquet.writer.file.size`; the "
        "`parquet.writer.ack.lag.records` gauge drains to 0.") == []


def test_foreign_prefix_ignored():
    # dotted tokens outside the registry's prefixes are file names / API
    # references, not stage citations
    assert _name_failures("see `bench.py` and `jax.lax.sort` for details.") == []


def test_duplicate_citation_reported_once():
    out = _name_failures("`rowgroup.bogus` here and `rowgroup.bogus` there.")
    assert len(out) == 1


def test_canonical_registry_importable():
    """The real registries back the checker: every name used by a stage()
    call site must be present (spot-check the pipeline's load-bearing
    ones)."""
    names = check_docs._canonical_names()
    assert {"consumer.fetch", "worker.shred", "rowgroup.launch",
            "rowgroup.assemble", "rowgroup.io_write", "encode.assemble",
            "parquet.writer.written.records",
            "parquet.writer.ack.lag.records"} <= names


def test_committed_docs_reconcile():
    """The repo's own docs + sweep artifact must pass the full checker."""
    assert check_docs.main() == 0


# --- cited-test + durability-claim reconciliation (durability PR) -----------

TESTS = {"test_crash_smoke_kill9_at_least_once", "test_page_checksums_roundtrip",
         "test_truncation_at_every_structural_boundary"}


def _test_failures(text: str, fname: str = "PARITY.md") -> list:
    docs = {f: "" for f in set(check_docs.KEY_DOCS) | set(check_docs.NAME_DOCS)}
    docs[fname] = text
    return check_docs.check_cited_tests(docs, test_names=TESTS)


def test_cited_test_must_exist():
    out = _test_failures("proven by `test_imaginary_quarantine_pass`.")
    assert len(out) == 1 and "test_imaginary_quarantine_pass" in out[0]


def test_cited_test_exact_and_prefix_pass():
    assert _test_failures(
        "see `test_crash_smoke_kill9_at_least_once` and "
        "`test_page_checksums_*`.") == []


def test_cited_test_bad_prefix_flagged():
    out = _test_failures("see `test_nonexistent_prefix_*`.")
    assert len(out) == 1


def _claim_failures(text: str) -> list:
    docs = {f: "" for f in set(check_docs.KEY_DOCS) | set(check_docs.NAME_DOCS)}
    docs["README.md"] = text
    return check_docs.check_durability_claims(docs, test_names=TESTS)


def test_quarantine_claim_without_test_fails():
    out = _claim_failures("invalid finals are quarantined, never deleted.")
    assert len(out) == 1 and "quarantine/verify claims" in out[0]


def test_quarantine_claim_with_matching_test_passes():
    assert _claim_failures(
        "invalid finals are quarantined, never deleted — proven by "
        "`test_crash_smoke_kill9_at_least_once`.") == []


def test_quarantine_claim_with_unrelated_test_still_fails():
    out = _claim_failures(
        "files are quarantined; see `test_page_checksums_roundtrip`... "
        "wait, that test checks nothing about quarantine — but "
        "`test_crash` does not exist either.")
    # page_checksums matches neither the durability-name pattern strictly?
    # it DOES contain no quarantine/verify/crash token... actually it has
    # none of quarantine|verif|crash|corrupt|torn -> not backing evidence
    assert len(out) == 1


def test_doc_without_durability_claims_exempt():
    assert _claim_failures("plain prose about rotation and acks.") == []


def test_verifier_claim_without_test_fails():
    """'structurally verified' guarantees are durability claims too, not
    just prose containing the word quarantine."""
    out = _claim_failures(
        "every published file is structurally verified at startup.")
    assert len(out) == 1


def test_neutral_verified_prose_not_a_claim():
    assert _claim_failures(
        "page checksums are verified by pyarrow's strict reader.") == []


# --- analyze-pass + name-completeness reconciliation (ISSUE 7) --------------

def _analyze_failures(readme: str) -> list:
    return check_docs.check_analyze_docs({"README.md": readme})


_SECTION = ("## Correctness tooling\n\n"
            "The `lock-discipline` pass and the `hot-imports` pass run "
            "via tools/analyze; allowlist entries are "
            "`kpw_tpu.ops.backend`.\n"
            "Also `canonical-names`, `fault-isolation` and "
            "`swallowed-exceptions` are lint passes.\n\n## Next\n")


def test_analyze_section_required():
    out = _analyze_failures("# readme with no tooling section\n")
    assert len(out) == 1 and "Correctness tooling" in out[0]


def test_bogus_pass_name_flagged():
    out = _analyze_failures(_SECTION.replace(
        "`lock-discipline` pass", "`bogus-pass` pass"))
    assert any("bogus-pass" in f for f in out)


def test_registered_pass_must_be_documented():
    out = _analyze_failures(_SECTION.replace("`fault-isolation`", "`x`"))
    assert any("fault-isolation" in f and "not documented" in f
               for f in out)


def test_stale_allowlist_citation_flagged():
    out = _analyze_failures(_SECTION.replace(
        "`kpw_tpu.ops.backend`", "`kpw_tpu.ops.nonexistent`"))
    assert any("nonexistent" in f for f in out)


def test_committed_analyze_section_passes():
    docs = {"README.md": open(os.path.join(
        HERE, os.pardir, "README.md")).read()}
    assert check_docs.check_analyze_docs(docs) == []


def test_name_completeness_flags_undocumented_registry_entry():
    docs = {f: "prose citing nothing" for f in check_docs.NAME_DOCS}
    out = check_docs.check_name_completeness(docs)
    # every canonical name missing -> every one reported
    assert len(out) == len(check_docs._canonical_names())


def test_name_completeness_passes_on_committed_docs():
    docs = {f: open(os.path.join(HERE, os.pardir, f)).read()
            for f in check_docs.NAME_DOCS}
    assert check_docs.check_name_completeness(docs) == []


# --- schedule-explorer / tsan claim reconciliation (ISSUE 13) ---------------

_SCENARIOS = {"a": {"seeds": [0, 1, 2], "refind_seeds": [1]},
              "b": {"seeds": [0, 1], "refind_seeds": [0]}}


def _schedx_failures(text, scenarios=_SCENARIOS, tsan=(200, 4)):
    return check_docs.check_schedx_claims({"README.md": text},
                                          scenarios=scenarios, tsan=tsan)


def test_schedx_matching_counts_pass():
    text = ("**5** committed seeds across **2** scenarios; "
            "**200** iterations per thread across **4** threads")
    assert _schedx_failures(text) == []


def test_schedx_drifted_seed_count_flagged():
    text = ("**9** committed seeds across **2** scenarios; "
            "**200** iterations per thread across **4** threads")
    out = _schedx_failures(text)
    assert len(out) == 1 and "seeds.json commits 5 / 2" in out[0]


def test_schedx_missing_anchor_flagged():
    out = _schedx_failures("no claims here at all")
    assert len(out) == 2  # both anchors missing


def test_schedx_scenario_without_refind_seeds_flagged():
    bad = {"a": {"seeds": [0], "refind_seeds": []}}
    text = ("**1** committed seeds across **1** scenarios; "
            "**200** iterations per thread across **4** threads")
    out = _schedx_failures(text, scenarios=bad)
    assert len(out) == 1 and "negative control" in out[0]


def test_tsan_drifted_iteration_count_flagged():
    text = ("**5** committed seeds across **2** scenarios; "
            "**999** iterations per thread across **4** threads")
    out = _schedx_failures(text)
    assert len(out) == 1 and "sanitize.sh commits 200 x 4" in out[0]


def test_schedx_committed_docs_reconcile():
    docs = {"README.md": open(os.path.join(
        HERE, os.pardir, "README.md")).read()}
    assert check_docs.check_schedx_claims(docs) == []
