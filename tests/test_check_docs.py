"""tools/check_docs.py cited-artifact-key reconciliation (VERDICT r5 ask
#2): a doc sentence claiming a key is recorded in the sweep artifact must
fail when the key does not exist there, stay silent for keys that do,
skip explicit pending-next-sweep promises, and never treat a code
identifier in neutral prose as a claim."""

import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "check_docs", os.path.join(HERE, os.pardir, "tools", "check_docs.py"))
check_docs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_docs)


RECORD = {"configs": {"config2": {"vs_dist": {"median": 2.0},
                                  "projected_system": {"median": {}}}}}


def _failures(text: str) -> list:
    docs = {f: "" for f in check_docs.KEY_DOCS}
    docs["PARITY.md"] = text
    return check_docs.check_cited_keys(RECORD, docs)


def test_flags_absent_cited_key():
    out = _failures("the win is recorded as `encode_side_vs_baseline` "
                    "in the artifact.")
    assert len(out) == 1 and "encode_side_vs_baseline" in out[0]


def test_present_key_passes():
    assert _failures("recorded as `vs_dist` in the artifact.") == []


def test_pending_claim_is_exempt():
    assert _failures("will be recorded as the `writer_route` block, "
                     "pending the next sweep.") == []


def test_neutral_code_identifier_not_a_claim():
    assert _failures("tune `encoder_threads` to size the pool.") == []


# --- cited stage/metric-name reconciliation (observability PR) -------------

NAMES = {"rowgroup.encode", "rowgroup.assemble",
         "parquet.writer.file.size", "parquet.writer.ack.lag.records"}


def _name_failures(text: str) -> list:
    docs = {f: "" for f in check_docs.NAME_DOCS}
    docs["PARITY.md"] = text
    return check_docs.check_cited_names(docs, names=NAMES)


def test_unknown_stage_name_flagged():
    out = _name_failures("host work hides in the `rowgroup.asemble` stage.")
    assert len(out) == 1 and "rowgroup.asemble" in out[0]


def test_unknown_metric_name_flagged():
    out = _name_failures("watch `parquet.writer.ack.lag.seconds` climb.")
    assert len(out) == 1 and "parquet.writer.ack.lag.seconds" in out[0]


def test_known_names_pass():
    assert _name_failures(
        "`rowgroup.encode` feeds `parquet.writer.file.size`; the "
        "`parquet.writer.ack.lag.records` gauge drains to 0.") == []


def test_foreign_prefix_ignored():
    # dotted tokens outside the registry's prefixes are file names / API
    # references, not stage citations
    assert _name_failures("see `bench.py` and `jax.lax.sort` for details.") == []


def test_duplicate_citation_reported_once():
    out = _name_failures("`rowgroup.bogus` here and `rowgroup.bogus` there.")
    assert len(out) == 1


def test_canonical_registry_importable():
    """The real registries back the checker: every name used by a stage()
    call site must be present (spot-check the pipeline's load-bearing
    ones)."""
    names = check_docs._canonical_names()
    assert {"consumer.fetch", "worker.shred", "rowgroup.launch",
            "rowgroup.assemble", "rowgroup.io_write", "encode.assemble",
            "parquet.writer.written.records",
            "parquet.writer.ack.lag.records"} <= names


def test_committed_docs_reconcile():
    """The repo's own docs + sweep artifact must pass the full checker."""
    assert check_docs.main() == 0
