"""Native wire-format shredder (kpw_proto_shred) vs the Python columnarizer.

The C++ fast path must produce a ColumnBatch identical to
ProtoColumnarizer.columnarize() over parsed messages — same values, same
def levels, same ByteColumn payloads — and must flag (not mis-decode) every
record the Python parser would reject, so the worker's fallback keeps exact
poison-pill semantics (reference KafkaProtoParquetWriter.java:271-275)."""

import numpy as np
import pytest

from kpw_tpu.core.bytecol import ByteColumn
from kpw_tpu.models.proto_bridge import ProtoColumnarizer, WireShredError

from proto_helpers import build_classes, _field, _F


def wide_message_class(syntax="proto2"):
    """Every wire-shreddable field type in one flat message."""
    label = _F.LABEL_OPTIONAL if syntax == "proto3" else _F.LABEL_REQUIRED
    fields = [
        _field("i64", 1, _F.TYPE_INT64, label),
        _field("u64", 2, _F.TYPE_UINT64),
        _field("s64", 3, _F.TYPE_SINT64),
        _field("f64", 4, _F.TYPE_FIXED64),
        _field("sf64", 5, _F.TYPE_SFIXED64),
        _field("i32", 6, _F.TYPE_INT32),
        _field("u32", 7, _F.TYPE_UINT32),
        _field("s32", 8, _F.TYPE_SINT32),
        _field("f32", 9, _F.TYPE_FIXED32),
        _field("sf32", 10, _F.TYPE_SFIXED32),
        _field("b", 11, _F.TYPE_BOOL),
        _field("d", 12, _F.TYPE_DOUBLE),
        _field("fl", 13, _F.TYPE_FLOAT),
        _field("s", 14, _F.TYPE_STRING),
        _field("by", 15, _F.TYPE_BYTES),
        # a high field number exercises the lookup table sizing
        _field("hi", 1234, _F.TYPE_INT64),
    ]
    return build_classes("wide", {"Wide": fields}, syntax=syntax)["Wide"]


def random_wide(cls, rng, i, syntax="proto2"):
    m = cls()
    m.i64 = int(rng.integers(-1 << 62, 1 << 62))
    if syntax == "proto3" or rng.random() < 0.8:  # proto2: leave some unset
        m.u64 = int(rng.integers(0, 1 << 63)) * 2 + int(rng.integers(0, 2))
        m.s64 = int(rng.integers(-1 << 62, 1 << 62))
        m.f64 = int(rng.integers(0, 1 << 63)) * 2 + int(rng.integers(0, 2))
        m.sf64 = int(rng.integers(-1 << 62, 1 << 62))
        m.i32 = int(rng.integers(-1 << 31, 1 << 31))
        m.u32 = int(rng.integers(0, 1 << 32))
        m.s32 = int(rng.integers(-1 << 31, 1 << 31))
        m.f32 = int(rng.integers(0, 1 << 32))
        m.sf32 = int(rng.integers(-1 << 31, 1 << 31))
        m.b = bool(rng.integers(0, 2))
        m.d = float(rng.normal())
        m.fl = float(np.float32(rng.normal()))
        m.s = f"héllo-{i}-" + "x" * int(rng.integers(0, 20))
        m.by = rng.bytes(int(rng.integers(0, 16)))
        m.hi = i
    return m


def assert_batches_equal(a, b):
    assert a.num_rows == b.num_rows
    for ca, cb in zip(a.chunks, b.chunks):
        assert ca.column.path == cb.column.path
        if isinstance(ca.values, np.ndarray):
            np.testing.assert_array_equal(ca.values, cb.values)
        else:
            va = list(ca.values) if isinstance(ca.values, ByteColumn) else ca.values
            vb = list(cb.values) if isinstance(cb.values, ByteColumn) else cb.values
            assert va == vb
        # None means "all present at max level" (required) / "no repetition"
        # — normalize so an all-NULL zeros array can never pass as equal
        n = a.num_rows
        for attr, full in (("def_levels", ca.column.max_def),
                           ("rep_levels", 0)):
            la, lb = getattr(ca, attr), getattr(cb, attr)
            la = la if la is not None else np.full(n, full, np.int32)
            lb = lb if lb is not None else np.full(n, full, np.int32)
            np.testing.assert_array_equal(la, lb)


@pytest.mark.parametrize("syntax", ["proto2", "proto3"])
def test_wire_shred_matches_python(syntax):
    cls = wide_message_class(syntax)
    colz = ProtoColumnarizer(cls)
    assert colz.wire_capable
    rng = np.random.default_rng(17)
    msgs = [random_wide(cls, rng, i, syntax) for i in range(500)]
    payloads = [m.SerializeToString() for m in msgs]
    got = colz.columnarize_payloads(payloads)
    want = colz.columnarize([cls.FromString(p) for p in payloads])
    assert_batches_equal(got, want)


def test_wire_shred_rejects_what_python_rejects():
    cls = wide_message_class("proto2")
    colz = ProtoColumnarizer(cls)
    ok = random_wide(cls, np.random.default_rng(0), 0).SerializeToString()

    # truncated payload (mid-field: drop the final varint's value byte)
    with pytest.raises(WireShredError) as ei:
        colz.columnarize_payloads([ok, ok[:-1], ok])
    assert ei.value.record_index == 1
    with pytest.raises(Exception):
        cls.FromString(ok[:-1])

    # missing proto2 required field (i64 is field 1): the shredder flags it
    # so the Python fallback decides — this runtime's FromString (upb)
    # tolerates it (IsInitialized()=False) and the fallback encodes defaults;
    # the Java reference parser would throw.  Either way the fallback, not
    # the fast path, owns the semantics.
    m = cls()
    m.u64 = 7
    bad = m.SerializePartialToString()
    with pytest.raises(WireShredError):
        colz.columnarize_payloads([bad])
    assert not cls.FromString(bad).IsInitialized()

    # garbage bytes
    with pytest.raises(WireShredError):
        colz.columnarize_payloads([b"\xff\xff\xff\xff"])


def test_wire_shred_proto3_utf8_and_defaults():
    cls = wide_message_class("proto3")
    colz = ProtoColumnarizer(cls)
    # invalid UTF-8 in a proto3 string field -> flagged (Python parser raises)
    m = cls()
    m.by = b"fine"
    good = m.SerializeToString()
    # field 14 (string "s"), wire type 2, bad continuation byte
    bad = good + bytes([14 << 3 | 2, 2, 0xC3, 0x28])
    with pytest.raises(WireShredError):
        colz.columnarize_payloads([bad])
    with pytest.raises(Exception):
        cls.FromString(bad)

    # absent proto3 fields decode as defaults, matching the Python path
    empty = cls().SerializeToString()
    got = colz.columnarize_payloads([empty, good])
    want = colz.columnarize([cls.FromString(empty), cls.FromString(good)])
    assert_batches_equal(got, want)


def test_wire_shred_unknown_fields_and_last_wins():
    cls = wide_message_class("proto2")
    colz = ProtoColumnarizer(cls)
    base = random_wide(cls, np.random.default_rng(3), 0).SerializeToString()
    # append an unknown varint field (#99: tag 792 -> 0xB8 0x06) and an
    # unknown length-delimited (#100: tag 802 -> 0xA2 0x06), then a second
    # occurrence of i64 (#1) — last value must win
    extra = bytes([0xB8, 0x06, 42]) + bytes([0xA2, 0x06, 3]) + b"abc"
    rewrite = extra + bytes([1 << 3 | 0, 9])  # i64 = 9
    payload = base + rewrite
    got = colz.columnarize_payloads([payload])
    want = colz.columnarize([cls.FromString(payload)])
    assert_batches_equal(got, want)
    i64_col = [c for c in got.chunks if c.column.path == ("i64",)][0]
    assert i64_col.values[0] == 9


def test_wire_plan_fallbacks():
    """Plan routing: flat scalar schemas take the lean flat decoder;
    nested schemas are wire-capable too, via the nested decoder
    (tests/test_nested_shred.py owns its semantics)."""
    from proto_helpers import nested_message_classes, sample_message_class

    nested = ProtoColumnarizer(nested_message_classes())
    assert nested.wire_capable and nested._wire is None
    flat = ProtoColumnarizer(sample_message_class())
    assert flat.wire_capable and flat._wire is not None
    enum_cls = build_classes("withenum", {"E": [
        _field("x", 1, _F.TYPE_INT64),
    ]})["E"]
    assert ProtoColumnarizer(enum_cls).wire_capable
